"""CI perf-regression gate: compare a bench artifact against the
committed ``bench_baseline.json``.

The hardware bench rounds kept going dark (r03-r05 died to a wedged
device tunnel), so the HOST-SIDE echo/CPU bench is the perf signal that
must never disappear: this gate runs it in CI (see the perf-gate job),
always uploads the artifact, and FAILS the build when the serving
stack's host-side overheads regress beyond tolerance vs the committed
baseline:

- ``req_per_sec`` (TTFT-path throughput through the real HTTP
  transport/batcher/scheduler stack) must stay above
  ``baseline * BENCH_GATE_RPS_FACTOR`` (default 0.40 — CI runners are
  noisy; the gate catches structural regressions, not jitter);
- ``value`` (p50 TTFT ms) must stay below
  ``baseline * BENCH_GATE_TTFT_FACTOR`` (default 2.5);
- the paged-KV microbench must still show copied-bytes SAVINGS:
  paged copied-KV-bytes per prefix hit strictly below the slot/copy
  model's, and the admission path must not blow up
  (``paged admission_ms <= slot_copy admission_ms *
  BENCH_GATE_KV_FACTOR``, default 3.0 — aliasing bookkeeping may cost
  a little CPU; it must never cost an order of magnitude);
- the host-mesh round (sharded block tables over tp=2 fake devices)
  must stay bookkeeping-cheap: mesh per-token dispatch latency
  ``<= single * BENCH_GATE_MESH_FACTOR`` (default 5.0 — loose-first;
  tighten as the trajectory stabilizes) and mesh copied-KV-bytes per
  prefix hit ``<= single + 64`` (sharding must never introduce KV
  copies; aliasing is placement-agnostic);
- the durable generation journal (resumable streams) must stay
  per-token cheap: ``journal_microbench.per_token_us <= baseline *
  BENCH_GATE_JOURNAL_FACTOR`` (default 5.0 — the journal append is a
  GIL-atomic list append; a regression here taxes EVERY stream);
- journal PERSISTENCE (the crash-durable WAL, journal_wal.py) must
  stay a bounded tax on top of that:
  ``journal_wal_microbench.per_token_us_wal <= baseline *
  BENCH_GATE_WAL_FACTOR`` (default 10.0, loose-first — a WAL append
  is a buffered write + flush; a blow-up means the frame/rotation
  path grew a stall or an fsync leaked into the default policy);
- deadline-aware serving must stay fast at saying no:
  ``shed_microbench.shed_p50_us <= baseline *
  BENCH_GATE_SHED_FACTOR`` (default 10.0, loose-first — the shed path
  is what overload leans on) and an abandoned stream's KV blocks must
  reclaim within ``baseline reclaim_ms * BENCH_GATE_RECLAIM_FACTOR``
  (default 10.0 — "within one chunk" is the contract; an order of
  magnitude past baseline means the abort hook stopped reaching the
  decode loop);
- pooled speculative decoding must keep earning its dispatches:
  pooled-spec decode tok/s must stay ``>= plain pooled decode *
  BENCH_GATE_SPEC_FACTOR`` (default 1.5 — the ROADMAP's "cheaper
  tokens" floor), ``tokens_per_dispatch`` must stay above the
  ABSOLUTE 1.5 floor (a verify that stops carrying multiple tokens
  has silently become plain decode, whatever the baseline said), and
  the echo n-gram acceptance must stay above zero;
- the disaggregated KV handoff must stay protocol-cheap: the
  cross-replica transfer path (pull + verify + install + aliased
  admission over real HTTP) must finish within
  ``local_prefill_ms_p50 * BENCH_GATE_TRANSFER_FACTOR`` (default
  10.0, loose-first — echo "prefill" is nearly free so the ratio
  prices pure protocol overhead; a blow-up here means the wire
  format or the pin/verify path grew a stall), every pull must take
  the fast path (``fallbacks == 0`` — a silent fallback would make
  the latency number a lie), and one pull's wire size must stay
  within ``baseline * 2`` (framing bloat: checksums + headers are
  bounded, payload is the payload);
- fleet tracing must stay cheap on both sides: the per-request hop
  stamp (request-id sanitize + ``X-Gofr-Hop`` mint + parse-back, paid
  on the router hot path) within ``baseline stamp_us *
  BENCH_GATE_TRACE_FACTOR`` and one ``/admin/fleet/trace`` timeline
  assembly within ``baseline assemble_us`` times the same factor
  (default 10.0, loose-first — stamping is string work that must stay
  microseconds; a blow-up means the correlation layer started taxing
  every routed request);
- the dispatch cost model (tpu/costmodel.py) must stay a dict lookup
  plus a handful of float ops on the dispatch path:
  ``costmodel_microbench.per_dispatch_us <= baseline *
  BENCH_GATE_COSTMODEL_FACTOR`` (default 10.0, loose-first — predict
  at begin + residual EMA at finish ride EVERY dispatch record), and
  the microbench's healthy loop must report ``anomalies == 0`` (an
  anomaly raised by steady-state traffic means the watchtower's
  false-positive floor broke);
- SLO + tenant metering (slo.py, telemetry.TenantLedger) must stay a
  bounded tax on the flight-record path:
  ``slo_microbench.per_request_us <= baseline *
  BENCH_GATE_SLO_FACTOR`` (default 10.0, loose-first — the measured
  loop deliberately churns the sketch's eviction path, its worst
  case), and the microbench's all-ok loop must report
  ``burn_alerts == 0`` (a burn alert raised by healthy traffic means
  the multi-window judge or its thresholds broke — the one regression
  that pages a human at 3am for nothing).

Usage::

    python tools/bench_gate.py BENCH.json [BASELINE.json]

Exit 0 = within tolerance, 1 = regression (each failure printed).
Refreshing the baseline is an explicit act: run the bench locally with
the same env as the CI job and commit the new ``bench_baseline.json``
next to the change that moved it — the file is the perf contract.
"""

from __future__ import annotations

import json
import os
import sys


def _num(d: dict, key: str):
    v = d.get(key)
    return v if isinstance(v, (int, float)) else None


def gate(bench: dict, baseline: dict) -> list[str]:
    failures: list[str] = []
    rps_factor = float(os.environ.get("BENCH_GATE_RPS_FACTOR", "0.40"))
    ttft_factor = float(os.environ.get("BENCH_GATE_TTFT_FACTOR", "2.5"))
    kv_factor = float(os.environ.get("BENCH_GATE_KV_FACTOR", "3.0"))
    mesh_factor = float(os.environ.get("BENCH_GATE_MESH_FACTOR", "5.0"))
    journal_factor = float(os.environ.get("BENCH_GATE_JOURNAL_FACTOR", "5.0"))
    wal_factor = float(os.environ.get("BENCH_GATE_WAL_FACTOR", "10.0"))
    shed_factor = float(os.environ.get("BENCH_GATE_SHED_FACTOR", "10.0"))
    reclaim_factor = float(os.environ.get("BENCH_GATE_RECLAIM_FACTOR", "10.0"))
    transfer_factor = float(
        os.environ.get("BENCH_GATE_TRANSFER_FACTOR", "10.0")
    )
    spec_factor = float(os.environ.get("BENCH_GATE_SPEC_FACTOR", "1.5"))
    trace_factor = float(os.environ.get("BENCH_GATE_TRACE_FACTOR", "10.0"))
    costmodel_factor = float(
        os.environ.get("BENCH_GATE_COSTMODEL_FACTOR", "10.0")
    )
    slo_factor = float(os.environ.get("BENCH_GATE_SLO_FACTOR", "10.0"))

    if bench.get("backend") != baseline.get("backend"):
        failures.append(
            f"backend mismatch: bench ran on {bench.get('backend')!r}, "
            f"baseline is {baseline.get('backend')!r} — not comparable"
        )
        return failures

    rps, base_rps = _num(bench, "req_per_sec"), _num(baseline, "req_per_sec")
    if base_rps:
        if rps is None:
            failures.append("req_per_sec missing from the bench artifact")
        elif rps < base_rps * rps_factor:
            failures.append(
                f"req/s regression: {rps} < {base_rps} * {rps_factor} "
                f"(= {base_rps * rps_factor:.2f})"
            )
    ttft, base_ttft = _num(bench, "value"), _num(baseline, "value")
    if base_ttft:
        if ttft is None:
            failures.append("p50 TTFT missing from the bench artifact")
        elif ttft > base_ttft * ttft_factor:
            failures.append(
                f"p50 TTFT regression: {ttft}ms > {base_ttft}ms * "
                f"{ttft_factor} (= {base_ttft * ttft_factor:.2f}ms)"
            )

    kv = bench.get("kv_microbench") or {}
    if baseline.get("kv_microbench"):
        paged, slot = kv.get("paged"), kv.get("slot_copy")
        if not (paged and slot):
            failures.append("kv_microbench missing from the bench artifact")
        else:
            if paged["copied_kv_bytes_per_hit"] >= slot["copied_kv_bytes_per_hit"]:
                failures.append(
                    "paged KV no longer saves copies: "
                    f"{paged['copied_kv_bytes_per_hit']} bytes/hit paged vs "
                    f"{slot['copied_kv_bytes_per_hit']} slot-copy"
                )
            if paged["admission_ms"] > slot["admission_ms"] * kv_factor:
                failures.append(
                    f"paged admission latency blew up: "
                    f"{paged['admission_ms']}ms > "
                    f"{slot['admission_ms']}ms * {kv_factor}"
                )

    mesh = bench.get("mesh_microbench") or {}
    if baseline.get("mesh_microbench"):
        single, meshed = mesh.get("single"), mesh.get("mesh")
        if not (single and meshed):
            failures.append("mesh_microbench missing from the bench artifact")
        else:
            if (
                meshed["per_token_dispatch_ms"]
                > single["per_token_dispatch_ms"] * mesh_factor
            ):
                failures.append(
                    "host-mesh per-token dispatch blew up: "
                    f"{meshed['per_token_dispatch_ms']}ms > "
                    f"{single['per_token_dispatch_ms']}ms * {mesh_factor}"
                )
            if (
                meshed["copied_kv_bytes_per_hit"]
                > single["copied_kv_bytes_per_hit"] + 64
            ):
                failures.append(
                    "sharded block tables introduced KV copies: "
                    f"{meshed['copied_kv_bytes_per_hit']} bytes/hit mesh vs "
                    f"{single['copied_kv_bytes_per_hit']} single (+64 slack)"
                )
    journal = bench.get("journal_microbench") or {}
    base_journal = baseline.get("journal_microbench") or {}
    if base_journal:
        per_token = _num(journal, "per_token_us")
        base_token = _num(base_journal, "per_token_us")
        if per_token is None:
            failures.append("journal_microbench missing from the bench artifact")
        elif base_token and per_token > base_token * journal_factor:
            failures.append(
                f"journal per-token overhead regression: {per_token}us > "
                f"{base_token}us * {journal_factor} "
                f"(= {base_token * journal_factor:.3f}us)"
            )
    wal = bench.get("journal_wal_microbench") or {}
    base_wal = baseline.get("journal_wal_microbench") or {}
    if base_wal:
        per_token = _num(wal, "per_token_us_wal")
        base_token = _num(base_wal, "per_token_us_wal")
        if per_token is None:
            failures.append(
                "journal_wal_microbench missing from the bench artifact"
            )
        elif base_token and per_token > base_token * wal_factor:
            failures.append(
                f"journal WAL per-token overhead regression: {per_token}us "
                f"> {base_token}us * {wal_factor} "
                f"(= {base_token * wal_factor:.2f}us)"
            )
    shed = bench.get("shed_microbench") or {}
    base_shed = baseline.get("shed_microbench") or {}
    if base_shed:
        p50, base_p50 = _num(shed, "shed_p50_us"), _num(base_shed, "shed_p50_us")
        if p50 is None:
            failures.append("shed_microbench missing from the bench artifact")
        elif base_p50 and p50 > base_p50 * shed_factor:
            failures.append(
                f"deadline shed latency regression: {p50}us > "
                f"{base_p50}us * {shed_factor} "
                f"(= {base_p50 * shed_factor:.1f}us)"
            )
        reclaim = _num(shed, "reclaim_ms")
        base_reclaim = _num(base_shed, "reclaim_ms")
        if base_reclaim:
            if reclaim is None:
                failures.append(
                    "abandoned-stream KV blocks never reclaimed "
                    "(reclaim_ms null in the bench artifact)"
                )
            elif reclaim > base_reclaim * reclaim_factor:
                failures.append(
                    f"abandoned-stream reclaim regression: {reclaim}ms > "
                    f"{base_reclaim}ms * {reclaim_factor} "
                    f"(= {base_reclaim * reclaim_factor:.1f}ms)"
                )
    spec = bench.get("spec_microbench") or {}
    base_spec = baseline.get("spec_microbench") or {}
    if base_spec:
        speedup = _num(spec, "speedup")
        tpd = _num(spec.get("spec") or {}, "tokens_per_dispatch")
        if speedup is None or tpd is None:
            failures.append("spec_microbench missing from the bench artifact")
        else:
            if speedup < spec_factor:
                failures.append(
                    f"pooled-spec speedup regression: {speedup}x < "
                    f"{spec_factor}x over plain pooled decode (the whole "
                    "point of speculation is cheaper tokens)"
                )
            # absolute floor, not baseline-relative: a verify dispatch
            # that stops carrying multiple tokens has silently become
            # plain decode whatever the baseline said
            if tpd <= 1.5:
                failures.append(
                    f"pooled-spec tokens_per_dispatch collapsed: {tpd} "
                    "<= 1.5 (speculation is no longer batching verifies)"
                )
            accept = _num(spec.get("spec") or {}, "accept_rate")
            if accept is not None and accept <= 0.0:
                failures.append(
                    "pooled-spec acceptance hit zero — the draft source "
                    "is proposing garbage (or the verify rejects "
                    "everything)"
                )
    transfer = bench.get("transfer_microbench") or {}
    base_transfer = baseline.get("transfer_microbench") or {}
    if base_transfer:
        t_p50 = _num(transfer, "transfer_ms_p50")
        local_p50 = _num(transfer, "local_prefill_ms_p50")
        if t_p50 is None or local_p50 is None:
            failures.append(
                "transfer_microbench missing from the bench artifact"
            )
        else:
            if local_p50 and t_p50 > local_p50 * transfer_factor:
                failures.append(
                    f"kv-transfer latency regression: {t_p50}ms p50 > "
                    f"local-prefill {local_p50}ms * {transfer_factor} "
                    f"(= {local_p50 * transfer_factor:.2f}ms)"
                )
            if transfer.get("fallbacks"):
                failures.append(
                    "kv-transfer pulls silently fell back to local "
                    f"prefill ({transfer['fallbacks']}/"
                    f"{transfer.get('rounds')}) — the transfer latency "
                    "number is not measuring the transfer path"
                )
            wire = _num(transfer, "wire_bytes_per_pull")
            base_wire = _num(base_transfer, "wire_bytes_per_pull")
            # wire bytes scale with the prompt (BENCH_TRANSFER_PROMPT):
            # only comparable when this run used the baseline's size
            same_prompt = (
                _num(transfer, "prompt_tokens")
                == _num(base_transfer, "prompt_tokens")
            )
            if base_wire and same_prompt:
                if wire is None:
                    failures.append(
                        "wire_bytes_per_pull missing from the bench artifact"
                    )
                elif wire > base_wire * 2:
                    failures.append(
                        f"kv wire format bloated: {wire} bytes/pull > "
                        f"baseline {base_wire} * 2"
                    )
    trace = bench.get("trace_microbench") or {}
    base_trace = baseline.get("trace_microbench") or {}
    if base_trace:
        for key, what in (
            ("stamp_us", "per-request hop stamp"),
            ("assemble_us", "trace assembly"),
        ):
            got, base = _num(trace, key), _num(base_trace, key)
            if got is None:
                failures.append(
                    f"trace_microbench.{key} missing from the bench artifact"
                )
            elif base and got > base * trace_factor:
                failures.append(
                    f"fleet-tracing {what} regression: {got}us > "
                    f"{base}us * {trace_factor} "
                    f"(= {base * trace_factor:.2f}us)"
                )
    costmodel = bench.get("costmodel_microbench") or {}
    base_costmodel = baseline.get("costmodel_microbench") or {}
    if base_costmodel:
        got = _num(costmodel, "per_dispatch_us")
        base = _num(base_costmodel, "per_dispatch_us")
        if got is None:
            failures.append(
                "costmodel_microbench missing from the bench artifact"
            )
        else:
            if base and got > base * costmodel_factor:
                failures.append(
                    f"cost-model per-dispatch overhead regression: {got}us "
                    f"> {base}us * {costmodel_factor} "
                    f"(= {base * costmodel_factor:.2f}us)"
                )
            anomalies = _num(costmodel, "anomalies")
            if anomalies:
                failures.append(
                    f"cost-model microbench raised {anomalies} anomalies on "
                    "a healthy steady-state loop — the false-positive floor "
                    "(COSTMODEL_MIN_ANOMALY_MS) is broken"
                )
    slo = bench.get("slo_microbench") or {}
    base_slo = baseline.get("slo_microbench") or {}
    if base_slo:
        got = _num(slo, "per_request_us")
        base = _num(base_slo, "per_request_us")
        if got is None:
            failures.append("slo_microbench missing from the bench artifact")
        else:
            if base and got > base * slo_factor:
                failures.append(
                    f"tenant-metering per-request overhead regression: "
                    f"{got}us > {base}us * {slo_factor} "
                    f"(= {base * slo_factor:.2f}us)"
                )
            burn_alerts = _num(slo, "burn_alerts")
            if burn_alerts:
                failures.append(
                    f"SLO microbench raised {burn_alerts} burn alerts on an "
                    "all-ok loop — a healthy run must never page "
                    "(slo.py burn thresholds or judge logic are broken)"
                )
    return failures


def main(argv: list[str]) -> int:
    if len(argv) < 2:
        print(__doc__)
        return 2
    bench_path = argv[1]
    base_path = argv[2] if len(argv) > 2 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_baseline.json",
    )
    with open(bench_path) as f:
        bench = json.load(f)
    with open(base_path) as f:
        baseline = json.load(f)
    failures = gate(bench, baseline)
    print(
        f"bench gate: backend={bench.get('backend')} "
        f"req/s={bench.get('req_per_sec')} (baseline "
        f"{baseline.get('req_per_sec')}) p50={bench.get('value')}ms "
        f"(baseline {baseline.get('value')}ms) "
        f"kv={json.dumps(bench.get('kv_microbench'))}"
    )
    if failures:
        for f_ in failures:
            print(f"FAIL: {f_}")
        return 1
    print("bench gate: OK (within tolerance of bench_baseline.json)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
