"""Pretty-print a postmortem black-box bundle (gofr_tpu/postmortem.py).

    python tools/postmortem_view.py                      # newest bundle in ./postmortems
    python tools/postmortem_view.py hw/r05               # newest bundle in a dir
    python tools/postmortem_view.py postmortem-...json   # a specific bundle
    python tools/postmortem_view.py ... --json           # machine-readable digest

Renders the operator's triage view: the header (reason, time, engine
state + last transitions), versions and config fingerprint, the
dispatch-timeline tail (the wedged dispatch shows `running`), the
watchdog's stalled entries, the in-flight + recent flight records, the
timebase coverage, and a per-thread STACK DIGEST (threads grouped by
identical stacks — the wedged thread's unique stack stands out instead
of drowning in 60 idle pool threads).

Exit codes: 0 rendered, 1 no bundle found, 2 bundle unparseable (CI's
postmortem smoke gates on this).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Optional


def find_bundle(target: str) -> Optional[str]:
    """Resolve a path argument: a bundle file as-is, a directory to its
    newest bundle."""
    if os.path.isfile(target):
        return target
    if os.path.isdir(target):
        bundles = sorted(
            n for n in os.listdir(target)
            if n.startswith("postmortem-") and n.endswith(".json")
        )
        if bundles:
            return os.path.join(target, bundles[-1])
    return None


def load_bundle(path: str) -> dict[str, Any]:
    """Parse + structurally validate a bundle; raises ValueError when it
    is not a postmortem bundle (CI smoke gates on this)."""
    with open(path, encoding="utf-8") as fh:
        bundle = json.load(fh)
    if not isinstance(bundle, dict) or not str(
        bundle.get("schema", "")
    ).startswith("gofr-postmortem/"):
        raise ValueError(f"{path}: not a gofr postmortem bundle")
    for field in ("reason", "ts", "versions", "config", "threads"):
        if field not in bundle:
            raise ValueError(f"{path}: bundle missing required field {field!r}")
    return bundle


def stack_digest(threads: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Group threads by identical stack; most-unusual (smallest group)
    first — the wedged thread is the one that looks like nothing else."""
    groups: dict[str, list[str]] = {}
    for t in threads:
        groups.setdefault(t.get("stack", ""), []).append(t.get("name", "?"))
    out = [
        {"threads": sorted(names), "stack": stack}
        for stack, names in groups.items()
    ]
    out.sort(key=lambda g: (len(g["threads"]), g["threads"]))
    return out


def digest(bundle: dict[str, Any]) -> dict[str, Any]:
    """The machine-readable summary (--json)."""
    engine = bundle.get("engine") or {}
    state = (engine.get("engine") or {}).get("state")
    dispatches = bundle.get("dispatches") or []
    running = [d for d in dispatches if d.get("status") == "running"]
    watchdog = engine.get("watchdog") or {}
    stalled = [w for w in watchdog.get("watching", []) if w.get("stalled")]
    return {
        "reason": bundle.get("reason"),
        "detail": bundle.get("detail"),
        "iso": bundle.get("iso"),
        "engine_state": state,
        "versions": bundle.get("versions"),
        "config_fingerprint": (bundle.get("config") or {}).get("fingerprint"),
        "dispatches": len(dispatches),
        "dispatches_running": [d.get("dispatch_id") for d in running],
        "stalled_watches": stalled,
        "requests": len(bundle.get("requests") or []),
        "requests_in_flight": len(bundle.get("requests_in_flight") or []),
        "timebase_snapshots": len(bundle.get("timebase") or []),
        "threads": len(bundle.get("threads") or []),
        "unique_stacks": len(stack_digest(bundle.get("threads") or [])),
    }


def _fmt_ts(ts: Any) -> str:
    try:
        return time.strftime("%H:%M:%S", time.gmtime(float(ts)))
    except (TypeError, ValueError):
        return "?"


def render(bundle: dict[str, Any], out=sys.stdout) -> None:
    p = lambda line="": print(line, file=out)  # noqa: E731
    d = digest(bundle)
    p("=" * 72)
    p(f"POSTMORTEM  reason={d['reason']}  at {bundle.get('iso')}")
    if d["detail"]:
        p(f"  detail: {d['detail']}")
    p(f"  versions: {d['versions']}  config fingerprint: "
      f"{d['config_fingerprint']}")
    engine = bundle.get("engine") or {}
    machine = engine.get("engine") or {}
    p(f"  engine state: {machine.get('state')}"
      + (f" ({machine.get('detail')})" if machine.get("detail") else ""))
    history = machine.get("history") or []
    for h in history[-5:]:
        p(f"    {_fmt_ts(h.get('ts'))}  -> {h.get('state')}"
          + (f"  {h.get('detail')}" if h.get("detail") else ""))

    watchdog = engine.get("watchdog") or {}
    if d["stalled_watches"]:
        p("-" * 72)
        p("STALLED DISPATCHES (watchdog):")
        for w in d["stalled_watches"]:
            p(f"  dispatch {w.get('dispatch_id')}  kind={w.get('kind')}  "
              f"elapsed={w.get('elapsed_s')}s")
    elif watchdog.get("stalls"):
        p(f"  past stalls: {watchdog['stalls']}")

    dispatches = bundle.get("dispatches") or []
    if dispatches:
        p("-" * 72)
        p(f"DISPATCH TAIL (newest of {len(dispatches)}):")
        for rec in dispatches[:10]:
            dur = rec.get("duration_s")
            p(f"  #{rec.get('dispatch_id')}  {rec.get('kind'):<15s} "
              f"{rec.get('status'):<8s} "
              f"dur={f'{dur:.4f}s' if dur is not None else 'IN FLIGHT'}")

    in_flight = bundle.get("requests_in_flight") or []
    if in_flight:
        p("-" * 72)
        p(f"REQUESTS IN FLIGHT ({len(in_flight)}):")
        for rec in in_flight[:10]:
            p(f"  {rec.get('trace_id')}  {rec.get('model')}  "
              f"{rec.get('endpoint')}  dispatch_ids={rec.get('dispatch_ids')}")
    recent = bundle.get("requests") or []
    if recent:
        p(f"recent completed requests: {len(recent)} "
          f"(errored: {sum(1 for r in recent if r.get('status') != 'ok')})")

    snaps = bundle.get("timebase") or []
    p("-" * 72)
    if snaps:
        p(f"TIMEBASE: {len(snaps)} snapshots, "
          f"{_fmt_ts(snaps[0].get('ts'))} .. {_fmt_ts(snaps[-1].get('ts'))}")
    else:
        p("TIMEBASE: no snapshots (sampler off or bundle written at boot)")

    p("-" * 72)
    groups = stack_digest(bundle.get("threads") or [])
    p(f"THREAD STACK DIGEST ({d['threads']} threads, "
      f"{len(groups)} unique stacks; most unusual first):")
    for g in groups:
        p(f"  [{', '.join(g['threads'][:6])}"
          + (f" +{len(g['threads']) - 6} more" if len(g["threads"]) > 6 else "")
          + "]")
        tail = [ln for ln in g["stack"].splitlines() if ln.strip()][-6:]
        for line in tail:
            p(f"    {line.rstrip()}")
        p()
    p("=" * 72)


def main(argv: list[str]) -> int:
    args = [a for a in argv if not a.startswith("--")]
    as_json = "--json" in argv
    target = args[0] if args else "./postmortems"
    path = find_bundle(target)
    if path is None:
        print(f"no postmortem bundle at {target}", file=sys.stderr)
        return 1
    try:
        bundle = load_bundle(path)
    except (ValueError, OSError, json.JSONDecodeError) as exc:
        print(f"unparseable bundle: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps({"path": path, **digest(bundle)}, indent=1))
    else:
        print(f"bundle: {path}")
        render(bundle)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
