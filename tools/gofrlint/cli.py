"""lint_paths + the command line. Orchestration order:

1. per-file pass (GFL001–006) — unchanged v1 semantics,
2. whole-program pass over the same files: project model → fixpoint
   summaries → interprocedural GFL004 + static lock-order graph,
3. contract registries (GFL007/008/009) against the repo artifacts
   (tests/test_metric_naming.py, config.py DECLARED_KEYS, README.md).

Flags on top of v1's ``--format``: ``--ledger`` (print the per-rule
suppression counts), ``--ledger-check FILE`` (fail if any count grew
past the committed baseline — the ledger only shrinks), and
``--emit-lock-graph FILE`` (write the static lock-order graph JSON for
tools/lockgraph_check.py)."""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Optional

from .base import Violation, iter_files
from .contracts import contract_violations
from .interproc import WholeProgram
from .local import FileLinter
from .model import Project


def _detect_root(paths: list[str], files: list[Path]) -> Path:
    candidates = [Path(p).resolve() for p in paths] or \
        [f.resolve() for f in files]
    try:
        root = Path(os.path.commonpath([str(c) for c in candidates]))
    except ValueError:  # mixed drives / empty
        return Path.cwd()
    if root.is_file():
        root = root.parent
    if root.name == "gofr_tpu" and (root / "__init__.py").exists():
        root = root.parent  # scanning the package dir alone: artifacts
        # (README, tests/) live beside it
    return root


class LintRun:
    """One full analysis: violations, suppression ledger, lock graph."""

    def __init__(self, paths: list[str]):
        self.files = iter_files(paths)
        self.root = _detect_root(paths, self.files)
        self.violations: list[Violation] = []
        self.ledger: dict[str, int] = {}
        sources: dict[str, str] = {}      # model rel -> source
        display: dict[str, str] = {}      # model rel -> output path
        for path in self.files:
            try:
                source = path.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError):
                continue
            rel = str(path)
            linter = FileLinter(path, rel, source)
            self.violations.extend(linter.run())
            for rule, count in linter.directives.disable_counts().items():
                self.ledger[rule] = self.ledger.get(rule, 0) + count
            try:
                model_rel = path.resolve().relative_to(self.root).as_posix()
            except ValueError:
                model_rel = path.as_posix()
            sources[model_rel] = source
            display[model_rel] = rel
        self.project = Project.from_sources(sources)
        whole = WholeProgram(self.project)
        self.lock_graph = whole.lock_graph()
        seen = {(v.rule, v.path, v.line) for v in self.violations}
        for v in whole.violations() + contract_violations(
            self.project, self.root
        ):
            v.path = display.get(v.path, v.path)
            if (v.rule, v.path, v.line) not in seen:
                seen.add((v.rule, v.path, v.line))
                self.violations.append(v)


def lint_paths(paths: list[str]) -> tuple[list[Violation], int]:
    run = LintRun(paths)
    return run.violations, len(run.files)


def check_ledger(current: dict[str, int], baseline_path: str) -> list[str]:
    """Growth errors vs the committed ledger (empty = ok). A rule
    missing from the baseline counts as baseline 0."""
    try:
        with open(baseline_path, "r", encoding="utf-8") as f:
            baseline = json.load(f).get("counts", {})
    except (OSError, ValueError) as exc:
        return [f"ledger baseline {baseline_path} unreadable: {exc}"]
    errors = []
    for rule in sorted(set(current) | set(baseline)):
        have, allowed = current.get(rule, 0), baseline.get(rule, 0)
        if have > allowed:
            errors.append(
                f"suppression ledger grew: {rule} has {have} "
                f"disable(s), baseline allows {allowed} — fix the "
                "violation instead of suppressing it (the ledger only "
                "shrinks; if a suppression was genuinely removed "
                "elsewhere, re-emit the baseline with --ledger)"
            )
    return errors


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="gofrlint",
        description="project-invariant linter for the gofr_tpu tree",
    )
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        dest="fmt", help="output format",
    )
    parser.add_argument(
        "--ledger", action="store_true",
        help="print the per-rule suppression-ledger counts as JSON "
             "and exit (0 always — this is the baseline emitter)",
    )
    parser.add_argument(
        "--ledger-check", metavar="FILE", default=None,
        help="fail (exit 1) if any per-rule suppression count exceeds "
             "the committed baseline FILE",
    )
    parser.add_argument(
        "--emit-lock-graph", metavar="FILE", default=None,
        help="write the static lock-order graph JSON to FILE",
    )
    args = parser.parse_args(argv)
    run = LintRun(args.paths)
    if args.ledger:
        print(json.dumps(
            {"version": 1, "counts": dict(sorted(run.ledger.items()))},
            indent=2,
        ))
        return 0
    if args.emit_lock_graph:
        with open(args.emit_lock_graph, "w", encoding="utf-8") as f:
            json.dump(run.lock_graph, f, indent=2)
            f.write("\n")
    violations = run.violations
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    ledger_errors = (
        check_ledger(run.ledger, args.ledger_check)
        if args.ledger_check else []
    )
    if args.fmt == "json":
        counts: dict[str, int] = {}
        for v in violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        print(json.dumps({
            "version": 1,
            "files_scanned": len(run.files),
            "violations": [v.as_dict() for v in violations],
            "counts_by_rule": counts,
            "suppressions": dict(sorted(run.ledger.items())),
            "ledger_errors": ledger_errors,
        }, indent=2))
    else:
        for v in violations:
            print(f"{v.path}:{v.line}:{v.col + 1}: {v.rule} {v.message}")
        for err in ledger_errors:
            print(f"gofrlint: {err}")
        print(
            f"gofrlint: {len(violations)} violation(s) in "
            f"{len(run.files)} file(s)"
            if violations else f"gofrlint: clean ({len(run.files)} files)"
        )
    return 1 if (violations or ledger_errors) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
