"""gofrlint v2 — whole-program project-invariant linter.

ruff holds the style/complexity line; gofrlint holds the PROJECT
invariants generic linters cannot know. v1 was per-file; v2 adds a
whole-program pass (symbol table + conservative call graph) so the
rules see through attribute dispatch — the PR 14 class of hazard (a
WAL fsync reached while the per-token journal lock is held) — plus
cross-module contract registries for metrics, config keys, and the
admin surface.

Rules
-----
GFL001  no raw ``os.environ``/``os.getenv`` READS outside config.py
        (package code; writes and entry-point scripts exempt).
GFL002  ``time.time()`` only at sites annotated
        ``# gofrlint: wall-clock — <why>``.
GFL003  every ``threading.Thread`` named and daemon-or-joined.
GFL004  no blocking call while holding a lock — per-file AND
        interprocedurally: per-function {may-block, acquires}
        summaries to a fixpoint over the call graph.
GFL005  metric naming convention, statically.
GFL006  no swallowed exceptions in engine paths.
GFL007  metric contract: one registration home per family, help and
        labels consistent at every touch point, a row in
        tests/test_metric_naming.py.
GFL008  config-key provenance: reads declared in config.py
        DECLARED_KEYS; declared keys read somewhere (inert knobs).
GFL009  admin-surface parity: /admin/* registrations ↔ README table.

Suppression: ``# gofrlint: disable=GFLnnn — <reason>`` on (or on a
comment line directly above) the reported line. Suppressions are the
violation LEDGER (``--ledger``), ratcheted by ``--ledger-check`` —
the committed ledger only shrinks.

The static lock-order graph (``--emit-lock-graph``) shares node ids
with the runtime sanitizer's observed graph (lock CREATION SITES,
``path:lineno``); tools/lockgraph_check.py fails on cycles in the
union. See docs/advanced-guide/static-analysis.md."""

from .base import (  # noqa: F401
    _COUNTER_SUFFIXES,
    _GAUGE_ALLOWLIST,
    _GAUGE_SUFFIXES,
    _HISTOGRAM_SUFFIXES,
    RULES,
    Violation,
    iter_files,
)
from .cli import LintRun, check_ledger, lint_paths, main  # noqa: F401
from .contracts import contract_violations  # noqa: F401
from .interproc import WholeProgram  # noqa: F401
from .local import FileLinter  # noqa: F401
from .model import Project  # noqa: F401
