"""Contract registries — the cross-module drift rules.

GFL007  metric contract: a family has ONE registration home carrying
        its help text (every other touch point is a lookup), all
        literal label declarations agree, the kind never flips, and
        the family has a row in tests/test_metric_naming.py.
GFL008  config-key provenance: every key read through a config
        accessor is declared in config.py's DECLARED_KEYS registry,
        and every declared key is read somewhere (inert-knob
        detection — the SPEC_FAKE_ACCEPT class).
GFL009  admin-surface parity: every /admin/* route registered in code
        appears in the README route table and vice versa.

Each rule deactivates itself when its repo artifact is absent from
the scanned tree (no config.py → no GFL008), so linting a snippet
directory stays meaningful."""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import Violation, src_of
from .model import Project

_UPPER_KEY_RE = re.compile(r"^[A-Z][A-Z0-9_]{2,}$")
_CONFIGISH_RE = re.compile(r"(\b|_)(config|cfg)\b", re.IGNORECASE)

# environment keys the process reads but does not own — platform
# surface, not framework config, so they need no DECLARED_KEYS entry
_EXTERNAL_KEYS = {
    "HOME", "PATH", "PWD", "TMPDIR", "XDG_CACHE_HOME", "JAX_PLATFORMS",
}

# regexes for the auxiliary read scan over tests/ (read-evidence only:
# a test SETTING a key does not make the knob live)
_AUX_READ_RES = (
    re.compile(
        r"(?:get_env|env_flag|get_or_default|getenv|environ\.get)\(\s*"
        r"['\"]([A-Z][A-Z0-9_]{2,})['\"]"
    ),
    re.compile(r"environ\[\s*['\"]([A-Z][A-Z0-9_]{2,})['\"]\]"),
)

_ROUTE_METHODS = {"add", "get", "post", "put", "delete", "add_route", "route"}
_README_ROUTE_RE = re.compile(r"`(/admin/[^`\s]*)`")


def _norm_route(path: str) -> str:
    return re.sub(r"<([^>]+)>", r"{\1}", path.rstrip("/")) or "/"


def _route_key(path: str) -> str:
    # parity is about the SHAPE of the surface, not parameter spelling:
    # code's /admin/kv/{hash} and the README's /admin/kv/{prompt_hash}
    # are the same route
    return re.sub(r"\{[^}]*\}", "{}", _norm_route(path))


def _suppressed(project: Project, rel: str, rule: str, line: int) -> bool:
    mod = project.modules.get(rel)
    return bool(mod and mod.directives.suppressed(rule, line))


# -- GFL007: metric contract --------------------------------------------------

class _MetricSite:
    __slots__ = ("name", "kind", "help", "has_help", "labels", "rel", "line")

    def __init__(self, name, kind, help_, has_help, labels, rel, line):
        self.name = name
        self.kind = kind
        self.help = help_        # str | None (None = dynamic/absent)
        self.has_help = has_help
        self.labels = labels     # sorted tuple | None (None = dynamic/absent)
        self.rel = rel
        self.line = line


def _metric_sites(project: Project) -> dict[str, list[_MetricSite]]:
    families: dict[str, list[_MetricSite]] = {}
    for rel, mod in project.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and
                    fn.attr in ("counter", "gauge", "histogram")):
                continue
            if not node.args or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if not name.startswith("gofr_"):
                continue
            help_, has_help = None, False
            if len(node.args) >= 2:
                has_help = True
                if isinstance(node.args[1], ast.Constant) and \
                        isinstance(node.args[1].value, str):
                    help_ = node.args[1].value
            labels: Optional[tuple] = None
            for kw in node.keywords:
                if kw.arg in ("help_", "help"):
                    has_help = True
                    if isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, str):
                        help_ = kw.value.value
                elif kw.arg == "labels" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    elts = kw.value.elts
                    if all(isinstance(e, ast.Constant) and
                           isinstance(e.value, str) for e in elts):
                        labels = tuple(sorted(e.value for e in elts))
            families.setdefault(name, []).append(_MetricSite(
                name, fn.attr, help_, has_help, labels, rel, node.lineno,
            ))
    return families


def check_metrics(project: Project, root: Path) -> list[Violation]:
    out: list[Violation] = []
    naming_test = root / "tests" / "test_metric_naming.py"
    naming_text = ""
    if naming_test.is_file():
        try:
            naming_text = naming_test.read_text(encoding="utf-8")
        except OSError:
            pass
    for name, sites in sorted(_metric_sites(project).items()):
        sites.sort(key=lambda s: (s.rel, s.line))

        def flag(site, message, name=name):
            if not _suppressed(project, site.rel, "GFL007", site.line):
                out.append(Violation(
                    "GFL007", site.rel, site.line, 0,
                    f"metric {name!r}: {message}",
                ))

        first = sites[0]
        for site in sites[1:]:
            if site.kind != first.kind:
                flag(site, f"registered as a {first.kind} at "
                           f"{first.rel}:{first.line} but as a "
                           f"{site.kind} here — the registry keeps the "
                           "first kind and this site reads the wrong "
                           "shape")
        helped = [s for s in sites if s.has_help and s.help]
        for site in helped[1:]:
            if site.help != helped[0].help:
                flag(site, "help text diverges from the registration "
                           f"home at {helped[0].rel}:{helped[0].line} "
                           "— registration order decides which string "
                           "serves, silently")
            else:
                flag(site, "duplicate registration home (same help "
                           f"declared at {helped[0].rel}:"
                           f"{helped[0].line}) — keep ONE home and "
                           "make other touch points lookups, or the "
                           "copies drift apart")
        labeled = [s for s in sites if s.labels is not None]
        for site in labeled[1:]:
            if site.labels != labeled[0].labels:
                flag(site, f"labels {site.labels} disagree with "
                           f"{labeled[0].labels} declared at "
                           f"{labeled[0].rel}:{labeled[0].line}")
        if naming_text and f'"{name}"' not in naming_text:
            home = helped[0] if helped else first
            flag(home, "no row in tests/test_metric_naming.py — add "
                       "the family to the known-registrations sweep so "
                       "a refactor cannot silently drop it")
    return out


# -- GFL008: config-key provenance --------------------------------------------

def _declared_keys(mod) -> Optional[dict[str, int]]:
    for stmt in mod.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "DECLARED_KEYS"
                   for t in targets):
            continue
        if not isinstance(value, ast.Dict):
            return {}
        out = {}
        for key in value.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out[key.value] = key.lineno
        return out
    return None


def _is_read_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in ("get_env", "env_flag")
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr in ("get_env", "env_flag"):
        return True
    if fn.attr == "getenv":
        return isinstance(fn.value, ast.Name) and fn.value.id == "os"
    if fn.attr in ("get", "get_or_default"):
        receiver = src_of(fn.value)
        if receiver == "os.environ":
            return fn.attr == "get"
        return bool(_CONFIGISH_RE.search(receiver))
    return False


def _key_reads(project: Project) -> dict[str, list[tuple[str, int]]]:
    """key -> [(rel, line), ...] across every scanned module, including
    one-hop wrappers (a function whose first parameter feeds a config
    accessor — the fleet ``_f``/``_i`` idiom)."""
    reads: dict[str, list[tuple[str, int]]] = {}

    def record(key: str, rel: str, line: int) -> None:
        if _UPPER_KEY_RE.match(key):
            reads.setdefault(key, []).append((rel, line))

    for rel, mod in project.modules.items():
        wrappers: set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = node.args.args
            if not params:
                continue
            first = params[0].arg
            if first == "self" and len(params) > 1:
                first = params[1].arg
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _is_read_call(sub) and \
                        sub.args and isinstance(sub.args[0], ast.Name) and \
                        sub.args[0].id == first:
                    wrappers.add(node.name)
                    break
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            arg0 = node.args[0] if node.args else None
            literal = (
                arg0.value
                if isinstance(arg0, ast.Constant) and
                isinstance(arg0.value, str) else None
            )
            if literal is None:
                continue
            if _is_read_call(node):
                record(literal, rel, node.lineno)
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in wrappers:
                record(literal, rel, node.lineno)
            # os.environ["KEY"] reads are Subscripts, handled below
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Subscript) and \
                    src_of(node.value) == "os.environ" and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                record(node.slice.value, rel, node.lineno)
    return reads


def _aux_reads(root: Path) -> set[str]:
    """Read-evidence from the tests tree (e.g. GOFR_SANITIZE_REPORT is
    consumed only by tests/conftest.py) — enough to prove a declared
    knob live, never enough to excuse an undeclared package read."""
    found: set[str] = set()
    tests_dir = root / "tests"
    if not tests_dir.is_dir():
        return found
    for path in sorted(tests_dir.rglob("*.py")):
        try:
            text = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        for pattern in _AUX_READ_RES:
            found.update(pattern.findall(text))
    return found


def check_config_keys(project: Project, root: Path) -> list[Violation]:
    config_mod = None
    for rel, mod in project.modules.items():
        parts = Path(rel).parts
        if Path(rel).name == "config.py" and "gofr_tpu" in parts:
            config_mod = mod
            break
    if config_mod is None:
        return []
    out: list[Violation] = []
    declared = _declared_keys(config_mod)
    if declared is None:
        return [Violation(
            "GFL008", config_mod.rel, 1, 0,
            "config.py declares no DECLARED_KEYS registry — the "
            "config surface has no provenance anchor",
        )]
    reads = _key_reads(project)
    # provenance is a PACKAGE contract: a read inside the gofr_tpu
    # package must trace to DECLARED_KEYS; harness knobs (bench.py,
    # tools/) are out of the package's config surface, though their
    # reads still prove a declared key live below
    pkg_prefix = str(Path(config_mod.rel).parent).replace("\\", "/") + "/"
    for key in sorted(reads):
        if key in declared or key in _EXTERNAL_KEYS:
            continue
        pkg_sites = sorted(
            s for s in reads[key] if s[0].startswith(pkg_prefix)
        )
        if not pkg_sites:
            continue
        rel, line = pkg_sites[0]
        if _suppressed(project, rel, "GFL008", line):
            continue
        out.append(Violation(
            "GFL008", rel, line, 0,
            f"config key {key!r} is read here but not declared in "
            "config.py DECLARED_KEYS — declare and document it (or it "
            "is invisible to operators)",
        ))
    aux = _aux_reads(root)
    for key, line in sorted(declared.items()):
        if key in reads or key in aux:
            continue
        if _suppressed(project, config_mod.rel, "GFL008", line):
            continue
        out.append(Violation(
            "GFL008", config_mod.rel, line, 0,
            f"declared config key {key!r} is never read in the scanned "
            "tree — an inert knob (the SPEC_FAKE_ACCEPT class): wire "
            "it or delete the declaration",
        ))
    return out


# -- GFL009: admin-surface parity ---------------------------------------------

def _code_routes(project: Project) -> dict[str, tuple[str, int]]:
    routes: dict[str, tuple[str, int]] = {}
    for rel, mod in project.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and
                    fn.attr in _ROUTE_METHODS):
                continue
            for arg in node.args[:3]:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("/admin/"):
                    routes.setdefault(
                        _norm_route(arg.value), (rel, node.lineno)
                    )
                    break
    return routes


def check_admin_routes(project: Project, root: Path) -> list[Violation]:
    readme = root / "README.md"
    if not readme.is_file():
        return []
    try:
        text = readme.read_text(encoding="utf-8")
    except OSError:
        return []
    routes = _code_routes(project)
    if not routes:
        return []  # partial scan with no registration sites in view
    documented: set[str] = set()
    claimed: dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for raw in _README_ROUTE_RE.findall(line):
            path = _norm_route(raw)
            documented.add(_route_key(path))
            if line.lstrip().startswith("|"):
                claimed.setdefault(path, lineno)
    code_keys = {_route_key(p) for p in routes}
    out: list[Violation] = []
    for path, (rel, line) in sorted(routes.items()):
        if _route_key(path) in documented:
            continue
        if _suppressed(project, rel, "GFL009", line):
            continue
        out.append(Violation(
            "GFL009", rel, line, 0,
            f"admin route '{path}' is registered here but missing from "
            "the README route table — operators discover the admin "
            "plane from that table",
        ))
    for path, lineno in sorted(claimed.items()):
        if _route_key(path) in code_keys:
            continue
        out.append(Violation(
            "GFL009", str(readme), lineno, 0,
            f"README route table lists '{path}' but no registration "
            "for it exists in the scanned tree — stale row",
        ))
    return out


def contract_violations(project: Project, root: Path) -> list[Violation]:
    out = check_metrics(project, root)
    out.extend(check_config_keys(project, root))
    out.extend(check_admin_routes(project, root))
    return out
