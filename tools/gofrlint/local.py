"""The per-file rules (GFL001–GFL006) — unchanged semantics from
gofrlint v1, now layered on the shared substrate in ``base``. The
whole-program rules (interprocedural GFL004, GFL007–009) live in
``interproc``/``contracts`` and run from ``cli.lint_paths``."""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from .base import (
    _COUNTER_SUFFIXES,
    _GAUGE_ALLOWLIST,
    _GAUGE_SUFFIXES,
    _HISTOGRAM_SUFFIXES,
    Directives,
    Violation,
    classify_blocking,
    lockish,
    src_of,
)

# GFL001: os.environ methods that WRITE (allowed anywhere — scripts and
# test scaffolding set the process environment; only reads must route
# through config.py accessors)
_ENV_WRITE_METHODS = {"update", "pop", "setdefault", "clear", "__setitem__"}

# GFL006: modules whose code runs on (or under the locks of) engine
# threads — a swallowed exception there is a silent wedge
_ENGINE_MODULES = {
    "telemetry.py", "timebase.py", "tracing.py", "postmortem.py",
    "metrics.py", "profiling.py",
}

_LOCKISH_RE = re.compile(r"(lock|mutex|_mu)\b", re.IGNORECASE)


class FileLinter:
    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.directives = Directives(source)
        self.comments = self.directives.comments
        self.violations: list[Violation] = []
        self.in_package = "gofr_tpu" in Path(rel).parts
        parts = Path(rel).parts
        self.is_engine = (
            ("tpu" in parts and self.in_package)
            or Path(rel).name in _ENGINE_MODULES and self.in_package
        )

    # -- directives -----------------------------------------------------------
    def suppressed(self, rule: str, lineno: int) -> bool:
        return self.directives.suppressed(rule, lineno)

    def wall_annotated(self, lineno: int) -> bool:
        return self.directives.wall_annotated(lineno)

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.suppressed(rule, lineno):
            return
        self.violations.append(Violation(rule, self.rel, lineno, col, message))

    # -- entry ----------------------------------------------------------------
    def run(self) -> list[Violation]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as exc:
            self.violations.append(Violation(
                "GFL000", self.rel, exc.lineno or 1, 0,
                f"syntax error: {exc.msg}",
            ))
            return self.violations
        parents: dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent
        self._parents = parents
        module_joins = self._module_has_thread_join(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_env_read_call(node)
                self._check_wall_clock(node)
                self._check_thread(node, module_joins)
                self._check_metric_name(node)
            elif isinstance(node, ast.Attribute):
                self._check_environ_use(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_except(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_lock_holds(node)
        return self.violations

    # -- GFL001 ---------------------------------------------------------------
    def _gfl001_active(self) -> bool:
        return self.in_package and Path(self.rel).name != "config.py"

    def _check_env_read_call(self, node: ast.Call) -> None:
        if not self._gfl001_active():
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "getenv" and \
                isinstance(fn.value, ast.Name) and fn.value.id == "os":
            self.report(
                "GFL001", node,
                "os.getenv() outside config.py — use a config.py accessor "
                "(get_env/env_flag)",
            )

    def _check_environ_use(self, node: ast.Attribute) -> None:
        if not self._gfl001_active():
            return
        if node.attr != "environ" or not (
            isinstance(node.value, ast.Name) and node.value.id == "os"
        ):
            return
        parent = self._parents.get(id(node))
        # allowed: write-method calls and item writes/deletes
        if isinstance(parent, ast.Attribute) and \
                parent.attr in _ENV_WRITE_METHODS:
            return
        if isinstance(parent, ast.Subscript) and isinstance(
            parent.ctx, (ast.Store, ast.Del)
        ):
            return
        self.report(
            "GFL001", node,
            "raw os.environ read outside config.py — use a config.py "
            "accessor (get_env/env_flag/environ_snapshot)",
        )

    # -- GFL002 ---------------------------------------------------------------
    def _check_wall_clock(self, node: ast.Call) -> None:
        fn = node.func
        is_time_time = (
            isinstance(fn, ast.Attribute) and fn.attr == "time"
            and isinstance(fn.value, ast.Name) and fn.value.id == "time"
        )
        if not is_time_time:
            return
        if self.wall_annotated(node.lineno):
            return
        self.report(
            "GFL002", node,
            "time.time() — use time.monotonic()/perf_counter() for "
            "durations and ordering; annotate true presentation sites "
            "with '# gofrlint: wall-clock — <why>'",
        )

    # -- GFL003 ---------------------------------------------------------------
    @staticmethod
    def _module_has_thread_join(tree: ast.Module) -> bool:
        """A zero-positional-arg ``.join()`` call anywhere in the module
        (``t.join()``, ``self._thread.join(timeout=5)``). ``str.join``
        and ``os.path.join`` always take positional args."""
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not node.args
            ):
                return True
        return False

    def _check_thread(self, node: ast.Call, module_joins: bool) -> None:
        fn = node.func
        is_thread = (
            isinstance(fn, ast.Attribute) and fn.attr == "Thread"
            and isinstance(fn.value, ast.Name) and fn.value.id == "threading"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if not is_thread:
            return
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        if "name" not in kwargs:
            self.report(
                "GFL003", node,
                "unnamed thread — pass name=... so stacks, the watchdog, "
                "and the leak detector can attribute it",
            )
        daemon = kwargs.get("daemon")
        is_daemon = isinstance(daemon, ast.Constant) and daemon.value is True
        if not is_daemon and not module_joins:
            self.report(
                "GFL003", node,
                "non-daemon thread with no .join() in this module — "
                "daemonize it or join it in close()",
            )

    # -- GFL004 (local: blocking primitive directly under a held lock) --------
    def _check_lock_holds(self, func: ast.AST) -> None:
        self._walk_stmts(list(getattr(func, "body", [])), held=[])

    def _walk_stmts(self, stmts: list, held: list) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are visited on their own
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = [
                    src_of(item.context_expr)
                    for item in stmt.items
                    if lockish(item.context_expr)
                ]
                held.extend(acquired)
                self._walk_stmts(stmt.body, held)
                for _ in acquired:
                    held.pop()
                continue
            lock_op = self._acquire_release(stmt)
            if lock_op is not None:
                op, name = lock_op
                if op == "acquire":
                    held.append(name)
                elif name in held:
                    held.remove(name)
                continue
            if held:
                for call in (
                    n for n in ast.walk(stmt) if isinstance(n, ast.Call)
                ):
                    self._check_blocking(call, held)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    self._walk_stmts(list(getattr(stmt, attr, [])), held)
                for handler in getattr(stmt, "handlers", []):
                    self._walk_stmts(list(handler.body), held)

    def _acquire_release(self, stmt: ast.stmt) -> Optional[tuple]:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        if not isinstance(call.func, ast.Attribute):
            return None
        if call.func.attr not in ("acquire", "release"):
            return None
        receiver = src_of(call.func.value)
        if not _LOCKISH_RE.search(receiver):
            return None
        return (call.func.attr, receiver)

    def _check_blocking(self, call: ast.Call, held: list) -> None:
        label = classify_blocking(call, held)
        if label is None:
            return
        self.report(
            "GFL004", call,
            f"{label} while holding {held[-1]!r} — blocking under a lock "
            "stalls every contending thread (move it outside the "
            "critical section)",
        )

    # -- GFL005 ---------------------------------------------------------------
    def _check_metric_name(self, node: ast.Call) -> None:
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("counter", "gauge", "histogram")
        ):
            return
        if not node.args or not (
            isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return
        name = node.args[0].value
        kind = fn.attr
        problem = None
        if not name.startswith("gofr_"):
            problem = "missing gofr_ prefix"
        elif not re.fullmatch(r"[a-z][a-z0-9_]*", name) or "__" in name:
            problem = "not snake_case"
        elif kind == "counter" and not name.endswith(_COUNTER_SUFFIXES):
            problem = "counter must end in _total"
        elif kind == "histogram" and not name.endswith(_HISTOGRAM_SUFFIXES):
            problem = f"histogram needs a unit suffix {_HISTOGRAM_SUFFIXES}"
        elif kind == "gauge" and name not in _GAUGE_ALLOWLIST and \
                not name.endswith(_GAUGE_SUFFIXES):
            problem = (
                f"gauge needs a unit/dimension suffix {_GAUGE_SUFFIXES} "
                "(or an allowlist entry)"
            )
        if problem:
            self.report("GFL005", node, f"metric {name!r}: {problem}")

    # -- GFL006 ---------------------------------------------------------------
    def _check_except(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.report(
                "GFL006", node,
                "bare except: — catch a concrete exception type",
            )
            return
        if not self.is_engine:
            return
        broad = isinstance(node.type, ast.Name) and node.type.id in (
            "Exception", "BaseException"
        )
        body_is_pass = all(
            isinstance(s, ast.Pass)
            or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
            for s in node.body
        )
        if broad and body_is_pass:
            # report at the pass statement: the suppression comment (the
            # ledger entry) belongs next to the swallow itself
            self.report(
                "GFL006", node.body[0],
                f"except {node.type.id}: pass in an engine path — a "
                "swallowed exception on an engine thread is a silent "
                "wedge; log it, re-raise, or narrow the type",
            )
