"""Interprocedural half of GFL004 plus the static lock-order graph.

Per function we extract a summary: the call sites it makes (with the
set of locks held at each site), any directly-blocking primitive in
its body, and the locks it acquires. Two monotone facts are then
computed to a fixpoint over the call graph:

- ``may_block(f)``: f contains a blocking primitive, or calls (through
  the resolved graph) a function that does. A witness chain is kept so
  the finding names the path (``append_tokens → _sync → os.fsync()``).
- ``acquires_any(f)``: every lock f may take, directly or transitively.

Findings: a call site executed while a lock is held whose callee
``may_block`` — the PR 14 shape (WAL fsync reached through attribute
dispatch while the per-token journal lock is held) that the per-file
rule is structurally blind to.

Lock-order edges: lock B acquired (directly or via a callee) while A
is held → edge A→B, exported as JSON for the merge with the runtime
sanitizer's observed graph (tools/lockgraph_check.py)."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .base import Violation, classify_blocking, lockish, src_of
from .model import FunctionInfo, Project

_STMT_LIST_FIELDS = {"body", "orelse", "finalbody", "handlers", "items"}

_FIXPOINT_CAP = 50  # call-graph depth bound; deeper chains than this
# don't occur in a ~25k LoC tree and a cap keeps pathological inputs
# from spinning


def _expr_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Call nodes in a statement's own expressions (header of a
    compound statement, the whole of a simple one) — NOT in nested
    statement bodies, which the structural walk visits itself."""
    for field, value in ast.iter_fields(stmt):
        if field in _STMT_LIST_FIELDS:
            continue
        values = value if isinstance(value, list) else [value]
        for v in values:
            if isinstance(v, ast.AST):
                for node in ast.walk(v):
                    if isinstance(node, ast.Call):
                        yield node


class Summary:
    __slots__ = (
        "func", "calls", "direct_block", "acquires", "edges",
        "may_block", "witness", "acquires_any",
    )

    def __init__(self, func: FunctionInfo):
        self.func = func
        # (call node, tuple of held lock ids at the site)
        self.calls: list[tuple[ast.Call, tuple]] = []
        self.direct_block: Optional[tuple] = None  # (label, lineno)
        self.acquires: set[str] = set()
        # (held_id, acquired_id, "rel:lineno" of the acquisition)
        self.edges: set[tuple] = set()
        self.may_block = False
        self.witness = ""          # human chain, e.g. "_sync → os.fsync()"
        self.acquires_any: set[str] = set()


class _FunctionScanner:
    """One structural walk of a function body, tracking the held-lock
    stack through ``with`` blocks and acquire()/release() statements."""

    def __init__(self, project: Project, func: FunctionInfo):
        self.project = project
        self.func = func
        self.summary = Summary(func)

    def run(self) -> Summary:
        self._walk(list(self.func.node.body), held=[])
        self.summary.acquires_any = set(self.summary.acquires)
        return self.summary

    def _record_call(self, call: ast.Call, held: list) -> None:
        self.summary.calls.append(
            (call, tuple(lid for lid, _src in held))
        )
        if self.summary.direct_block is None:
            label = classify_blocking(call, None)
            if label is not None:
                self.summary.direct_block = (label, call.lineno)

    def _acquire(self, expr: ast.AST, held: list) -> str:
        lid = self.project.lock_id(expr, self.func)
        site = f"{self.func.rel}:{getattr(expr, 'lineno', 0)}"
        for held_id, _src in held:
            if held_id != lid:
                self.summary.edges.add((held_id, lid, site))
        self.summary.acquires.add(lid)
        return lid

    def _walk(self, stmts: list, held: list) -> None:
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # nested defs don't run at definition time
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for call in _expr_calls(stmt):
                    self._record_call(call, held)
                acquired = 0
                for item in stmt.items:
                    if lockish(item.context_expr):
                        lid = self._acquire(item.context_expr, held)
                        held.append((lid, src_of(item.context_expr)))
                        acquired += 1
                self._walk(stmt.body, held)
                for _ in range(acquired):
                    held.pop()
                continue
            lock_op = self._acquire_release_stmt(stmt)
            if lock_op is not None:
                op, expr = lock_op
                if op == "acquire":
                    lid = self._acquire(expr, held)
                    held.append((lid, src_of(expr)))
                else:
                    lid = self.project.lock_id(expr, self.func)
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == lid:
                            del held[i]
                            break
                continue
            for call in _expr_calls(stmt):
                self._record_call(call, held)
            for field in ("body", "orelse", "finalbody"):
                self._walk(list(getattr(stmt, field, [])), held)
            for handler in getattr(stmt, "handlers", []):
                self._walk(list(handler.body), held)

    @staticmethod
    def _acquire_release_stmt(stmt: ast.stmt) -> Optional[tuple]:
        if not (isinstance(stmt, ast.Expr) and
                isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        if not isinstance(call.func, ast.Attribute):
            return None
        if call.func.attr not in ("acquire", "release"):
            return None
        if not lockish(call.func.value):
            return None
        return (call.func.attr, call.func.value)


class WholeProgram:
    """The fixpoint pass over a built :class:`Project`."""

    def __init__(self, project: Project):
        self.project = project
        self.summaries: dict[str, Summary] = {}
        for func in project.functions.values():
            self.summaries[func.qname] = _FunctionScanner(project, func).run()
        self._resolved: dict[int, list[FunctionInfo]] = {}
        self._fixpoint()

    def _callees(self, summary: Summary, call: ast.Call) -> list[FunctionInfo]:
        key = id(call)
        if key not in self._resolved:
            self._resolved[key] = self.project.resolve_call(
                call, summary.func
            )
        return self._resolved[key]

    def _fixpoint(self) -> None:
        for s in self.summaries.values():
            if s.direct_block is not None:
                s.may_block = True
                s.witness = s.direct_block[0]
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for s in self.summaries.values():
                for call, _held in s.calls:
                    for callee in self._callees(s, call):
                        cs = self.summaries.get(callee.qname)
                        if cs is None:
                            continue
                        if cs.may_block and not s.may_block:
                            s.may_block = True
                            s.witness = f"{callee.name} → {cs.witness}"
                            changed = True
                        extra = cs.acquires_any - s.acquires_any
                        if extra:
                            s.acquires_any |= extra
                            changed = True
            if not changed:
                break

    # -- whole-program GFL004 -------------------------------------------------
    def _blocks_only_within(self, cls, qname: str, seen: set) -> bool:
        """Every may-block path from ``qname`` stays inside methods of
        ``cls`` — the resource-guard shape (a class serializing its OWN
        blocking resource behind its own lock: JournalWAL's fsync under
        JournalWAL._lock). Such chains are visible in one screen of
        code; the rule exists for reach-through that crosses object
        boundaries."""
        summary = self.summaries.get(qname)
        if summary is None or summary.func.cls is not cls:
            return False
        if qname in seen:
            return True
        seen.add(qname)
        for call, _held in summary.calls:
            for callee in self._callees(summary, call):
                cs = self.summaries.get(callee.qname)
                if cs is not None and cs.may_block and \
                        not self._blocks_only_within(cls, callee.qname, seen):
                    return False
        return True

    def _self_intrinsic(self, s: Summary, held: tuple,
                        callee: FunctionInfo) -> bool:
        cls = s.func.cls
        if cls is None:
            return False
        if not all(
            self.project.lock_owned_by_class(lid, cls) for lid in held
        ):
            return False
        return self._blocks_only_within(cls, callee.qname, set())

    def violations(self) -> list[Violation]:
        out: list[Violation] = []
        for s in self.summaries.values():
            directives = s.func.module.directives
            for call, held in s.calls:
                if not held:
                    continue
                for callee in self._callees(s, call):
                    cs = self.summaries.get(callee.qname)
                    if cs is None or not cs.may_block:
                        continue
                    if self._self_intrinsic(s, held, callee):
                        continue
                    if directives.suppressed("GFL004", call.lineno):
                        continue
                    out.append(Violation(
                        "GFL004", s.func.rel, call.lineno,
                        call.col_offset,
                        f"call to {callee.name}() may block "
                        f"({callee.name} → {cs.witness}) while holding "
                        f"lock {held[-1]} — reached through the call "
                        "graph; move the blocking work outside the "
                        "critical section",
                    ))
                    break  # one finding per call site is enough
        return out

    # -- static lock-order graph ----------------------------------------------
    def lock_graph(self) -> dict:
        """``{"source": "static", "nodes": [...], "edges": [...]}`` —
        node ids are creation sites (``rel:lineno``) where resolvable,
        matching the runtime sanitizer's creation labels."""
        edges: dict[tuple, str] = {}
        for s in self.summaries.values():
            for a, b, site in s.edges:
                edges.setdefault((a, b), site)
            # interprocedural: a call made under lock A to a function
            # that may acquire B is an A→B ordering edge
            for call, held in s.calls:
                if not held:
                    continue
                for callee in self._callees(s, call):
                    cs = self.summaries.get(callee.qname)
                    if cs is None:
                        continue
                    site = f"{s.func.rel}:{call.lineno}"
                    for b in sorted(cs.acquires_any):
                        for a in held:
                            if a != b:
                                edges.setdefault((a, b), site)
        nodes = sorted({n for pair in edges for n in pair})
        return {
            "version": 1,
            "source": "static",
            "nodes": [{"id": n} for n in nodes],
            "edges": [
                {"from": a, "to": b, "site": site}
                for (a, b), site in sorted(edges.items())
            ],
        }
