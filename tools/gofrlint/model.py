"""Project model for the whole-program pass: modules, classes,
module-level functions, an import map, lock creation sites, and the
conservative call-resolution the interprocedural rules share.

Resolution is deliberately conservative (documented blind spots in
docs/advanced-guide/static-analysis.md): it follows

- direct calls to module-level functions (same module or imported),
- ``self.method(...)`` through the enclosing class and its in-project
  bases,
- ``self.attr.method(...)`` where ``attr``'s class is inferred from a
  ``self.attr = SomeClass(...)`` assignment in any method of the class
  (the PR 14 journal→WAL shape), and
- ``module.func(...)`` through ``import``/``from .. import`` aliases.

Dynamic dispatch through dicts, monkeypatched attributes, callables
passed as arguments, and nested ``def``s are NOT resolved — the rules
built on top must stay sound-for-the-resolved-subgraph, not complete.

Lock identity is the CREATION SITE ``relpath:lineno`` of the
``threading.Lock()`` / ``RLock()`` / ``Condition()`` call — the same
label the runtime sanitizer stamps on its observed graph (modulo path
normalization), so the static and runtime lock-order graphs merge on
node id."""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from .base import Directives

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def dotted_name(rel: str) -> str:
    parts = list(Path(rel).parts)
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]  # strip .py
    return ".".join(parts)


class FunctionInfo:
    __slots__ = ("qname", "name", "rel", "cls", "node", "module")

    def __init__(self, qname, name, rel, cls, node, module):
        self.qname = qname          # "rel::Class.meth" or "rel::func"
        self.name = name
        self.rel = rel
        self.cls = cls              # ClassInfo | None
        self.node = node
        self.module = module        # ModuleInfo

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<fn {self.qname}>"


class ClassInfo:
    __slots__ = ("qname", "name", "rel", "bases", "methods", "attr_types",
                 "module")

    def __init__(self, qname, name, rel, bases, module):
        self.qname = qname
        self.name = name
        self.rel = rel
        self.bases = bases          # list[ast.expr]
        self.methods: dict[str, FunctionInfo] = {}
        self.attr_types: dict[str, str] = {}   # attr -> ClassInfo.qname
        self.module = module


class ModuleInfo:
    __slots__ = ("rel", "dotted", "source", "tree", "directives",
                 "functions", "classes", "import_map")

    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.dotted = dotted_name(rel)
        self.source = source
        self.tree = tree
        self.directives = Directives(source)
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # local name -> ("module", dotted) | ("symbol", dotted, symbol)
        self.import_map: dict[str, tuple] = {}


class Project:
    """Symbol table + call resolution over a set of parsed sources."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.by_dotted: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}       # qname -> ClassInfo
        self.functions: dict[str, FunctionInfo] = {}  # qname -> FunctionInfo
        # (owner, attr/name) -> "rel:lineno" creation site; owner is a
        # class qname for instance locks, a module rel for globals
        self.lock_sites: dict[tuple, str] = {}

    # -- construction ---------------------------------------------------------
    @classmethod
    def from_sources(cls, sources: dict[str, str]) -> "Project":
        proj = cls()
        for rel in sorted(sources):
            try:
                tree = ast.parse(sources[rel])
            except SyntaxError:
                continue  # the per-file pass reports GFL000
            proj._add_module(rel, sources[rel], tree)
        for mod in proj.modules.values():
            proj._infer_attr_types(mod)
        return proj

    def _add_module(self, rel: str, source: str, tree: ast.Module) -> None:
        mod = ModuleInfo(rel, source, tree)
        self.modules[rel] = mod
        self.by_dotted[mod.dotted] = mod
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.asname and alias.name or \
                        alias.name.split(".")[0]
                    mod.import_map[local] = ("module", target)
            elif isinstance(stmt, ast.ImportFrom):
                base = self._resolve_from(mod, stmt)
                if base is None:
                    continue
                for alias in stmt.names:
                    local = alias.asname or alias.name
                    mod.import_map[local] = ("symbol", base, alias.name)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = f"{rel}::{stmt.name}"
                info = FunctionInfo(qname, stmt.name, rel, None, stmt, mod)
                mod.functions[stmt.name] = info
                self.functions[qname] = info
            elif isinstance(stmt, ast.ClassDef):
                qname = f"{rel}::{stmt.name}"
                cinfo = ClassInfo(qname, stmt.name, rel, stmt.bases, mod)
                mod.classes[stmt.name] = cinfo
                self.classes[qname] = cinfo
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fq = f"{rel}::{stmt.name}.{sub.name}"
                        finfo = FunctionInfo(
                            fq, sub.name, rel, cinfo, sub, mod
                        )
                        cinfo.methods[sub.name] = finfo
                        self.functions[fq] = finfo
            elif isinstance(stmt, ast.Assign):
                self._maybe_lock_site(
                    mod, stmt.targets, stmt.value, owner=rel, selfish=False
                )

    @staticmethod
    def _resolve_from(mod: ModuleInfo, stmt: ast.ImportFrom) -> Optional[str]:
        if stmt.level == 0:
            return stmt.module or ""
        parts = mod.dotted.split(".")
        # level=1 from a plain module: strip the module's own name;
        # from a package __init__: the package itself is the base
        is_pkg = Path(mod.rel).name == "__init__.py"
        drop = stmt.level - 1 if is_pkg else stmt.level
        if drop >= len(parts):
            return stmt.module
        base = parts[: len(parts) - drop] if drop else parts
        if stmt.module:
            return ".".join(base + [stmt.module])
        return ".".join(base)

    def _maybe_lock_site(self, mod, targets, value, owner, selfish) -> None:
        """Record ``<target> = threading.Lock()`` creation sites."""
        if not isinstance(value, ast.Call):
            return
        fn = value.func
        is_factory = (
            isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES
            and isinstance(fn.value, ast.Name) and fn.value.id == "threading"
        ) or (isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES)
        if not is_factory:
            return
        site = f"{mod.rel}:{value.lineno}"
        for target in targets:
            if selfish:
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    self.lock_sites[(owner, target.attr)] = site
            elif isinstance(target, ast.Name):
                self.lock_sites[(owner, target.id)] = site

    def _infer_attr_types(self, mod: ModuleInfo) -> None:
        """``self.attr = SomeClass(...)`` in any method body → the attr
        is SomeClass for dispatch purposes; also record lock creation
        sites on self attributes."""
        for cinfo in mod.classes.values():
            for meth in cinfo.methods.values():
                for node in ast.walk(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    self._maybe_lock_site(
                        mod, node.targets, node.value,
                        owner=cinfo.qname, selfish=True,
                    )
                    if not isinstance(node.value, ast.Call):
                        continue
                    target_cls = self.resolve_class(node.value.func, mod)
                    if target_cls is None:
                        continue
                    for target in node.targets:
                        if isinstance(target, ast.Attribute) and \
                                isinstance(target.value, ast.Name) and \
                                target.value.id == "self":
                            cinfo.attr_types[target.attr] = target_cls.qname

    # -- resolution -----------------------------------------------------------
    def resolve_module(self, mod: ModuleInfo, local: str) -> Optional[ModuleInfo]:
        entry = mod.import_map.get(local)
        if entry and entry[0] == "module":
            return self.by_dotted.get(entry[1])
        if entry and entry[0] == "symbol":
            # "from pkg import submodule" style
            return self.by_dotted.get(f"{entry[1]}.{entry[2]}")
        return None

    def resolve_class(self, func_expr: ast.AST,
                      mod: ModuleInfo) -> Optional[ClassInfo]:
        """The ClassInfo a constructor expression refers to, if any."""
        if isinstance(func_expr, ast.Name):
            if func_expr.id in mod.classes:
                return mod.classes[func_expr.id]
            entry = mod.import_map.get(func_expr.id)
            if entry and entry[0] == "symbol":
                target = self.by_dotted.get(entry[1])
                if target:
                    return target.classes.get(entry[2])
        elif isinstance(func_expr, ast.Attribute) and \
                isinstance(func_expr.value, ast.Name):
            target = self.resolve_module(mod, func_expr.value.id)
            if target:
                return target.classes.get(func_expr.attr)
        return None

    def method_lookup(self, cinfo: ClassInfo, name: str,
                      _depth: int = 0) -> Optional[FunctionInfo]:
        if name in cinfo.methods:
            return cinfo.methods[name]
        if _depth >= 4:
            return None
        for base_expr in cinfo.bases:
            base = self.resolve_class(base_expr, cinfo.module)
            if base is not None:
                found = self.method_lookup(base, name, _depth + 1)
                if found is not None:
                    return found
        return None

    def attr_class(self, caller: FunctionInfo,
                   attr: str) -> Optional[ClassInfo]:
        if caller.cls is None:
            return None
        qname = caller.cls.attr_types.get(attr)
        return self.classes.get(qname) if qname else None

    def resolve_call(self, call: ast.Call,
                     caller: FunctionInfo) -> list[FunctionInfo]:
        """Callees a call site may reach (possibly empty — unresolved)."""
        fn = call.func
        mod = caller.module
        out: list[FunctionInfo] = []
        if isinstance(fn, ast.Name):
            if fn.id in mod.functions:
                out.append(mod.functions[fn.id])
            else:
                cls = self.resolve_class(fn, mod)
                if cls is not None:
                    init = self.method_lookup(cls, "__init__")
                    if init is not None:
                        out.append(init)
                else:
                    entry = mod.import_map.get(fn.id)
                    if entry and entry[0] == "symbol":
                        target = self.by_dotted.get(entry[1])
                        if target and entry[2] in target.functions:
                            out.append(target.functions[entry[2]])
        elif isinstance(fn, ast.Attribute):
            value = fn.value
            if isinstance(value, ast.Name) and value.id == "self" and \
                    caller.cls is not None:
                found = self.method_lookup(caller.cls, fn.attr)
                if found is not None:
                    out.append(found)
            elif isinstance(value, ast.Name):
                target = self.resolve_module(mod, value.id)
                if target and fn.attr in target.functions:
                    out.append(target.functions[fn.attr])
            elif isinstance(value, ast.Attribute) and \
                    isinstance(value.value, ast.Name) and \
                    value.value.id == "self":
                # self.attr.method(): class-typed attribute dispatch
                cls = self.attr_class(caller, value.attr)
                if cls is not None:
                    found = self.method_lookup(cls, fn.attr)
                    if found is not None:
                        out.append(found)
        return out

    # -- lock identity --------------------------------------------------------
    def lock_id(self, expr: ast.AST, caller: FunctionInfo) -> str:
        """A stable id for the lock object an acquisition expression
        names: the ``relpath:lineno`` creation site when resolvable
        (mergeable with the runtime sanitizer's labels), else a
        synthetic ``relpath::qualifier`` id."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and caller.cls is not None:
                # walk the class and its bases for the creation site
                cinfo: Optional[ClassInfo] = caller.cls
                depth = 0
                while cinfo is not None and depth < 5:
                    site = self.lock_sites.get((cinfo.qname, expr.attr))
                    if site:
                        return site
                    nxt = None
                    for base_expr in cinfo.bases:
                        nxt = self.resolve_class(base_expr, cinfo.module)
                        if nxt is not None:
                            break
                    cinfo, depth = nxt, depth + 1
                return f"{caller.rel}::{caller.cls.name}.{expr.attr}"
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Attribute) and \
                isinstance(expr.value.value, ast.Name) and \
                expr.value.value.id == "self":
            # self.attr._lock — a lock owned by a class-typed attribute
            cls = self.attr_class(caller, expr.value.attr)
            if cls is not None:
                site = self.lock_sites.get((cls.qname, expr.attr))
                if site:
                    return site
                return f"{cls.rel}::{cls.name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            site = self.lock_sites.get((caller.rel, expr.id))
            if site:
                return site
            return f"{caller.rel}::{expr.id}"
        try:
            text = ast.unparse(expr)
        except Exception:
            text = "<lock>"
        return f"{caller.rel}::{text}"

    def lock_owned_by_class(self, lock_id: str, cinfo: ClassInfo) -> bool:
        """True when ``lock_id`` names a lock this class created on
        ``self`` (creation site recorded in one of its methods) or a
        synthetic id minted for one of its own attributes."""
        if lock_id.startswith(f"{cinfo.rel}::{cinfo.name}."):
            return True
        return any(
            owner == cinfo.qname and site == lock_id
            for (owner, _attr), site in self.lock_sites.items()
        )
