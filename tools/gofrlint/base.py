"""Shared substrate for gofrlint: rule table, violation record,
suppression directives, and the blocking-call classifier both the
per-file pass (GFL004 local) and the whole-program pass (GFL004
interprocedural summaries) agree on. Stdlib only."""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import Optional

RULES = {
    "GFL001": "raw environment read outside config.py",
    "GFL002": "time.time() without a wall-clock annotation",
    "GFL003": "threading.Thread hygiene (name + daemon-or-joined)",
    "GFL004": "blocking call while holding a lock",
    "GFL005": "metric name violates the naming convention",
    "GFL006": "swallowed exception in an engine path",
    "GFL007": "metric contract drift across registration sites",
    "GFL008": "config-key provenance (undeclared read / inert knob)",
    "GFL009": "admin-surface parity (code vs README route table)",
}

_DISABLE_RE = re.compile(r"#\s*gofrlint:\s*disable=([A-Z0-9,\s]+)")
_WALL_RE = re.compile(r"#\s*gofrlint:\s*wall-clock")

# GFL005: mirrored from tests/test_metric_naming.py — the static half
# of the same convention
_COUNTER_SUFFIXES = ("_total",)
_HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_size")
_GAUGE_SUFFIXES = (  # keep in lockstep with tests/test_metric_naming.py
    "_seconds", "_bytes", "_total", "_depth", "_ratio", "_entries",
    "_active", "_acceptance", "_state", "_blocks", "_size", "_level",
    "_per_dispatch", "_rate", "_remaining",
)
_GAUGE_ALLOWLIST = {"gofr_tpu_mfu", "gofr_tpu_mbu"}

# GFL004 heuristics (shared with the interprocedural summaries)
_LOCKISH_RE = re.compile(r"(lock|mutex|_mu)\b", re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"(queue|(^|\.)q$|_q$)", re.IGNORECASE)
_EVENTISH_RE = re.compile(r"(event|_stop$|_ready$|stopped)", re.IGNORECASE)
_THREADISH_RE = re.compile(r"(thread|worker|proc)", re.IGNORECASE)


class Violation:
    __slots__ = ("rule", "path", "line", "col", "message")

    def __init__(self, rule: str, path: str, line: int, col: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message

    def as_dict(self) -> dict:
        return {
            "file": self.path, "line": self.line, "col": self.col,
            "rule": self.rule, "message": self.message,
        }


def src_of(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # very old nodes / synthetic trees
        return ""


def collect_comments(source: str) -> dict[int, str]:
    """line number -> comment text (tokenize-accurate: a ``# gofrlint``
    inside a string literal never counts)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class Directives:
    """Per-file suppression/annotation directives. Comment-only lines
    pass their directives down to the next CODE line (cascading through
    blank lines and further comment lines, so a multi-line reason block
    above a statement works)."""

    def __init__(self, source: str):
        self.comments = collect_comments(source)
        lines = source.splitlines()
        self._directive_lines: dict[int, str] = {}
        for lineno, comment in self.comments.items():
            line = lines[lineno - 1]
            code = line[: line.index("#")] if "#" in line else line
            target = lineno
            if not code.strip():
                target = lineno + 1
                while target <= len(lines):
                    stripped = lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            self._directive_lines.setdefault(target, "")
            self._directive_lines[target] += " " + comment

    def at(self, lineno: int) -> str:
        return self._directive_lines.get(lineno, "")

    def suppressed(self, rule: str, lineno: int) -> bool:
        m = _DISABLE_RE.search(self.at(lineno))
        if not m:
            return False
        codes = {c.strip() for c in m.group(1).split(",")}
        return rule in codes

    def wall_annotated(self, lineno: int) -> bool:
        return bool(_WALL_RE.search(self.at(lineno)))

    def disable_counts(self) -> dict[str, int]:
        """Per-rule count of disable-directive mentions in this file —
        one increment per rule per directive comment (the suppression
        LEDGER the ratchet sums)."""
        counts: dict[str, int] = {}
        for comment in self.comments.values():
            m = _DISABLE_RE.search(comment)
            if not m:
                continue
            for code in m.group(1).split(","):
                code = code.strip()
                if code:
                    counts[code] = counts.get(code, 0) + 1
        return counts


def lockish(expr: ast.AST) -> bool:
    return bool(_LOCKISH_RE.search(src_of(expr)))


def has_timeout(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    # Queue.get(block, timeout) positional form
    return len(call.args) >= 2


def classify_blocking(call: ast.Call, held: Optional[list] = None) -> Optional[str]:
    """The label of a blocking call, or None. ``held`` is the lock
    stack for the (local) under-a-lock context; summary mode passes
    None and counts socket reads unconditionally — a function that
    reads a socket MAY block, whether or not its own body holds a
    lock."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return "sleep()" if fn.id == "sleep" else None
    if not isinstance(fn, ast.Attribute):
        return None
    receiver = src_of(fn.value)
    attr = fn.attr
    if attr == "sleep" and receiver == "time":
        return "time.sleep()"
    if attr == "join" and not call.args and not has_timeout(call) \
            and _THREADISH_RE.search(receiver):
        # join(timeout=...) is a BOUNDED wait (teardown idiom) — only
        # the indefinite form counts as blocking
        return f"{receiver}.join()"
    if attr in ("get", "put") and _QUEUEISH_RE.search(receiver) \
            and not has_timeout(call):
        return f"timeout-less {receiver}.{attr}()"
    if attr == "wait" and _EVENTISH_RE.search(receiver) and \
            not has_timeout(call) and not call.args:
        return f"timeout-less {receiver}.wait()"
    if attr in ("accept", "recv", "recvfrom"):
        if held is None or _LOCKISH_RE.search(" ".join(held)):
            return f"socket .{attr}()"
        return None
    if attr in ("fsync", "fdatasync") and receiver == "os":
        # durability barriers stall for the device, not the GIL — the
        # PR 14 WAL-under-journal-lock hazard class
        return f"os.{attr}()"
    if receiver == "subprocess" and attr in (
        "run", "call", "check_call", "check_output"
    ):
        return f"subprocess.{attr}()"
    if receiver in ("requests", "urllib.request") or attr == "urlopen":
        return f"{receiver}.{attr}()"
    return None


def iter_files(paths: list[str]) -> list[Path]:
    out: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out
