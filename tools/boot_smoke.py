"""Real-TPU pallas boot smoke: compile + run the flash-attention kernel
family on the actual device (interpret=False) and compare against the
plain-attention reference. This is the FIRST stage of any hardware
window: the r04 lse/dvec tiling fix (671cbf7) targets a bug class that
interpret mode cannot observe (real Mosaic lowering rejects block shapes
interpret mode accepts — see /tmp/r04_hw/sweep.log in round 4), so the
kernels are only "known good" once this has passed on hardware.

Prints ONE JSON line: {"ok": bool, "cases": {...}, "platform": "..."}.
Exit 0 iff every case matched.

    python tools/boot_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    if os.environ.get("BOOT_SMOKE_CPU"):
        # script-validation mode: the ambient sitecustomize force-registers
        # the TPU plugin even under JAX_PLATFORMS=cpu; only this sticks
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from gofr_tpu.ops.flash import _reference, flash_attention

    platform = jax.devices()[0].platform
    interpret = platform != "tpu" and platform != "axon"
    rng = np.random.default_rng(0)
    cases: dict[str, dict] = {}
    ok = True

    def run(name: str, fn) -> None:
        nonlocal ok
        t0 = time.monotonic()
        try:
            err = float(fn())
            cases[name] = {
                "ok": err < 2e-2, "max_err": err,
                "seconds": round(time.monotonic() - t0, 2),
            }
            ok = ok and cases[name]["ok"]
        except Exception as exc:  # a lowering failure IS the finding
            cases[name] = {
                "ok": False, "error": repr(exc)[:500],
                "seconds": round(time.monotonic() - t0, 2),
            }
            ok = False

    def mk(b, s, h, d, dtype=jnp.bfloat16):
        return jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)

    def case_prefill():
        q, k, v = mk(2, 256, 4, 64), mk(2, 256, 4, 64), mk(2, 256, 4, 64)
        out = flash_attention(q, k, v, causal=True, interpret=interpret)
        ref = _reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), jnp.zeros((2,), jnp.int32),
            jnp.full((2,), 256, jnp.int32), True, 64 ** -0.5,
        )
        return jnp.max(jnp.abs(out.astype(jnp.float32) - ref))

    def case_gqa():
        q, k, v = mk(1, 256, 8, 64), mk(1, 256, 2, 64), mk(1, 256, 2, 64)
        out = flash_attention(q, k, v, causal=True, interpret=interpret)
        kr = jnp.repeat(k, 4, axis=2).astype(jnp.float32)
        vr = jnp.repeat(v, 4, axis=2).astype(jnp.float32)
        ref = _reference(
            q.astype(jnp.float32), kr, vr, jnp.zeros((1,), jnp.int32),
            jnp.full((1,), 256, jnp.int32), True, 64 ** -0.5,
        )
        return jnp.max(jnp.abs(out.astype(jnp.float32) - ref))

    def case_ragged_decode():
        # Sq=1 rows at per-request absolute offsets with a padded KV tail
        q = mk(4, 1, 4, 64)
        k, v = mk(4, 512, 4, 64), mk(4, 512, 4, 64)
        offs = jnp.asarray([3, 100, 257, 511], jnp.int32)
        lens = offs + 1
        out = flash_attention(
            q, k, v, causal=True, q_offset=offs, kv_lens=lens,
            interpret=interpret,
        )
        ref = _reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), offs, lens, True, 64 ** -0.5,
        )
        return jnp.max(jnp.abs(out.astype(jnp.float32) - ref))

    def case_bwd():
        # the lse residual feeds the fused backward — the exact path the
        # r04 tiling fix changed
        q, k, v = mk(1, 128, 2, 64), mk(1, 128, 2, 64), mk(1, 128, 2, 64)

        def loss_flash(q_, k_, v_):
            return jnp.sum(
                flash_attention(q_, k_, v_, causal=True, interpret=interpret)
                .astype(jnp.float32)
            )

        def loss_ref(q_, k_, v_):
            return jnp.sum(_reference(
                q_.astype(jnp.float32), k_.astype(jnp.float32),
                v_.astype(jnp.float32), jnp.zeros((1,), jnp.int32),
                jnp.full((1,), 128, jnp.int32), True, 64 ** -0.5,
            ))

        gq, gk, gv = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        return max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            for a, b in ((gq, rq), (gk, rk), (gv, rv))
        )

    run("prefill_256", case_prefill)
    run("gqa_4to1", case_gqa)
    run("ragged_decode", case_ragged_decode)
    run("fused_bwd", case_bwd)

    print(json.dumps({
        "ok": ok, "platform": platform, "interpret": bool(interpret),
        "cases": cases, "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
