"""CI smoke for the postmortem black box: boot the echo runner, inject
a synthetic device stall, and assert that a PARSEABLE postmortem bundle
lands on disk with the forensics an operator needs — the stalling
dispatch visible, thread stacks, timebase snapshots, and flight data.

    python tools/postmortem_smoke.py          # exit 0 = black box works

Compile-free (MODEL_NAME=echo, no XLA): safe for CPU-only CI runners.
Unlike the unit/e2e tests this exercises the FULL out-of-process
contract — the same bundle file a wedged bench round leaves in hw/rNN/,
validated through tools/postmortem_view.py, the same way a human (or
the driver) would read it after the process is gone.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"[pm-smoke] {msg}", flush=True)


def main() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    pm_dir = tempfile.mkdtemp(prefix="gofr_pm_smoke_")
    os.environ.update(
        HTTP_PORT=str(port),
        LOG_LEVEL="ERROR",
        MODEL_NAME="echo",
        TOKENIZER="byte",
        POSTMORTEM_DIR=pm_dir,
        TIMEBASE_INTERVAL_S="0.05",
        # 0.7s injected stall vs 0.1s deadline: degraded at 0.1s,
        # wedged (3x) at 0.3s — the wedge transition writes the bundle
        WATCHDOG_DISPATCH_TIMEOUT_S="0.1",
    )

    import gofr_tpu
    from gofr_tpu.openai_compat import register_openai_routes

    app = gofr_tpu.new()
    register_openai_routes(app)
    app.start()
    base = f"http://127.0.0.1:{port}"
    tpu = app.container.tpu
    assert tpu is not None, "echo TPU datasource failed to wire"
    try:
        # let the timebase accumulate pre-incident snapshots
        time.sleep(0.2)
        log("injecting 0.7s device stall")
        tpu.runner.stall_hook = lambda: time.sleep(0.7)

        def fire() -> None:
            req = urllib.request.Request(
                base + "/v1/chat/completions",
                data=json.dumps(
                    {"messages": [{"role": "user", "content": "stall"}],
                     "max_tokens": 1, "temperature": 0}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=30).read()

        worker = threading.Thread(target=fire, name="pm-smoke-fire")
        worker.start()

        bundle_path = None
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and bundle_path is None:
            bundles = sorted(
                n for n in os.listdir(pm_dir)
                if n.startswith("postmortem-") and n.endswith(".json")
            )
            if bundles:
                bundle_path = os.path.join(pm_dir, bundles[0])
                break
            time.sleep(0.05)
        worker.join()
        tpu.runner.stall_hook = None
        assert bundle_path, f"no bundle appeared in {pm_dir} within 15s"
        log(f"bundle written: {bundle_path}")

        # validate THROUGH the viewer — the same parser a human uses
        from tools import postmortem_view

        bundle = postmortem_view.load_bundle(bundle_path)
        d = postmortem_view.digest(bundle)
        log(f"digest: {json.dumps(d)}")
        assert bundle["reason"] == "wedged", bundle["reason"]
        assert d["engine_state"] == "wedged", d["engine_state"]
        assert d["stalled_watches"], "no stalled watchdog entry in bundle"
        stalled_ids = {w["dispatch_id"] for w in d["stalled_watches"]}
        running = set(d["dispatches_running"])
        assert stalled_ids & running, (
            f"stalling dispatch {stalled_ids} not visible as running "
            f"in the timeline ({running})"
        )
        assert d["timebase_snapshots"] >= 2, d["timebase_snapshots"]
        assert d["threads"] >= 2, d["threads"]
        assert d["requests_in_flight"] >= 1, "wedged request not in bundle"
        rc = postmortem_view.main([bundle_path])
        assert rc == 0, f"postmortem_view exited {rc}"
        log("postmortem black box OK")
        return 0
    finally:
        app.shutdown()


if __name__ == "__main__":
    sys.exit(main())
