"""Inter-service HTTP client: named downstream services with uniform
logging, tracing, and correlation-ID propagation.

Parity: /root/reference/pkg/gofr/service/new.go:18-176 — the ten-method
surface (Get/Post/Put/Patch/Delete × plain / WithHeaders, :25-54),
per-request CLIENT span (:116-119), correlation ID from the caller's trace
(:126), timed ServiceLog / ErrorLog (:134-156), and query encoding
(:161-176). Over DCN between pod hosts this same client is the host-to-host
coordination path (SURVEY.md §2 #20).
"""

from __future__ import annotations

import json as _json
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Optional

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.tracing import CLIENT, current_span, get_tracer


@dataclass
class ServiceLog:
    """Typed outbound-call log entry (parity: service/logger.go:5-21)."""

    correlation_id: str
    service: str
    method: str
    uri: str
    status: int
    response_time_us: int

    def pretty_terminal(self) -> str:
        color = 32 if 0 < self.status < 400 else 31
        return (
            f"\x1b[{color}m{self.status}\x1b[0m "
            f"{self.method:<7s} {self.uri} {self.response_time_us}µs [svc {self.service}]"
        )

    def log_fields(self) -> dict[str, Any]:
        return {
            "correlation_id": self.correlation_id,
            "service": self.service,
            "method": self.method,
            "uri": self.uri,
            "status": self.status,
            "response_time_us": self.response_time_us,
        }


class ServiceResponse:
    """Parity: service/response.go:5-17."""

    def __init__(self, status_code: int, body: bytes, headers: dict[str, str]):
        self.status_code = status_code
        self.body = body
        self.headers = headers

    def json(self) -> Any:
        return _json.loads(self.body.decode("utf-8") or "null")


class HTTPService:
    """A named downstream-service client (parity: service/new.go:18-23)."""

    def __init__(self, address: str, logger: Any, name: str = "", timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.logger = logger
        self.name = name or self.address
        self.timeout = timeout

    # -- the 10-method HTTP interface (parity: new.go:25-54) -----------------
    def get(self, path: str, params: Optional[dict] = None) -> ServiceResponse:
        return self._send("GET", path, params, None, None)

    def get_with_headers(self, path: str, params: Optional[dict], headers: dict) -> ServiceResponse:
        return self._send("GET", path, params, None, headers)

    def post(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self._send("POST", path, params, body, None)

    def post_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self._send("POST", path, params, body, headers)

    def put(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self._send("PUT", path, params, body, None)

    def put_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self._send("PUT", path, params, body, headers)

    def patch(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self._send("PATCH", path, params, body, None)

    def patch_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self._send("PATCH", path, params, body, headers)

    def delete(self, path: str, body: Any = None) -> ServiceResponse:
        return self._send("DELETE", path, None, body, None)

    def delete_with_headers(self, path, body, headers) -> ServiceResponse:
        return self._send("DELETE", path, None, body, headers)

    # -- async variants -------------------------------------------------------
    # The sync methods block; calling them from an ``async def`` handler
    # would stall the server's event loop. Async handlers must use these.
    async def async_get(self, path: str, params: Optional[dict] = None) -> ServiceResponse:
        return await self._offload(self.get, path, params)

    async def async_post(self, path: str, params: Optional[dict] = None,
                         body: Any = None) -> ServiceResponse:
        return await self._offload(self.post, path, params, body)

    async def async_put(self, path: str, params: Optional[dict] = None,
                        body: Any = None) -> ServiceResponse:
        return await self._offload(self.put, path, params, body)

    async def async_patch(self, path: str, params: Optional[dict] = None,
                          body: Any = None) -> ServiceResponse:
        return await self._offload(self.patch, path, params, body)

    async def async_delete(self, path: str, body: Any = None) -> ServiceResponse:
        return await self._offload(self.delete, path, body)

    @staticmethod
    async def _offload(fn: Any, *args: Any) -> ServiceResponse:
        import asyncio
        import contextvars

        loop = asyncio.get_running_loop()
        call = contextvars.copy_context().run
        return await loop.run_in_executor(None, call, fn, *args)

    # -- internals (parity: createAndSendRequest, new.go:111-159) ------------
    def _send(
        self,
        method: str,
        path: str,
        params: Optional[dict],
        body: Any,
        headers: Optional[dict],
    ) -> ServiceResponse:
        uri = self.address + "/" + path.lstrip("/")
        if params:
            uri += "?" + _encode_query(params)

        data: Optional[bytes] = None
        send_headers = dict(headers or {})
        if body is not None:
            if isinstance(body, bytes):
                data = body
            else:
                data = _json.dumps(body).encode("utf-8")
                send_headers.setdefault("Content-Type", "application/json")

        tracer = get_tracer()
        span = tracer.start_span(f"{method} {uri}", kind=CLIENT, activate=False)
        correlation_id = span.trace_id
        # downstream SERVER span must parent onto this CLIENT span
        send_headers.setdefault("traceparent", span.traceparent())
        send_headers.setdefault("X-Correlation-ID", correlation_id)

        start = time.perf_counter()
        status = 0
        try:
            req = urllib.request.Request(uri, data=data, headers=send_headers, method=method)
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                status = resp.status
                payload = resp.read()
                resp_headers = dict(resp.headers.items())
        except urllib.error.HTTPError as exc:
            status = exc.code
            payload = exc.read()
            resp_headers = dict(exc.headers.items()) if exc.headers else {}
        except Exception as exc:
            elapsed_us = int((time.perf_counter() - start) * 1e6)
            span.set_tag("error", exc)
            span.end()
            self.logger.error(
                ServiceLog(correlation_id, self.name, method, uri, 0, elapsed_us)
            )
            raise ServiceCallError(self.name, uri, exc) from exc

        elapsed_us = int((time.perf_counter() - start) * 1e6)
        span.set_tag("http.status_code", status)
        span.end()
        log_entry = ServiceLog(correlation_id, self.name, method, uri, status, elapsed_us)
        if status >= 500:
            self.logger.error(log_entry)
        else:
            self.logger.info(log_entry)
        return ServiceResponse(status, payload, resp_headers)

    def health_check(self) -> Health:
        """GET /.well-known/health on the downstream (TPU-native addition:
        the container aggregates registered services into its own health)."""
        try:
            resp = self.get("/.well-known/health")
            return Health(UP if resp.status_code == 200 else DOWN, {"host": self.address})
        except Exception as exc:
            return Health(DOWN, {"host": self.address, "error": str(exc)})


class ServiceCallError(Exception):
    status_code = 502

    def __init__(self, service: str, uri: str, cause: Exception):
        super().__init__(f"call to service '{service}' failed: {cause}")
        self.service = service
        self.uri = uri
        self.cause = cause


def _encode_query(params: dict) -> str:
    """Parity: service/new.go:161-176 — list values repeat the key."""
    pairs: list[tuple[str, str]] = []
    for key, value in params.items():
        if isinstance(value, (list, tuple)):
            pairs.extend((key, str(v)) for v in value)
        else:
            pairs.append((key, str(value)))
    return urllib.parse.urlencode(pairs)


def new_http_service(address: str, logger: Any, name: str = "") -> HTTPService:
    """Parity: service/new.go:56."""
    return HTTPService(address, logger, name=name)
