"""Inter-service HTTP client: named downstream services with uniform
logging, tracing, and correlation-ID propagation.

Parity: /root/reference/pkg/gofr/service/new.go:18-176 — the ten-method
surface (Get/Post/Put/Patch/Delete × plain / WithHeaders, :25-54),
per-request CLIENT span (:116-119), correlation ID from the caller's trace
(:126), timed ServiceLog / ErrorLog (:134-156), and query encoding
(:161-176). Over DCN between pod hosts this same client is the host-to-host
coordination path (SURVEY.md §2 #20).

Resilience layer beyond the reference (the fleet router in
``gofr_tpu/fleet`` leans on all of it, but each piece works standalone):

- **connect/read timeout split** — the old flat ``timeout=30.0`` meant a
  dead host burned the whole request budget before the caller could try
  a sibling replica. ``connect_timeout`` bounds TCP establishment
  (default 5s), ``read_timeout`` bounds each response read (default
  30s), and every call can override both per request.
- **bounded retries with decorrelated-jitter backoff** — ``retries=N``
  re-attempts connect errors, read timeouts, and 502/503/504 replies
  for idempotent methods (callers that KNOW a POST is safe pass
  ``retryable=True``). Sleeps follow the decorrelated-jitter rule
  (``min(cap, uniform(base, 3*prev))``) so a failing fleet never sees
  synchronized retry waves. An optional ``deadline_s`` caps the total
  budget across attempts.
- **no leaked connections** — each attempt runs on its own
  ``http.client`` connection closed in a ``finally`` (the old
  ``urllib`` path could leak the response body on non-2xx replies and
  kept half-dead sockets around across failures).
- **streaming** — :meth:`HTTPService.stream` returns status + headers
  as soon as they arrive and an iterator over raw body chunks (SSE
  token passthrough for the fleet router).
- **redirects** — GET/HEAD follow up to 3 ``Location`` hops
  (``urlopen`` parity); other methods return the 3xx raw, because
  replaying a POST across a redirect is the caller's decision.
"""

from __future__ import annotations

import http.client
import json as _json
import random
import time
import urllib.parse
from dataclasses import dataclass
from typing import Any, Iterator, Optional

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.tracing import CLIENT, get_tracer

DEFAULT_CONNECT_TIMEOUT_S = 5.0
DEFAULT_READ_TIMEOUT_S = 30.0

# statuses a retry may help with: the upstream answered but couldn't
# serve (gateway errors / overload) — 4xx replies are the caller's bug
# and never retried
RETRYABLE_STATUSES = frozenset({502, 503, 504})

# methods safe to replay without caller opt-in (RFC 9110 §9.2.2)
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS"})

# the old urllib path auto-followed redirects; the http.client rewrite
# preserves that for SAFE methods only — replaying a POST across a 3xx
# is the caller's decision, not the client's
REDIRECT_STATUSES = frozenset({301, 302, 303, 307, 308})
REDIRECT_METHODS = frozenset({"GET", "HEAD"})
MAX_REDIRECTS = 3

# decorrelated-jitter backoff constants (AWS architecture-blog rule):
# sleep_n = min(cap, uniform(base, 3 * sleep_{n-1}))
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 2.0


@dataclass
class ServiceLog:
    """Typed outbound-call log entry (parity: service/logger.go:5-21)."""

    correlation_id: str
    service: str
    method: str
    uri: str
    status: int
    response_time_us: int
    attempts: int = 1

    def pretty_terminal(self) -> str:
        color = 32 if 0 < self.status < 400 else 31
        retry = f" ({self.attempts} attempts)" if self.attempts > 1 else ""
        return (
            f"\x1b[{color}m{self.status}\x1b[0m "
            f"{self.method:<7s} {self.uri} {self.response_time_us}µs "
            f"[svc {self.service}]{retry}"
        )

    def log_fields(self) -> dict[str, Any]:
        return {
            "correlation_id": self.correlation_id,
            "service": self.service,
            "method": self.method,
            "uri": self.uri,
            "status": self.status,
            "response_time_us": self.response_time_us,
            "attempts": self.attempts,
        }


class ServiceResponse:
    """Parity: service/response.go:5-17."""

    def __init__(self, status_code: int, body: bytes, headers: dict[str, str]):
        self.status_code = status_code
        self.body = body
        self.headers = headers

    def json(self) -> Any:
        return _json.loads(self.body.decode("utf-8") or "null")


class StreamingServiceResponse:
    """A response whose body is consumed incrementally: status + headers
    are available immediately; :meth:`iter_chunks` yields raw body bytes
    as the upstream produces them. The caller owns the connection and
    MUST exhaust the iterator or call :meth:`close` (both release it)."""

    def __init__(self, status_code: int, headers: dict[str, str],
                 resp: Any, conn: Any):
        self.status_code = status_code
        self.headers = headers
        self._resp = resp
        self._conn = conn
        self._closed = False

    def iter_chunks(self, size: int = 8192) -> Iterator[bytes]:
        # read1, not read: read(size) BLOCKS until `size` bytes (or EOF)
        # accumulate, which turned an SSE passthrough into an 8 KiB
        # store-and-forward buffer — every proxied token waited for the
        # whole stream on short responses. read1 returns as soon as the
        # socket has ANY bytes, so each upstream flush reaches the
        # client (and the router's resume journal) immediately.
        read1 = getattr(self._resp, "read1", None)
        try:
            while True:
                chunk = (
                    read1(size) if read1 is not None
                    else self._resp.read(size)
                )
                if not chunk:
                    break
                yield chunk
        finally:
            self.close()

    def read(self, budget_s: Optional[float] = None) -> bytes:
        """Drain the remaining body (non-streaming consumption).
        ``budget_s`` bounds the TOTAL drain time — callers draining an
        error body from an untrusted upstream must pass it, or a
        drip-fed body pins the thread (see :func:`_read_body`)."""
        try:
            if budget_s is None:
                return self._resp.read()
            return _read_body(self._resp, self._conn.sock, budget_s)
        finally:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._conn.close()
        except OSError:
            pass


class ServiceCallError(Exception):
    """The downstream never produced a usable reply. Carries the total
    elapsed time and how many attempts were burned — the fleet router's
    deadline budgeting and breaker accounting read both."""

    status_code = 502

    def __init__(self, service: str, uri: str, cause: Exception,
                 elapsed_s: float = 0.0, attempts: int = 1):
        super().__init__(
            f"call to service '{service}' failed after "
            f"{attempts} attempt(s) in {elapsed_s * 1000:.0f}ms: {cause}"
        )
        self.service = service
        self.uri = uri
        self.cause = cause
        self.elapsed_s = elapsed_s
        self.attempts = attempts


class _ConnectError(Exception):
    """Internal marker: the failure happened before the request was on
    the wire (always safe to retry, even for non-idempotent methods)."""

    def __init__(self, cause: Exception):
        super().__init__(str(cause))
        self.cause = cause


def _read_body(resp: Any, sock: Any, read_timeout: float) -> bytes:
    """Read a buffered response body under a TOTAL ``read_timeout``
    budget. Socket timeouts are per-``recv``, so a drip-fed body (one
    byte every few seconds — a broken or malicious upstream) would
    reset the clock forever and pin the calling thread; here the
    remaining budget shrinks the socket timeout before each ``read1``
    (at most one ``recv`` per call) and the read aborts when it hits
    zero. Streaming consumers (:meth:`HTTPService.stream`) are exempt
    by design — an SSE body is SUPPOSED to stay open."""
    deadline = time.perf_counter() + read_timeout
    read1 = getattr(resp, "read1", None)
    if read1 is None:  # non-buffered fake in tests: single bounded read
        return resp.read()
    chunks: list[bytes] = []
    while True:
        remaining = deadline - time.perf_counter()
        if remaining <= 0:
            raise TimeoutError(
                f"response body exceeded the {read_timeout}s read budget"
            )
        if sock is not None:
            sock.settimeout(remaining)
        chunk = read1(1 << 16)
        if not chunk:
            return b"".join(chunks)
        chunks.append(chunk)


def backoff_delays(retries: int, base: float = BACKOFF_BASE_S,
                   cap: float = BACKOFF_CAP_S) -> Iterator[float]:
    """Decorrelated-jitter delays: each sleep is drawn from
    ``uniform(base, 3 * previous)`` capped at ``cap`` — retry storms from
    many clients decorrelate instead of synchronizing into waves."""
    sleep = base
    for _ in range(retries):
        sleep = min(cap, random.uniform(base, max(base, sleep * 3)))
        yield sleep


class HTTPService:
    """A named downstream-service client (parity: service/new.go:18-23)."""

    def __init__(self, address: str, logger: Any, name: str = "",
                 timeout: float = DEFAULT_READ_TIMEOUT_S,
                 connect_timeout: Optional[float] = None,
                 read_timeout: Optional[float] = None,
                 retries: int = 0):
        if "://" not in address:
            address = "http://" + address
        self.address = address.rstrip("/")
        self.logger = logger
        self.name = name or self.address
        # back-compat: ``timeout`` is the legacy flat knob and seeds the
        # read timeout; the split knobs win when given explicitly
        self.timeout = timeout
        self.connect_timeout = (
            connect_timeout if connect_timeout is not None
            else min(DEFAULT_CONNECT_TIMEOUT_S, timeout)
        )
        self.read_timeout = read_timeout if read_timeout is not None else timeout
        self.retries = retries

    # -- the 10-method HTTP interface (parity: new.go:25-54) -----------------
    def get(self, path: str, params: Optional[dict] = None) -> ServiceResponse:
        return self.request("GET", path, params, None, None)

    def get_with_headers(self, path: str, params: Optional[dict], headers: dict) -> ServiceResponse:
        return self.request("GET", path, params, None, headers)

    def post(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self.request("POST", path, params, body, None)

    def post_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self.request("POST", path, params, body, headers)

    def put(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self.request("PUT", path, params, body, None)

    def put_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self.request("PUT", path, params, body, headers)

    def patch(self, path: str, params: Optional[dict] = None, body: Any = None) -> ServiceResponse:
        return self.request("PATCH", path, params, body, None)

    def patch_with_headers(self, path, params, body, headers) -> ServiceResponse:
        return self.request("PATCH", path, params, body, headers)

    def delete(self, path: str, body: Any = None) -> ServiceResponse:
        return self.request("DELETE", path, None, body, None)

    def delete_with_headers(self, path, body, headers) -> ServiceResponse:
        return self.request("DELETE", path, None, body, headers)

    # -- async variants -------------------------------------------------------
    # The sync methods block; calling them from an ``async def`` handler
    # would stall the server's event loop. Async handlers must use these.
    async def async_get(self, path: str, params: Optional[dict] = None) -> ServiceResponse:
        return await self._offload(self.get, path, params)

    async def async_post(self, path: str, params: Optional[dict] = None,
                         body: Any = None) -> ServiceResponse:
        return await self._offload(self.post, path, params, body)

    async def async_put(self, path: str, params: Optional[dict] = None,
                        body: Any = None) -> ServiceResponse:
        return await self._offload(self.put, path, params, body)

    async def async_patch(self, path: str, params: Optional[dict] = None,
                          body: Any = None) -> ServiceResponse:
        return await self._offload(self.patch, path, params, body)

    async def async_delete(self, path: str, body: Any = None) -> ServiceResponse:
        return await self._offload(self.delete, path, body)

    @staticmethod
    async def _offload(fn: Any, *args: Any) -> ServiceResponse:
        import asyncio
        import contextvars

        loop = asyncio.get_running_loop()
        call = contextvars.copy_context().run
        return await loop.run_in_executor(None, call, fn, *args)

    # -- internals (parity: createAndSendRequest, new.go:111-159) ------------
    def _resolve(self, path: str, params: Optional[dict]) -> tuple[str, str]:
        """Full display URI + the request target sent on the wire."""
        uri = self.address + "/" + path.lstrip("/")
        if params:
            uri += "?" + _encode_query(params)
        split = urllib.parse.urlsplit(uri)
        target = split.path or "/"
        if split.query:
            target += "?" + split.query
        return uri, target

    def _encode_body(self, body: Any, send_headers: dict) -> Optional[bytes]:
        if body is None:
            return None
        if isinstance(body, bytes):
            return body
        send_headers.setdefault("Content-Type", "application/json")
        return _json.dumps(body).encode("utf-8")

    def _open(self, connect_timeout: float,
              split: Optional[urllib.parse.SplitResult] = None,
              ) -> http.client.HTTPConnection:
        if split is None:
            split = urllib.parse.urlsplit(self.address)
        cls = (http.client.HTTPSConnection if split.scheme == "https"
               else http.client.HTTPConnection)
        return cls(split.hostname or "", split.port, timeout=connect_timeout)

    def _attempt(self, method: str, target: str, data: Optional[bytes],
                 headers: dict, connect_timeout: float,
                 read_timeout: float,
                 split: Optional[urllib.parse.SplitResult] = None,
                 ) -> tuple[int, bytes, dict[str, str]]:
        """One request on a fresh connection, closed whatever happens —
        an aborted attempt never leaks its socket or response body into
        the next one. ``split`` overrides the destination (redirect
        hops)."""
        conn = self._open(connect_timeout, split)
        try:
            try:
                conn.connect()
            except Exception as exc:
                raise _ConnectError(exc) from exc
            # connect succeeded: the remaining socket ops (send, response
            # head, body reads) run under the READ budget
            if conn.sock is not None:
                conn.sock.settimeout(read_timeout)
            conn.request(method, target, body=data, headers=headers)
            resp = conn.getresponse()
            payload = _read_body(resp, conn.sock, read_timeout)
            return resp.status, payload, dict(resp.getheaders())
        finally:
            conn.close()

    def _retry_loop(
        self,
        method: str,
        target: str,
        data: Optional[bytes],
        send_headers: dict,
        connect_t: float,
        read_t: float,
        budget: int,
        may_retry: bool,
        deadline_s: Optional[float],
        start: float,
    ) -> tuple[Optional[tuple[int, bytes, dict[str, str]]], int,
               Optional[Exception]]:
        """Attempt/backoff loop shared by every non-streaming call.
        Connect-phase failures replay even for non-idempotent methods
        (nothing was on the wire); post-connect failures and retryable
        statuses replay only when ``may_retry``. Returns
        ``(result-or-None, attempts, last_exception)``."""
        delays = backoff_delays(budget)
        attempts = 0
        last_exc: Optional[Exception] = None
        result: Optional[tuple[int, bytes, dict[str, str]]] = None
        while True:
            attempts += 1
            ct, rt = connect_t, read_t
            if deadline_s is not None:
                # the deadline is a TOTAL budget: each attempt's connect
                # and read windows shrink to what is left of it
                remaining = deadline_s - (time.perf_counter() - start)
                if remaining <= 0 and attempts > 1:
                    attempts -= 1  # this attempt never ran
                    break
                remaining = max(remaining, 0.001)
                ct, rt = min(ct, remaining), min(rt, remaining)
            try:
                result = self._attempt(
                    method, target, data, send_headers, ct, rt
                )
                last_exc = None
            except _ConnectError as exc:
                last_exc = exc.cause
            except Exception as exc:
                last_exc = exc
                if not may_retry:
                    break  # request may have executed: do not replay
            if result is not None and (
                result[0] not in RETRYABLE_STATUSES or not may_retry
            ):
                break
            delay = next(delays, None)
            if delay is None:
                break
            elapsed = time.perf_counter() - start
            if deadline_s is not None and elapsed + delay >= deadline_s:
                break  # budget exhausted: surface what we have
            time.sleep(delay)
            result = None
        return result, attempts, last_exc

    def request(
        self,
        method: str,
        path: str,
        params: Optional[dict] = None,
        body: Any = None,
        headers: Optional[dict] = None,
        *,
        connect_timeout: Optional[float] = None,
        read_timeout: Optional[float] = None,
        retries: Optional[int] = None,
        retryable: Optional[bool] = None,
        deadline_s: Optional[float] = None,
    ) -> ServiceResponse:
        """The generic call every helper delegates to, with per-call
        overrides for the timeout split and the retry budget.

        ``retryable=None`` applies the method rule (idempotent methods
        retry, POST/PATCH do not); ``True``/``False`` overrides it —
        the fleet router passes ``True`` for requests it KNOWS produced
        no client-visible effect yet. ``deadline_s`` bounds the total
        time across attempts including backoff sleeps."""
        method = method.upper()
        uri, target = self._resolve(path, params)
        send_headers = dict(headers or {})
        data = self._encode_body(body, send_headers)
        connect_t = connect_timeout if connect_timeout is not None else self.connect_timeout
        read_t = read_timeout if read_timeout is not None else self.read_timeout
        budget = retries if retries is not None else self.retries
        may_retry = (method in IDEMPOTENT_METHODS if retryable is None
                     else retryable)

        tracer = get_tracer()
        span = tracer.start_span(f"{method} {uri}", kind=CLIENT, activate=False)
        correlation_id = span.trace_id
        # downstream SERVER span must parent onto this CLIENT span
        send_headers.setdefault("traceparent", span.traceparent())
        send_headers.setdefault("X-Correlation-ID", correlation_id)

        start = time.perf_counter()
        result, attempts, last_exc = self._retry_loop(
            method, target, data, send_headers, connect_t, read_t,
            budget, may_retry, deadline_s, start,
        )
        if result is not None and result[0] in REDIRECT_STATUSES:
            try:
                uri, result = self._follow_redirects(
                    method, uri, result, data, send_headers,
                    connect_t, read_t,
                    deadline_left=(
                        None if deadline_s is None
                        else deadline_s - (time.perf_counter() - start)
                    ),
                )
            except Exception as exc:
                last_exc, result = exc, None
        elapsed_us = int((time.perf_counter() - start) * 1e6)
        if result is None:
            span.set_tag("error", last_exc)
            span.set_tag("attempts", attempts)
            span.end()
            self.logger.error(ServiceLog(
                correlation_id, self.name, method, uri, 0, elapsed_us,
                attempts=attempts,
            ))
            raise ServiceCallError(
                self.name, uri, last_exc or RuntimeError("request failed"),
                elapsed_s=elapsed_us / 1e6, attempts=attempts,
            ) from last_exc

        status, payload, resp_headers = result
        span.set_tag("http.status_code", status)
        span.set_tag("attempts", attempts)
        span.end()
        log_entry = ServiceLog(
            correlation_id, self.name, method, uri, status, elapsed_us,
            attempts=attempts,
        )
        if status >= 500:
            self.logger.error(log_entry)
        else:
            self.logger.info(log_entry)
        return ServiceResponse(status, payload, resp_headers)

    def _follow_redirects(
        self,
        method: str,
        uri: str,
        result: tuple[int, bytes, dict[str, str]],
        data: Optional[bytes],
        headers: dict,
        connect_t: float,
        read_t: float,
        deadline_left: Optional[float] = None,
    ) -> tuple[str, tuple[int, bytes, dict[str, str]]]:
        """Follow up to MAX_REDIRECTS Location hops for safe methods
        (``urlopen`` parity); everything else returns the 3xx raw.
        ``deadline_left`` is what remains of the caller's total budget
        — each hop's connect/read windows shrink with it, and an
        exhausted budget returns the last 3xx instead of hopping on."""
        hops = 0
        hop_start = time.perf_counter()
        while (result[0] in REDIRECT_STATUSES
               and method in REDIRECT_METHODS and hops < MAX_REDIRECTS):
            location = next(
                (v for k, v in result[2].items() if k.lower() == "location"),
                None,
            )
            if not location:
                break
            ct, rt = connect_t, read_t
            if deadline_left is not None:
                remaining = deadline_left - (time.perf_counter() - hop_start)
                if remaining <= 0:
                    break
                ct, rt = min(ct, remaining), min(rt, remaining)
            hops += 1
            uri = urllib.parse.urljoin(uri, location)
            split = urllib.parse.urlsplit(uri)
            target = (split.path or "/") + (
                "?" + split.query if split.query else ""
            )
            result = self._attempt(
                method, target, data, headers, connect_timeout=ct,
                read_timeout=rt, split=split,
            )
        return uri, result

    def stream(
        self,
        method: str,
        path: str,
        params: Optional[dict] = None,
        body: Any = None,
        headers: Optional[dict] = None,
        *,
        connect_timeout: Optional[float] = None,
        read_timeout: Optional[float] = None,
    ) -> StreamingServiceResponse:
        """Single-attempt streaming call: returns once the response HEAD
        arrives; the body is consumed through the returned object (SSE
        token passthrough). Retry policy is the CALLER's job — only it
        knows whether any chunk already reached its own client."""
        method = method.upper()
        uri, target = self._resolve(path, params)
        send_headers = dict(headers or {})
        data = self._encode_body(body, send_headers)
        connect_t = connect_timeout if connect_timeout is not None else self.connect_timeout
        read_t = read_timeout if read_timeout is not None else self.read_timeout

        tracer = get_tracer()
        span = tracer.start_span(f"{method} {uri}", kind=CLIENT, activate=False)
        send_headers.setdefault("traceparent", span.traceparent())
        send_headers.setdefault("X-Correlation-ID", span.trace_id)

        start = time.perf_counter()
        conn = self._open(connect_t)
        try:
            try:
                conn.connect()
            except Exception as exc:
                raise _ConnectError(exc) from exc
            if conn.sock is not None:
                conn.sock.settimeout(read_t)
            conn.request(method, target, body=data, headers=send_headers)
            resp = conn.getresponse()
        except Exception as exc:
            conn.close()
            elapsed_us = int((time.perf_counter() - start) * 1e6)
            cause = exc.cause if isinstance(exc, _ConnectError) else exc
            span.set_tag("error", cause)
            span.end()
            self.logger.error(ServiceLog(
                span.trace_id, self.name, method, uri, 0, elapsed_us
            ))
            raise ServiceCallError(
                self.name, uri, cause, elapsed_s=elapsed_us / 1e6
            ) from cause
        elapsed_us = int((time.perf_counter() - start) * 1e6)
        span.set_tag("http.status_code", resp.status)
        span.set_tag("streamed", True)
        span.end()
        self.logger.info(ServiceLog(
            span.trace_id, self.name, method, uri, resp.status, elapsed_us
        ))
        return StreamingServiceResponse(
            resp.status, dict(resp.getheaders()), resp, conn
        )

    def health_check(self) -> Health:
        """GET /.well-known/health on the downstream (TPU-native addition:
        the container aggregates registered services into its own health)."""
        try:
            resp = self.get("/.well-known/health")
            return Health(UP if resp.status_code == 200 else DOWN, {"host": self.address})
        except Exception as exc:
            return Health(DOWN, {"host": self.address, "error": str(exc)})


def _encode_query(params: dict) -> str:
    """Parity: service/new.go:161-176 — list values repeat the key."""
    pairs: list[tuple[str, str]] = []
    for key, value in params.items():
        if isinstance(value, (list, tuple)):
            pairs.extend((key, str(v)) for v in value)
        else:
            pairs.append((key, str(value)))
    return urllib.parse.urlencode(pairs)


def new_http_service(address: str, logger: Any, name: str = "") -> HTTPService:
    """Parity: service/new.go:56."""
    return HTTPService(address, logger, name=name)
