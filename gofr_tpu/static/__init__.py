"""Embedded static assets.

Parity: /root/reference/pkg/gofr/static/files.go:5-7 — a go:embed'd favicon
served at /favicon.ico. Here the icon ships inside the package and loads via
importlib.resources.
"""

from importlib import resources


def favicon() -> bytes:
    return resources.files(__package__).joinpath("favicon.ico").read_bytes()
