"""App core: construction, config load, tracer init, route registration,
concurrent server startup.

Parity: /root/reference/pkg/gofr/gofr.go —
- ``new()`` (:49): read config, build container, init tracer, prepare HTTP
  (port from HTTP_PORT|8000, :57-62) and gRPC (GRPC_PORT|9000, :65-70);
- ``new_cmd()`` (:76): config + container + tracer, no servers;
- ``run()`` (:90-126): default routes (health/favicon/catch-all, :102-107),
  servers started concurrently, blocks until shutdown;
- route helpers GET/PUT/POST/DELETE (:152-169), ``add_http_service``
  (:139-149), ``sub_command`` (:181), ``register_service`` for gRPC (:42).

Improvement over the reference (SURVEY.md §5 notes it lacks graceful
shutdown): SIGINT/SIGTERM drain servers and close the container.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from gofr_tpu.config import Config, EnvFileConfig
from gofr_tpu.container import Container
from gofr_tpu.context import Context
from gofr_tpu.handler import (
    Handler,
    catch_all_handler,
    adapter_load_handler,
    adapter_unload_handler,
    adapters_list_handler,
    anomalies_admin_handler,
    costmodel_admin_handler,
    dispatches_admin_handler,
    engine_admin_handler,
    favicon_handler,
    health_handler,
    kv_export_handler,
    make_endpoint,
    metrics_handler,
    overview_admin_handler,
    postmortem_list_handler,
    postmortem_trigger_handler,
    profiler_start_handler,
    profiler_status_handler,
    profiler_stop_handler,
    ready_handler,
    requests_admin_handler,
    slo_admin_handler,
    slo_budget_handler,
    tenants_admin_handler,
    timeseries_admin_handler,
)
from gofr_tpu.http.middleware import (
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    tracer_middleware,
)
from gofr_tpu.http.router import Router
from gofr_tpu.http.server import HTTPServer
from gofr_tpu.tracing import init_tracer

DEFAULT_HTTP_PORT = 8000  # parity: pkg/gofr/default.go:3-6
DEFAULT_GRPC_PORT = 9000


class App:
    def __init__(self, configs_dir: Optional[str] = None, cmd_app: bool = False):
        self.config: Config = EnvFileConfig(configs_dir or "./configs")
        self.container = Container(self.config)
        self.logger = self.container.logger
        self.tracer = init_tracer(self.config, self.logger)
        # exporter drops become a counter an alert can watch (the
        # exporter exists before the registry, so it is attached here)
        attach = getattr(self.tracer.exporter, "attach_metrics", None)
        if attach is not None:
            attach(self.container.metrics)
        self._cmd_app = cmd_app
        self._cmd_routes: list[tuple[str, Handler]] = []
        self._grpc_registrations: list[tuple[Any, Any]] = []
        self._grpc_json_services: dict[str, dict[str, Handler]] = {}
        self._grpc_json_stream_services: dict[str, dict[str, Handler]] = {}
        self._grpc_server: Optional[Any] = None
        self.http_server: Optional[HTTPServer] = None

        self.router = Router()
        if not cmd_app:
            self.http_port = int(self.config.get_or_default("HTTP_PORT", str(DEFAULT_HTTP_PORT)))
            self.grpc_port = int(self.config.get_or_default("GRPC_PORT", str(DEFAULT_GRPC_PORT)))
            # middleware chain, outermost first (parity: http/router.go:19-23)
            self.router.use(
                tracer_middleware,
                logging_middleware(self.logger),
                metrics_middleware(self.container.metrics),
                cors_middleware,
            )

    # -- route registration (parity: gofr.go:152-169) ------------------------
    def get(self, pattern: str, handler: Handler) -> None:
        self.add_route("GET", pattern, handler)

    def post(self, pattern: str, handler: Handler) -> None:
        self.add_route("POST", pattern, handler)

    def put(self, pattern: str, handler: Handler) -> None:
        self.add_route("PUT", pattern, handler)

    def patch(self, pattern: str, handler: Handler) -> None:
        self.add_route("PATCH", pattern, handler)

    def delete(self, pattern: str, handler: Handler) -> None:
        self.add_route("DELETE", pattern, handler)

    def add_route(self, method: str, pattern: str, handler: Handler) -> None:
        self.router.add(method, pattern, make_endpoint(handler, self.container))

    # -- inter-service clients (parity: gofr.go:139-149) ---------------------
    def add_http_service(self, name: str, address: str) -> None:
        from gofr_tpu.service import new_http_service

        self.container.services[name] = new_http_service(address, self.logger, name=name)

    # -- gRPC (parity: gofr.go:42-46) ----------------------------------------
    def register_service(self, add_to_server: Callable, servicer: Any) -> None:
        """Register a generated-stub gRPC service: ``add_to_server`` is the
        protoc-generated ``add_XServicer_to_server`` callable."""
        self._grpc_registrations.append((add_to_server, servicer))

    def register_json_service(
        self,
        service_name: str,
        methods: dict[str, Handler],
        stream_methods: Optional[dict[str, Handler]] = None,
    ) -> None:
        """Register a reflection-free JSON-over-gRPC service: each method is
        a transport-agnostic ``handler(ctx)`` (TPU-native addition for
        serving without protoc codegen). ``stream_methods`` handlers return
        an iterator; each item becomes one JSON message on a server stream
        (token decode, BASELINE.md config 4). A name appearing in both maps
        is rejected here, at registration time."""
        overlap = set(methods) & set(stream_methods or {})
        if overlap:
            raise ValueError(
                f"service '{service_name}' registers {sorted(overlap)} as both "
                "unary and streaming — a method must be one or the other"
            )
        if methods:
            self._grpc_json_services[service_name] = methods
        if stream_methods:
            self._grpc_json_stream_services[service_name] = stream_methods

    # -- CLI (parity: gofr.go:181, cmd.go:54-63) -----------------------------
    def sub_command(self, pattern: str, handler: Handler) -> None:
        self._cmd_routes.append((pattern, handler))

    # -- run ------------------------------------------------------------------
    def _install_default_routes(self) -> None:
        # parity: gofr.go:102-107
        self.router.add("GET", "/.well-known/health", make_endpoint(health_handler, self.container))
        self.router.add("GET", "/.well-known/ready", make_endpoint(ready_handler, self.container))
        self.router.add("GET", "/favicon.ico", make_endpoint(favicon_handler, self.container))
        self.router.add("GET", "/metrics", make_endpoint(metrics_handler, self.container))
        # device profiler admin surface (off the serving hot path)
        self.router.add("GET", "/admin/profiler",
                        make_endpoint(profiler_status_handler, self.container))
        self.router.add("POST", "/admin/profiler/start",
                        make_endpoint(profiler_start_handler, self.container))
        self.router.add("POST", "/admin/profiler/stop",
                        make_endpoint(profiler_stop_handler, self.container))
        # request flight recorder admin surface (telemetry.py)
        self.router.add("GET", "/admin/requests",
                        make_endpoint(requests_admin_handler, self.container))
        self.router.add("GET", "/admin/slo",
                        make_endpoint(slo_admin_handler, self.container))
        # SLO engine (slo.py): error budgets + burn-rate alerting; and
        # the bounded per-tenant usage sketch (telemetry.TenantLedger)
        self.router.add("GET", "/admin/slo/budget",
                        make_endpoint(slo_budget_handler, self.container))
        self.router.add("GET", "/admin/tenants",
                        make_endpoint(tenants_admin_handler, self.container))
        # engine introspection (tpu/introspect.py): the layer below the
        # flight recorder — engine state, boot/compile timeline, and the
        # device dispatch timeline
        self.router.add("GET", "/admin/engine",
                        make_endpoint(engine_admin_handler, self.container))
        self.router.add("GET", "/admin/dispatches",
                        make_endpoint(dispatches_admin_handler, self.container))
        # dispatch cost model (tpu/costmodel.py): cost sheets +
        # calibration + residuals, and the anomaly surface it feeds
        self.router.add("GET", "/admin/costmodel",
                        make_endpoint(costmodel_admin_handler, self.container))
        self.router.add("GET", "/admin/anomalies",
                        make_endpoint(anomalies_admin_handler, self.container))
        # telemetry timebase (timebase.py): retained metric history +
        # the one-page ops rollup; postmortem black box (postmortem.py)
        self.router.add("GET", "/admin/timeseries",
                        make_endpoint(timeseries_admin_handler, self.container))
        self.router.add("GET", "/admin/overview",
                        make_endpoint(overview_admin_handler, self.container))
        self.router.add("GET", "/admin/postmortem",
                        make_endpoint(postmortem_list_handler, self.container))
        self.router.add("POST", "/admin/postmortem",
                        make_endpoint(postmortem_trigger_handler, self.container))
        # cross-replica KV transfer (disaggregated prefill/decode):
        # peers pull cached paged-KV block tables by prompt hash
        self.router.add("GET", "/admin/kv/{hash}",
                        make_endpoint(kv_export_handler, self.container))
        self.router.add("GET", "/admin/adapters",
                        make_endpoint(adapters_list_handler, self.container))
        self.router.add("POST", "/admin/adapters",
                        make_endpoint(adapter_load_handler, self.container))
        self.router.add("DELETE", "/admin/adapters/{name}",
                        make_endpoint(adapter_unload_handler, self.container))
        self.router.set_not_found(make_endpoint(catch_all_handler, self.container))

    def run(self) -> None:
        """Blocking run (parity: gofr.go:90-126)."""
        if self._cmd_app:
            from gofr_tpu.cmd import run_cmd

            code = run_cmd(self)
            if code != 0:
                raise SystemExit(code)
            return
        self.start()
        stop = threading.Event()
        try:
            import signal

            signal.signal(signal.SIGTERM, lambda *_: stop.set())
        except (ValueError, OSError):
            pass  # not the main thread; SIGTERM keeps default handling
        try:
            stop.wait()
            self.logger.info("SIGTERM received, shutting down")
        except KeyboardInterrupt:
            self.logger.info("shutting down")
        finally:
            self.shutdown()

    def start(self) -> "App":
        """Start servers in background threads and return (test/bench shape;
        the reference achieves the same with goroutines + WaitGroup,
        gofr.go:109-125)."""
        self._install_default_routes()
        self.http_server = HTTPServer(self.router, self.http_port, self.logger)
        self.http_server.run_in_thread()
        if (
            self._grpc_registrations
            or self._grpc_json_services
            or self._grpc_json_stream_services
        ):
            from gofr_tpu.grpcx import GRPCServer

            self._grpc_server = GRPCServer(
                self.grpc_port,
                self.container,
                registrations=self._grpc_registrations,
                json_services=self._grpc_json_services,
                json_stream_services=self._grpc_json_stream_services,
            )
            self._grpc_server.start()
        return self

    def shutdown(self) -> None:
        # visible to in-flight stream teardown: asyncio acloses every
        # suspended response generator on shutdown, and those aborts
        # must not count as client_abort cancellations (no client left)
        self.container.closing = True
        fleet = getattr(self.container, "fleet", None)
        if fleet is not None:
            # graceful drain BEFORE the listener stops: admission closes
            # (new requests shed 503, readiness flips) while in-flight
            # requests finish through the still-running server
            timeout = float(
                self.config.get_or_default("FLEET_DRAIN_TIMEOUT_S", "10")
            )
            fleet.drain(timeout_s=timeout)
        if self.http_server:
            self.http_server.shutdown()
        if self._grpc_server:
            self._grpc_server.stop()
        self.container.close()
        self.tracer.shutdown()


def new(configs_dir: Optional[str] = None) -> App:
    """Parity: gofr.go:49."""
    return App(configs_dir=configs_dir)


def new_cmd(configs_dir: Optional[str] = None) -> App:
    """Parity: gofr.go:76."""
    return App(configs_dir=configs_dir, cmd_app=True)
