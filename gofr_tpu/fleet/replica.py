"""Replica bookkeeping + the health prober that decides rotation
membership.

Each engine replica is wrapped in a :class:`Replica`: its resilient
HTTP client (``service.py``), an outstanding-request counter (the
least-outstanding selection signal), a per-replica circuit breaker
(``fleet/breaker.py``), and the prober-maintained rotation state:

- ``healthy`` — in rotation, receives traffic.
- ``probation`` — recovering: the replica answered ready again after
  being out, but must string together ``probation_probes`` consecutive
  OK probes before traffic returns (a flapping replica — wedge,
  recover, wedge — never oscillates back into rotation on one good
  poll).
- ``out`` — readiness failed (connect error, 503 while booting,
  watchdog degraded/wedged); receives no traffic.

The prober (one named daemon thread per :class:`ReplicaSet`, joined on
``close()``) polls ``/.well-known/ready`` every ``probe_interval_s``
and — piggybacked on the same round — scrapes ``GET /admin/engine`` for
the saturation signals the admission layer sheds on: paged-KV free
blocks and batcher queue depth. Probes can optionally HEDGE: when
``hedge_ms`` > 0 a second probe fires if the first hasn't answered in
that window and the first reply wins — the p99 of a health check on a
busy replica stops deciding rotation membership.

Probe SCHEDULING is per-replica and jittered (``probe_jitter``, a
fraction of the interval): each replica draws its own next-due time
from an independent RNG, so a 16-replica fleet never fires 16 probe
threads + 16 engine scrapes in the same instant every interval — the
fleetsim harness measured the synchronized sweep putting every probe
of a round inside one 50 ms burst window at N=16, and the jittered
schedule spreading them across the whole interval (FLEETSIM artifact,
``hardening.probe_spread``).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import queue
import random
import threading
import time
from typing import Any, Optional

from gofr_tpu.fleet.breaker import CircuitBreaker
from gofr_tpu.service import HTTPService

HEALTHY = "healthy"
PROBATION = "probation"
OUT = "out"

# numeric gauge encoding for gofr_tpu_router_replica_state{replica}
STATE_VALUES = {OUT: 0, PROBATION: 1, HEALTHY: 2}


def affinity_order(key: str, names: list[str]) -> list[str]:
    """Rendezvous (highest-random-weight) order of ``names`` for
    ``key``: stable under membership churn — removing one replica only
    remaps the conversations that lived on it, never the whole fleet."""
    def score(name: str) -> int:
        digest = hashlib.md5(f"{key}|{name}".encode("utf-8")).digest()
        return int.from_bytes(digest[:8], "big")

    return sorted(names, key=score, reverse=True)


class Replica:
    def __init__(
        self,
        name: str,
        address: str,
        logger: Any,
        connect_timeout: float = 2.0,
        read_timeout: float = 30.0,
        breaker: Optional[CircuitBreaker] = None,
    ):
        self.name = name
        self.address = address
        self.client = HTTPService(
            address, logger, name=name,
            connect_timeout=connect_timeout, read_timeout=read_timeout,
        )
        self.breaker = breaker or CircuitBreaker()
        self._lock = threading.Lock()
        self._outstanding = 0
        self.state = HEALTHY  # optimistic: the prober corrects within a round
        self.ok_streak = 0
        self.fail_streak = 0
        self.probes = 0
        self.last_probe_error = ""
        self.saturated = False
        self.engine: Optional[dict[str, Any]] = None
        self.last_kv_rejects: Optional[int] = None  # prober-only state
        self.kv_starved = False  # KV-only component of `saturated`
        # restart awareness: the last boot_id the ready probe reported.
        # A CHANGED id means a new process answers at this address (the
        # supervisor respawned it after a crash/SIGKILL) — a first-class
        # `restarting` passage through probation: cold caches, empty
        # pools, and (with JOURNAL_DIR) a WAL rehydration behind it.
        self.boot_id: Optional[str] = None
        self.restarts = 0
        self.restarting = False
        # disaggregated serving: the role the replica ADVERTISES on
        # /admin/engine (FLEET_ROLE). "mixed" — the default, and what a
        # replica that advertises nothing gets — serves every tier, so
        # role routing can never shrink the fleet below today's
        # behavior. Sticky across probe failures (an out-of-rotation
        # replica keeps its last-known role for when it returns).
        self.role = "mixed"

    # -- outstanding-request accounting (selection signal) -------------------
    def mark_dispatch(self) -> int:
        with self._lock:
            self._outstanding += 1
            return self._outstanding

    def mark_done(self) -> int:
        with self._lock:
            self._outstanding = max(0, self._outstanding - 1)
            return self._outstanding

    @property
    def outstanding(self) -> int:
        # deliberately lock-free: reading an int attribute is atomic
        # under the GIL, and this property sits inside the router's
        # selection loop — N replicas × every request. Taking the
        # writer lock here measurably serialized selection against
        # dispatch accounting at fleet scale (the fleetsim's
        # selection microbench is the regression watch).
        return self._outstanding

    def snapshot(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "address": self.address,
            "state": self.state,
            "role": self.role,
            "outstanding": self.outstanding,
            "saturated": self.saturated,
            "probes": self.probes,
            "ok_streak": self.ok_streak,
            "fail_streak": self.fail_streak,
            "last_probe_error": self.last_probe_error or None,
            "boot_id": self.boot_id,
            "restarts": self.restarts,
            "restarting": self.restarting,
            "breaker": self.breaker.snapshot(),
            "engine": self.engine,
        }


class ReplicaSet:
    """The fleet membership + its prober thread."""

    def __init__(
        self,
        replicas: list[Replica],
        logger: Any,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 1.0,
        probe_jitter: float = 0.2,
        hedge_ms: float = 0.0,
        out_after: int = 2,
        probation_probes: int = 3,
        saturation_queue: int = 64,
        affinity_max_skew: int = 4,
        on_state_change: Optional[Any] = None,
    ):
        self.replicas = replicas
        self.logger = logger
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        # jitter as a FRACTION of the interval (0 = the old synchronized
        # sweep, clamped below 1 so the schedule can never stall): each
        # replica's next probe lands uniformly in interval*(1±jitter),
        # drawn from a per-replica RNG — de-synchronization is the
        # thundering-herd fix that is load-bearing at N=16
        self.probe_jitter = max(0.0, min(0.9, probe_jitter))
        self.hedge_ms = hedge_ms
        self.out_after = max(1, out_after)
        self.probation_probes = max(1, probation_probes)
        self.saturation_queue = saturation_queue
        self.affinity_max_skew = max(0, affinity_max_skew)
        self._on_state_change = on_state_change
        # fired when a probe detects a REBORN process (boot_id changed);
        # the router counts it on gofr_tpu_router_replica_restarts_total
        self._on_restart: Optional[Any] = None
        self._stop = threading.Event()
        # round-robin tie-break for equal-outstanding picks; a C-level
        # counter, not a locked int (see candidates())
        self._rr = itertools.count(1)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "ReplicaSet":
        self._thread = threading.Thread(
            target=self._probe_loop, name="gofr-fleet-probe", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- selection ------------------------------------------------------------
    def by_name(self, name: str) -> Optional[Replica]:
        for replica in self.replicas:
            if replica.name == name:
                return replica
        return None

    def candidates(self, affinity_key: str = "",
                   exclude: Optional[set[str]] = None,
                   role: Optional[str] = None) -> list[Replica]:
        """Dispatch order for one attempt round: in-rotation replicas,
        affinity target first (rendezvous on the conversation key —
        that replica holds the paged-KV blocks of the prefix), the rest
        by least-outstanding with a rotating tie-break. Affinity yields
        to load once the preferred replica runs ``affinity_max_skew``
        more outstanding requests than the least-loaded sibling — a
        popular shared prefix must not funnel the whole fleet onto one
        replica. ``exclude`` drops replicas already tried this
        request. ``role`` restricts to that tier (role-advertising
        replicas plus ``mixed`` ones); an empty tier returns [] and the
        CALLER falls back to role-free selection — role config narrows
        preference, never capacity."""
        eligible = [
            r for r in self.replicas
            if r.state == HEALTHY and (exclude is None or r.name not in exclude)
        ]
        if role is not None:
            eligible = [r for r in eligible if r.role in (role, "mixed")]
        if not eligible:
            return []
        # lock-free rotating tie-break: itertools.count.__next__ is a
        # single C call (GIL-atomic), where the old lock+int pair made
        # every selection of every request serialize on one mutex
        rotate = next(self._rr)
        # outstanding is SNAPSHOTTED once per selection: the sort and
        # the affinity-skew check below must agree on one consistent
        # view, and re-reading the live counters per comparison paid
        # n_replicas extra attribute reads per request for a value
        # that may shift mid-sort anyway
        loads = {r.name: r.outstanding for r in eligible}
        order = {r.name: i for i, r in enumerate(eligible)}
        eligible.sort(
            key=lambda r: (loads[r.name],
                           (order[r.name] + rotate) % len(order))
        )
        if affinity_key:
            ranked = affinity_order(affinity_key, [r.name for r in eligible])
            preferred = next(
                r for r in eligible if r.name == ranked[0]
            )
            least_loaded = loads[eligible[0].name]
            if loads[preferred.name] <= least_loaded + self.affinity_max_skew:
                eligible.sort(key=lambda r: 0 if r.name == preferred.name else 1)
        return eligible

    def in_rotation(self) -> list[Replica]:
        return [r for r in self.replicas if r.state == HEALTHY]

    def all_saturated(self) -> bool:
        """True when every in-rotation replica reports KV/queue
        saturation — the admission layer sheds instead of queueing."""
        rotation = self.in_rotation()
        return bool(rotation) and all(r.saturated for r in rotation)

    def snapshot(self) -> dict[str, Any]:
        return {
            "probe_interval_s": self.probe_interval_s,
            "probe_jitter": self.probe_jitter,
            "out_after": self.out_after,
            "probation_probes": self.probation_probes,
            "replicas": [r.snapshot() for r in self.replicas],
        }

    # -- probing --------------------------------------------------------------
    def next_probe_delays(self, rng: random.Random,
                          initial: bool = False) -> float:
        """One replica's delay until its next probe. Jitter draws
        uniformly from ``interval * (1 ± probe_jitter)`` per round —
        each replica's independent RNG decorrelates phases over time,
        so even replicas that START aligned drift apart. The INITIAL
        delay spreads only across the JITTER window ``[0,
        jitter*interval)``: a freshly booted 16-replica router must not
        open with one synchronized probe burst, but it must also still
        learn real rotation state within ≈ one round — replicas boot
        optimistically healthy, and a long stagger would stretch the
        window in which a dead replica keeps taking traffic."""
        spread = self.probe_jitter * self.probe_interval_s
        if self.probe_jitter <= 0.0:
            return 0.0 if initial else self.probe_interval_s
        if initial:
            return rng.random() * spread
        return self.probe_interval_s - spread + rng.random() * 2 * spread

    def _probe_loop(self) -> None:
        """One probe thread PER REPLICA per round: a serial sweep would
        make failure-detection latency O(n_replicas × probe_timeout) —
        two hard-down replicas must not delay taking a third, newly
        wedged one out of rotation. A replica whose previous probe is
        still running (stuck in its connect timeout) is skipped, never
        double-probed; each replica's state machine thus stays
        single-threaded. Scheduling is per-replica with decorrelated
        jitter (:meth:`next_probe_delays`)."""
        pending: dict[str, threading.Thread] = {}
        # per-replica RNGs, seeded off the replica name: deterministic
        # for a given fleet spec (tests can reason about it) while still
        # independent streams across replicas
        rngs = {
            r.name: random.Random(f"gofr-probe-jitter|{r.name}")
            for r in self.replicas
        }
        now = time.monotonic()
        due = {
            r.name: now + self.next_probe_delays(rngs[r.name], initial=True)
            for r in self.replicas
        }
        while not self._stop.is_set():
            now = time.monotonic()
            for replica in self.replicas:
                if now < due[replica.name]:
                    continue
                due[replica.name] = now + self.next_probe_delays(
                    rngs[replica.name]
                )
                previous = pending.get(replica.name)
                if previous is not None and previous.is_alive():
                    continue
                thread = threading.Thread(
                    target=self._probe_guarded, args=(replica,),
                    name=f"gofr-fleet-probe-{replica.name}", daemon=True,
                )
                pending[replica.name] = thread
                thread.start()
            wake = min(due.values()) - time.monotonic() if due else (
                self.probe_interval_s
            )
            self._stop.wait(min(max(wake, 0.001), self.probe_interval_s))
        for thread in pending.values():
            thread.join(timeout=self.probe_timeout_s * 2 + 1.0)

    def _probe_guarded(self, replica: Replica) -> None:
        try:
            self.probe_once(replica)
        except Exception as exc:
            # a prober crash would silently freeze rotation state
            self.logger.errorf(
                "fleet probe of %s failed: %r", replica.name, exc
            )

    def probe_once(self, replica: Replica) -> bool:
        """One probe round for ``replica``: readiness decides rotation,
        the piggybacked engine scrape updates saturation. Returns the
        readiness verdict (also applied to the state machine)."""
        ok, detail, recovering, boot_id = self._ready_probe(replica)
        replica.probes += 1
        replica.last_probe_error = "" if ok else detail
        self._apply_probe(replica, ok, recovering=recovering,
                          boot_id=boot_id)
        if ok:
            self._scrape_engine(replica)
        else:
            replica.saturated = False
            replica.engine = None
        return ok

    def _ready_probe(
        self, replica: Replica
    ) -> tuple[bool, str, bool, Optional[str]]:
        if self.hedge_ms and self.hedge_ms > 0:
            return self._hedged_ready(replica)
        return self._ready_once(replica)

    @staticmethod
    def _recovering_verdict(body: bytes) -> bool:
        """Does a 503 ready body say the engine is COMING BACK (an
        active wedge-recovery incident) rather than hard-down? Keys on
        the engine state and the recovery evidence block handler.py
        attaches; terminal verdicts (exhausted/hung) are NOT coming
        back."""
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return False
        if not isinstance(payload, dict):
            return False
        if payload.get("state") == "recovering":
            return True
        recovery = payload.get("recovery")
        return isinstance(recovery, dict) and recovery.get("state") in (
            "recovering", "waiting_backoff"
        )

    @staticmethod
    def _ready_boot_id(body: bytes) -> Optional[str]:
        """The ready 200 body's process identity (None on replicas that
        predate it — restart detection then simply stays off)."""
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(payload, dict):
            return None
        boot_id = payload.get("boot_id")
        return boot_id if isinstance(boot_id, str) and boot_id else None

    def _ready_once(
        self, replica: Replica
    ) -> tuple[bool, str, bool, Optional[str]]:
        try:
            resp = replica.client.request(
                "GET", "/.well-known/ready",
                connect_timeout=self.probe_timeout_s,
                read_timeout=self.probe_timeout_s,
                retries=0,
            )
        except Exception as exc:
            return False, str(exc), False, None
        if resp.status_code == 200:
            return True, "", False, self._ready_boot_id(resp.body)
        detail = resp.body.decode("utf-8", "replace")[:200]
        return (
            False, f"ready {resp.status_code}: {detail}",
            self._recovering_verdict(resp.body), None,
        )

    def _hedged_ready(
        self, replica: Replica
    ) -> tuple[bool, str, bool, Optional[str]]:
        """Hedged readiness read: fire a second probe if the first is
        slower than ``hedge_ms``; first answer wins. The loser's reply
        is discarded (its connection closes with its thread)."""
        results: "queue.Queue[tuple[bool, str, bool, Optional[str]]]" = (
            queue.Queue()
        )

        def attempt() -> None:
            results.put(self._ready_once(replica))

        first = threading.Thread(
            target=attempt, name="gofr-fleet-hedge", daemon=True
        )
        first.start()
        try:
            return results.get(timeout=self.hedge_ms / 1000.0)
        except queue.Empty:
            pass
        second = threading.Thread(
            target=attempt, name="gofr-fleet-hedge", daemon=True
        )
        second.start()
        try:
            return results.get(timeout=self.probe_timeout_s * 2 + 1.0)
        except queue.Empty:
            return False, "hedged probe timed out", False, None

    def _scrape_engine(self, replica: Replica) -> None:
        """Saturation signals off ``GET /admin/engine``: paged-KV free
        blocks and batcher queue depth. A router fronting replicas
        without an engine (or with admin auth) keeps saturated=False —
        shedding then falls back to the router's own in-flight cap."""
        try:
            resp = replica.client.request(
                "GET", "/admin/engine",
                connect_timeout=self.probe_timeout_s,
                read_timeout=self.probe_timeout_s,
                retries=0,
            )
            if resp.status_code != 200:
                replica.saturated = False
                return
            data = json.loads(resp.body.decode("utf-8"))
        except Exception:
            replica.saturated = False
            return
        if isinstance(data, dict) and isinstance(data.get("data"), dict):
            data = data["data"]  # the framework envelope
        engine: dict[str, Any] = {
            "state": (data.get("engine") or {}).get("state"),
            "queue_depth": data.get("queue_depth"),
        }
        # disaggregated serving: adopt the advertised role (FLEET_ROLE)
        # and carry the KV-transfer ledger onto /admin/fleet
        role = data.get("role")
        if role in ("prefill", "decode", "mixed"):
            replica.role = role
        engine["role"] = replica.role
        engine["kv_transfer"] = (
            data.get("kv_transfer")
            if isinstance(data.get("kv_transfer"), dict) else None
        )
        # overload-brownout level (0 normal): piggybacked for the
        # /admin/fleet/overview rollup — a fleet-wide brownout is an
        # incident headline, not something to scrape N replicas for
        brownout = data.get("brownout")
        engine["brownout_level"] = (
            brownout.get("level") if isinstance(brownout, dict) else None
        )
        # cost-model watchtower: anomaly totals + worst residual EMA
        # piggybacked off engine_snapshot()'s `costmodel` block, so
        # /admin/fleet/overview can name the replica blowing its
        # predictions without a second fan-out scrape
        cm = data.get("costmodel")
        engine["anomalies"] = (
            cm.get("anomalies_total") if isinstance(cm, dict) else None
        )
        engine["worst_residual_ema"] = (
            cm.get("worst_residual_ema") if isinstance(cm, dict) else None
        )
        # SLO + tenant headlines ride the same scrape: the fleet
        # overview aggregates burn/budget/top-talkers router-side with
        # zero extra endpoints (same piggyback discipline as costmodel)
        engine["slo"] = (
            data.get("slo") if isinstance(data.get("slo"), dict) else None
        )
        engine["tenants"] = (
            data.get("tenants")
            if isinstance(data.get("tenants"), dict) else None
        )
        kv = data.get("kv_blocks") or {}
        engine["kv_free"] = kv.get("free")
        engine["kv_cached"] = kv.get("cached")
        engine["kv_total"] = kv.get("total")
        engine["kv_exhausted_rejects"] = kv.get("kv_exhausted_rejects")
        replica.engine = engine
        # KV starvation keys on the replica's OWN verdicts: a rising
        # kv_exhausted_rejects counter means admissions are being
        # rejected RIGHT NOW (the pool's authoritative signal — free/
        # cached counts can't tell pinned-shared cache blocks from
        # evictable ones). Starvation then sustains while blocks stay
        # visibly scarce (free == 0 with live decodes) and clears when
        # free blocks appear or every decode has finished (an idle
        # cache is wholly evictable).
        rejects = int(kv.get("kv_exhausted_rejects") or 0)
        delta = (rejects - replica.last_kv_rejects
                 if replica.last_kv_rejects is not None else 0)
        replica.last_kv_rejects = rejects
        free = int(kv.get("free") or 0)
        active = int(kv.get("active") or 0)
        if delta > 0:
            replica.kv_starved = True
        elif free > 0 or active == 0:
            replica.kv_starved = False
        # else: sticky on the KV-ONLY flag while blocks stay scarce —
        # never on the composite `saturated`, or a one-time queue spike
        # would latch as KV starvation for as long as the warm cache
        # keeps the free list empty (its routine steady state)
        depth = engine["queue_depth"] or 0
        queue_full = self.saturation_queue > 0 and depth >= self.saturation_queue
        replica.saturated = replica.kv_starved or queue_full

    def _apply_probe(self, replica: Replica, ok: bool,
                     recovering: bool = False,
                     boot_id: Optional[str] = None) -> None:
        """The probation state machine. Runs on the prober thread only
        (plus tests), so plain attribute writes are safe.

        ``recovering``: the failed probe's 503 body carried an ACTIVE
        wedge-recovery incident — the replica is coming back, not
        hard-down. It parks in PROBATION (no traffic, but the router's
        stream-resume path may target it, and re-entry needs only the
        usual ok-probe streak) instead of dropping to OUT.

        ``boot_id``: the ready 200 body's process identity. A CHANGED
        id means a supervisor respawned the process (connection-refused
        then reborn): a first-class ``restarting`` passage — even a
        replica that never visibly failed a probe (killed and restarted
        inside one probe interval) re-enters through the probation
        window, because the NEW process has cold caches, empty pools,
        and possibly a WAL rehydration behind its ready verdict. The
        restart is counted (``on_restart`` hook → the router's
        gofr_tpu_router_replica_restarts_total) and ``restarting``
        stays visible on /admin/fleet until the replica walks back to
        HEALTHY."""
        was = replica.state
        if ok:
            reborn = (
                boot_id is not None
                and replica.boot_id is not None
                and boot_id != replica.boot_id
            )
            if boot_id is not None:
                replica.boot_id = boot_id
            if reborn:
                replica.restarts += 1
                replica.restarting = True
                replica.state = PROBATION
                replica.ok_streak = 0
                self._note_restart(replica)
            replica.ok_streak += 1
            replica.fail_streak = 0
            if replica.state == OUT:
                replica.state = PROBATION
                replica.ok_streak = 1
            if (replica.state == PROBATION
                    and replica.ok_streak >= self.probation_probes):
                replica.state = HEALTHY
            if replica.state == HEALTHY:
                replica.restarting = False
        else:
            replica.fail_streak += 1
            replica.ok_streak = 0
            if recovering:
                if replica.state == OUT or (
                    replica.state == HEALTHY
                    and replica.fail_streak >= self.out_after
                ):
                    replica.state = PROBATION
                # PROBATION holds: a replica mid-recovery never demotes
                # to hard-out on the strength of its own progress report
            elif replica.state == PROBATION or (
                replica.fail_streak >= self.out_after
            ):
                replica.state = OUT
        if was != replica.state and self._on_state_change is not None:
            try:
                self._on_state_change(replica, was, replica.state)
            except Exception:  # gofrlint: disable=GFL006 — hook must not kill the prober
                pass

    def _note_restart(self, replica: Replica) -> None:
        if self._on_restart is None:
            return
        try:
            self._on_restart(replica)
        except Exception:  # gofrlint: disable=GFL006 — hook must not kill the prober
            pass
