"""Admission control for the fleet front door: per-tenant token-bucket
quotas (redis-backed when the container has redis, in-memory otherwise)
and the shed decisions that keep the router's queue bounded.

Every deny carries a ``Retry-After`` hint so well-behaved clients back
off instead of hammering: for quota denials it is the exact refill time
of the next token; for saturation/in-flight sheds it is the configured
``retry_after_s`` coarse hint.

The redis backing makes quotas FLEET-WIDE: N router processes fronting
the same replicas share one bucket per tenant (key
``fleet:quota:<tenant>``, a hash of ``tokens`` + ``ts``). The
read-modify-write is not atomic across routers — a race can admit one
extra request per colliding pair — which is the right trade for a
quota (a rate hint, not a ledger); redis failures fail OPEN to the
in-memory bucket so a cache outage never takes admission down with it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

# bounded tenant map: beyond this, new tenants share one overflow bucket
# (same rationale as METRICS_MAX_SERIES — scanner traffic must not grow
# resident memory unboundedly)
MAX_TENANTS = 10_000
OVERFLOW_TENANT = "_overflow"


class TokenBucket:
    """Classic token bucket on the monotonic clock: ``rate`` tokens/s
    refill toward ``capacity``; :meth:`take` is lock-guarded arithmetic
    only (admission sits on the hot path)."""

    def __init__(self, rate: float, capacity: float):
        self.rate = rate
        self.capacity = capacity
        self._tokens = capacity
        self._updated = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> tuple[bool, float]:
        """(admitted, retry_after_s). ``retry_after_s`` is 0 when
        admitted, else the time until ``n`` tokens will exist."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.capacity, self._tokens + (now - self._updated) * self.rate
            )
            self._updated = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            needed = n - self._tokens
            return False, needed / self.rate if self.rate > 0 else 60.0

    def peek(self) -> float:
        with self._lock:
            now = time.monotonic()
            return min(
                self.capacity, self._tokens + (now - self._updated) * self.rate
            )


class QuotaTable:
    """Per-tenant buckets. ``rate_rps`` <= 0 disables quotas entirely
    (every take admits)."""

    def __init__(self, rate_rps: float, burst: float,
                 redis: Optional[Any] = None, logger: Optional[Any] = None,
                 metrics: Optional[Any] = None):
        self.rate_rps = rate_rps
        self.burst = burst if burst > 0 else max(1.0, 2 * rate_rps)
        self._redis = redis
        self._logger = logger
        # outage-window tracking: the first failure of an outage logs
        # (once — a dead redis must not flood the log at request rate),
        # recovery logs the all-clear and RE-ARMS the next outage's
        # first-failure log. Every fail-open take also counts on
        # gofr_tpu_router_quota_fallback_total, so a silent redis
        # outage — quotas quietly per-process instead of fleet-wide —
        # is visible on /admin/fleet and alertable, not just a single
        # log line scrolled away days ago.
        self._redis_down = False
        self._fallbacks = 0
        self._fallback_counter = (
            metrics.counter(
                "gofr_tpu_router_quota_fallback_total",
                "quota decisions that failed open to the per-process "
                "bucket because the redis backend was unavailable",
            )
            if metrics is not None else None
        )
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._denied = 0
        self._admitted = 0

    @property
    def enabled(self) -> bool:
        return self.rate_rps > 0

    def take(self, tenant: str) -> tuple[bool, float]:
        if not self.enabled:
            return True, 0.0
        if self._redis is not None:
            verdict = self._take_redis(tenant)
            if verdict is not None:
                self._count(verdict[0])
                return verdict
            self._note_fallback()
        ok, retry_after = self._bucket(tenant).take()
        self._count(ok)
        return ok, retry_after

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "rate_rps": self.rate_rps,
                "burst": self.burst,
                "backend": "redis" if self._redis is not None else "memory",
                "redis_down": self._redis_down,
                "fallbacks": self._fallbacks,
                "tenants": len(self._buckets),
                "admitted": self._admitted,
                "denied": self._denied,
            }

    # -- internals ------------------------------------------------------------
    def _count(self, admitted: bool) -> None:
        with self._lock:
            if admitted:
                self._admitted += 1
            else:
                self._denied += 1

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= MAX_TENANTS:
                    tenant = OVERFLOW_TENANT
                    bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = TokenBucket(self.rate_rps, self.burst)
                    self._buckets[tenant] = bucket
            return bucket

    def _take_redis(self, tenant: str) -> Optional[tuple[bool, float]]:
        """Fleet-wide bucket in redis; ``None`` = backend unavailable
        (caller falls back to the in-memory bucket: fail open). Two
        pipelined round trips per take (read both fields, write both +
        TTL) — this sits on the admission hot path, so five sequential
        RTTs would tax every admitted request. One RTT would need
        server-side scripting (EVAL), which the in-tree miniredis does
        not speak."""
        key = f"fleet:quota:{tenant}"
        try:
            # wall clock ON PURPOSE: the timestamp is shared across
            # router processes, whose monotonic clocks are unrelated
            now = time.time()  # gofrlint: wall-clock — cross-process bucket refill timestamp
            raw_tokens, raw_ts = self._redis.pipeline().hget(
                key, "tokens"
            ).hget(key, "ts").execute()
            tokens = _as_float(raw_tokens, self.burst)
            ts = _as_float(raw_ts, now)
            tokens = min(self.burst, tokens + max(0.0, now - ts) * self.rate_rps)
            if tokens >= 1.0:
                admitted, tokens, retry_after = True, tokens - 1.0, 0.0
            else:
                admitted = False
                retry_after = (1.0 - tokens) / self.rate_rps
            ttl = max(60, int(self.burst / max(self.rate_rps, 0.001)) + 60)
            # idle tenants expire instead of accreting forever
            self._redis.pipeline().hset(key, "tokens", repr(tokens)).hset(
                key, "ts", repr(now)
            ).expire(key, ttl).execute()
            if self._redis_down:
                self._redis_down = False
                if self._logger is not None:
                    self._logger.infof(
                        "fleet quota redis backend recovered; quotas are "
                        "fleet-wide again"
                    )
            return admitted, retry_after
        except Exception as exc:
            if not self._redis_down and self._logger is not None:
                self._logger.errorf(
                    "fleet quota redis backend failed (%r); failing open "
                    "to per-process buckets until it recovers", exc
                )
            self._redis_down = True
            return None

    def _note_fallback(self) -> None:
        with self._lock:
            self._fallbacks += 1
        if self._fallback_counter is not None:
            self._fallback_counter.inc()


def _as_float(value: Any, default: float) -> float:
    """Redis replies arrive as str/bytes/None depending on the client
    path; the bucket math wants a float either way."""
    if value is None:
        return default
    if isinstance(value, bytes):
        value = value.decode("utf-8", "replace")
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


def tenant_of(request: Any, trust_tenant_header: bool = False) -> str:
    """The quota subject of a request: the API key (``Authorization``
    value, HASHED — the tenant string lands in route records,
    ``/admin/fleet``, and redis keys, none of which may carry secret
    material), else a shared anonymous bucket.

    The client-supplied ``X-Tenant`` header is honored only when the
    operator opted in (``FLEET_TRUST_TENANT_HEADER=on``, for routers
    behind an authenticating gateway that STAMPS the header): trusted
    by default it would let any rate-limited client mint a fresh full
    bucket per request by randomizing the header."""
    if trust_tenant_header:
        tenant = request.header("X-Tenant")
        if tenant:
            return tenant
    auth = request.header("Authorization")
    if auth:
        import hashlib

        digest = hashlib.sha256(auth.encode("utf-8")).hexdigest()
        return "key-" + digest[:16]
    return "anonymous"
