"""Admission control for the fleet front door: per-tenant token-bucket
quotas (redis-backed when the container has redis, in-memory otherwise)
and the shed decisions that keep the router's queue bounded.

Every deny carries a ``Retry-After`` hint so well-behaved clients back
off instead of hammering: for quota denials it is the exact refill time
of the next token; for saturation/in-flight sheds it is the configured
``retry_after_s`` coarse hint.

The redis backing makes quotas FLEET-WIDE: N router processes fronting
the same replicas share one bucket per tenant (key
``fleet:quota:<tenant>``, a hash of ``tokens`` + ``ts``). The
read-modify-write is not atomic across routers — a race can admit one
extra request per colliding pair — which is the right trade for a
quota (a rate hint, not a ledger); redis failures fail OPEN to the
in-memory bucket so a cache outage never takes admission down with it.

The redis path is hot-key protected by a short-TTL local lease cache
(``cache_ttl_s`` > 0, ``FLEET_QUOTA_CACHE_TTL_S``): instead of two
pipelined redis round trips per request per tenant, the table leases a
small batch of tokens (≈ ``rate * ttl``) from the shared bucket once
per TTL window and admits locally from the lease; a denial verdict is
likewise cached for the window. Leased-but-unused tokens from an
expired lease are credited back on the tenant's next sync, so the
fleet-wide accounting error is bounded by one lease per router per TTL
— while a Zipf-skewed tenant mix (one tenant dominating traffic) stops
hammering one redis key once per request. The fleetsim harness
measured one redis sync (= two pipelined round trips) per request
without the cache and a small fraction of that with it (FLEETSIM
artifact, ``hardening.quota.syncs_per_request``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

# bounded tenant map: beyond this, new tenants share one overflow bucket
# (same rationale as METRICS_MAX_SERIES — scanner traffic must not grow
# resident memory unboundedly)
MAX_TENANTS = 10_000
OVERFLOW_TENANT = "_overflow"


class TokenBucket:
    """Classic token bucket on the monotonic clock: ``rate`` tokens/s
    refill toward ``capacity``; :meth:`take` is lock-guarded arithmetic
    only (admission sits on the hot path)."""

    def __init__(self, rate: float, capacity: float):
        self.rate = rate
        self.capacity = capacity
        self._tokens = capacity
        self._updated = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> tuple[bool, float]:
        """(admitted, retry_after_s). ``retry_after_s`` is 0 when
        admitted, else the time until ``n`` tokens will exist."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.capacity, self._tokens + (now - self._updated) * self.rate
            )
            self._updated = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            needed = n - self._tokens
            return False, needed / self.rate if self.rate > 0 else 60.0

    def peek(self) -> float:
        with self._lock:
            now = time.monotonic()
            return min(
                self.capacity, self._tokens + (now - self._updated) * self.rate
            )


class _Lease:
    """One tenant's short-lived local slice of the fleet-wide redis
    bucket: ``tokens`` admits locally until ``expires`` (monotonic);
    a lease with no tokens is a CACHED DENIAL (``retry_after`` is the
    hint minted at sync time, counted down as the window ages)."""

    __slots__ = ("tokens", "expires", "retry_after")

    def __init__(self, tokens: float, expires: float,
                 retry_after: float = 0.0):
        self.tokens = tokens
        self.expires = expires
        self.retry_after = retry_after


class QuotaTable:
    """Per-tenant buckets. ``rate_rps`` <= 0 disables quotas entirely
    (every take admits)."""

    def __init__(self, rate_rps: float, burst: float,
                 redis: Optional[Any] = None, logger: Optional[Any] = None,
                 metrics: Optional[Any] = None, cache_ttl_s: float = 0.0):
        self.rate_rps = rate_rps
        self.burst = burst if burst > 0 else max(1.0, 2 * rate_rps)
        self._redis = redis
        self._logger = logger
        # hot-key protection (module docstring): 0 = off, every take is
        # a redis round trip (the pre-cache behavior, and the unit-test
        # baseline the fleetsim A/B measures against)
        self.cache_ttl_s = max(0.0, cache_ttl_s)
        self._leases: dict[str, _Lease] = {}
        self._credit: dict[str, float] = {}  # expired-lease give-back
        self._redis_syncs = 0
        self._cache_hits = 0
        # outage-window tracking: the first failure of an outage logs
        # (once — a dead redis must not flood the log at request rate),
        # recovery logs the all-clear and RE-ARMS the next outage's
        # first-failure log. Every fail-open take also counts on
        # gofr_tpu_router_quota_fallback_total, so a silent redis
        # outage — quotas quietly per-process instead of fleet-wide —
        # is visible on /admin/fleet and alertable, not just a single
        # log line scrolled away days ago.
        self._redis_down = False
        self._fallbacks = 0
        self._fallback_counter = (
            metrics.counter(
                "gofr_tpu_router_quota_fallback_total",
                "quota decisions that failed open to the per-process "
                "bucket because the redis backend was unavailable",
            )
            if metrics is not None else None
        )
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._denied = 0
        self._admitted = 0

    @property
    def enabled(self) -> bool:
        return self.rate_rps > 0

    def take(self, tenant: str) -> tuple[bool, float]:
        if not self.enabled:
            return True, 0.0
        if self._redis is not None:
            verdict = self._take_lease(tenant)
            if verdict is None:
                verdict = self._take_redis(tenant)
            if verdict is not None:
                self._count(verdict[0])
                return verdict
            self._note_fallback()
        ok, retry_after = self._bucket(tenant).take()
        self._count(ok)
        return ok, retry_after

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "rate_rps": self.rate_rps,
                "burst": self.burst,
                "backend": "redis" if self._redis is not None else "memory",
                "redis_down": self._redis_down,
                "fallbacks": self._fallbacks,
                "tenants": len(self._buckets),
                "admitted": self._admitted,
                "denied": self._denied,
                "cache_ttl_s": self.cache_ttl_s,
                "redis_syncs": self._redis_syncs,
                "cache_hits": self._cache_hits,
            }

    # -- internals ------------------------------------------------------------
    def _count(self, admitted: bool) -> None:
        with self._lock:
            if admitted:
                self._admitted += 1
            else:
                self._denied += 1

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= MAX_TENANTS:
                    tenant = OVERFLOW_TENANT
                    bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = TokenBucket(self.rate_rps, self.burst)
                    self._buckets[tenant] = bucket
            return bucket

    def _take_lease(self, tenant: str) -> Optional[tuple[bool, float]]:
        """Serve a take from the tenant's local lease when one is live
        (no redis round trip); ``None`` = no usable lease, sync with
        redis. Lock-guarded arithmetic only. An EXPIRED lease moves its
        unused tokens into the credit ledger so the next sync gives
        them back to the fleet-wide bucket."""
        if self.cache_ttl_s <= 0:
            return None
        with self._lock:
            lease = self._leases.get(tenant)
            if lease is None:
                return None
            now = time.monotonic()
            if now >= lease.expires:
                del self._leases[tenant]
                if lease.tokens > 0:
                    self._credit[tenant] = (
                        self._credit.get(tenant, 0.0) + lease.tokens
                    )
                    if len(self._credit) > MAX_TENANTS:
                        # bounded like the bucket/lease maps: a churning
                        # tenant population must not grow the credit
                        # ledger forever (the popped sliver refills via
                        # the rate)
                        self._credit.pop(next(iter(self._credit)))
                return None
            if lease.tokens >= 1.0:
                lease.tokens -= 1.0
                self._cache_hits += 1
                return True, 0.0
            if lease.retry_after > 0:
                # cached denial: the hint counts DOWN as the window ages
                # (re-serving the sync-time value would push well-behaved
                # clients ever further out)
                self._cache_hits += 1
                remaining = lease.retry_after - (
                    self.cache_ttl_s - (lease.expires - now)
                )
                return False, max(0.05, remaining)
            return None

    def _lease_target(self) -> float:
        """Tokens to lease per sync: a TTL window's worth at the full
        rate — but AT LEAST one token (at realistic per-tenant rates
        ``rate * ttl`` is fractional and a sub-1.0 lease can never
        admit, which silently disabled the cache in the first fleetsim
        runs) — bounded so one router can never hoard the whole
        burst. The hoard bound gets the same ≥1 floor: clamping below
        a whole token (tiny bursts) would re-open the fractional-lease
        hole the floor exists to close."""
        want = max(1.0, self.rate_rps * self.cache_ttl_s)
        return min(want, max(1.0, self.burst / 2.0))

    def _take_redis(self, tenant: str) -> Optional[tuple[bool, float]]:
        """Fleet-wide bucket in redis; ``None`` = backend unavailable
        (caller falls back to the in-memory bucket: fail open). Two
        pipelined round trips per take (read both fields, write both +
        TTL) — this sits on the admission hot path, so five sequential
        RTTs would tax every admitted request. One RTT would need
        server-side scripting (EVAL), which the in-tree miniredis does
        not speak. With ``cache_ttl_s`` > 0 this sync also LEASES a
        batch of tokens into the local cache (debited here, admitted
        locally by :meth:`_take_lease`) and credits back any expired
        lease's unused remainder."""
        key = f"fleet:quota:{tenant}"
        with self._lock:
            credit = self._credit.pop(tenant, 0.0)
        try:
            # wall clock ON PURPOSE: the timestamp is shared across
            # router processes, whose monotonic clocks are unrelated
            now = time.time()  # gofrlint: wall-clock — cross-process bucket refill timestamp
            raw_tokens, raw_ts = self._redis.pipeline().hget(
                key, "tokens"
            ).hget(key, "ts").execute()
            with self._lock:
                self._redis_syncs += 1
            tokens = _as_float(raw_tokens, self.burst)
            ts = _as_float(raw_ts, now)
            tokens = min(
                self.burst,
                tokens + max(0.0, now - ts) * self.rate_rps + credit,
            )
            leased = 0.0
            if tokens >= 1.0:
                admitted, tokens, retry_after = True, tokens - 1.0, 0.0
                if self.cache_ttl_s > 0:
                    leased = min(tokens, self._lease_target())
                    tokens -= leased
            else:
                admitted = False
                retry_after = (1.0 - tokens) / self.rate_rps
            ttl = max(60, int(self.burst / max(self.rate_rps, 0.001)) + 60)
            # idle tenants expire instead of accreting forever
            self._redis.pipeline().hset(key, "tokens", repr(tokens)).hset(
                key, "ts", repr(now)
            ).expire(key, ttl).execute()
            # the lease installs only AFTER the write-back landed: a
            # redis failure between read and write falls open (caller
            # gets None), and a lease installed early would be PHANTOM
            # tokens — admitted locally for a whole TTL window but
            # never debited fleet-wide, over-admitting past the
            # documented one-per-colliding-pair bound (and the except
            # path's credit restore would double-count whatever had
            # already flowed into it)
            if self.cache_ttl_s > 0:
                with self._lock:
                    prev = self._leases.get(tenant)
                    if prev is not None and prev.tokens > 0:
                        # a concurrent sync for the SAME tenant landed
                        # while this one round-tripped: both debited a
                        # lease batch from the shared bucket, so an
                        # overwrite would strand the loser's tokens —
                        # debited in redis, never admitted, never
                        # credited. Merge instead: the combined lease
                        # is bounded by one extra batch, and every
                        # debited token stays spendable.
                        leased += prev.tokens
                    self._leases[tenant] = _Lease(
                        leased, time.monotonic() + self.cache_ttl_s,
                        retry_after=retry_after if not admitted else 0.0,
                    )
                    if len(self._leases) > MAX_TENANTS:
                        # same bound rationale as the bucket map: scanner
                        # traffic must not grow resident memory forever —
                        # but an evicted lease's unused tokens were
                        # debited from the shared bucket, so they move
                        # to the credit ledger, never into the void
                        evicted = next(iter(self._leases))
                        old = self._leases.pop(evicted)
                        if old.tokens > 0:
                            self._credit[evicted] = (
                                self._credit.get(evicted, 0.0) + old.tokens
                            )
                        if len(self._credit) > MAX_TENANTS:
                            # the credit ledger gets the same cap; the
                            # popped sliver of tokens refills via the
                            # rate anyway — bounded memory wins over
                            # perfect accounting at scanner scale
                            self._credit.pop(next(iter(self._credit)))
            if self._redis_down:
                self._redis_down = False
                if self._logger is not None:
                    self._logger.infof(
                        "fleet quota redis backend recovered; quotas are "
                        "fleet-wide again"
                    )
            return admitted, retry_after
        except Exception as exc:
            if credit > 0:
                # the give-back never happened; keep it for the next sync
                with self._lock:
                    self._credit[tenant] = (
                        self._credit.get(tenant, 0.0) + credit
                    )
            if not self._redis_down and self._logger is not None:
                self._logger.errorf(
                    "fleet quota redis backend failed (%r); failing open "
                    "to per-process buckets until it recovers", exc
                )
            self._redis_down = True
            return None

    def _note_fallback(self) -> None:
        with self._lock:
            self._fallbacks += 1
        if self._fallback_counter is not None:
            self._fallback_counter.inc()


def _as_float(value: Any, default: float) -> float:
    """Redis replies arrive as str/bytes/None depending on the client
    path; the bucket math wants a float either way."""
    if value is None:
        return default
    if isinstance(value, bytes):
        value = value.decode("utf-8", "replace")
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


def tenant_of(request: Any, trust_tenant_header: bool = False) -> str:
    """The quota subject of a request: the API key (``Authorization``
    value, HASHED — the tenant string lands in route records,
    ``/admin/fleet``, and redis keys, none of which may carry secret
    material), else a shared anonymous bucket.

    The client-supplied ``X-Tenant`` header is honored only when the
    operator opted in (``FLEET_TRUST_TENANT_HEADER=on``, for routers
    behind an authenticating gateway that STAMPS the header): trusted
    by default it would let any rate-limited client mint a fresh full
    bucket per request by randomizing the header."""
    if trust_tenant_header:
        tenant = request.header("X-Tenant")
        if tenant:
            return tenant
    auth = request.header("Authorization")
    if auth:
        import hashlib

        digest = hashlib.sha256(auth.encode("utf-8")).hexdigest()
        return "key-" + digest[:16]
    return "anonymous"
