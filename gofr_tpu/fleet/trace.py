"""Fleet-wide trace assembly: one request's causal timeline across
processes.

PRs 1/3/4 built deep single-process observability; the fleet then grew
routers, failover attempts, KV donors, and mid-stream resume hunts —
and no surface could show one request's PATH across those processes.
This module joins the evidence the hop-correlation layer leaves behind:

- the router's route record (``FleetRouter.records``) keyed by the
  fleet-wide ``X-Gofr-Request-Id``;
- each attempt's replica-side FlightRecord, whose ``origin`` block
  (router id, attempt index, resume-from event id — stamped off the
  ``X-Gofr-Hop`` header at admission) says exactly which route-record
  attempt caused it;
- the KV-transfer ledgers on both ends (the donor's ``served_recent``
  and the receiver's ``pulls_recent`` rings on ``/admin/engine``),
  stamped with the same id.

:func:`assemble` is PURE — dicts in, dict out, no I/O, no clock — so
bench.py can measure it and tests can drive it with fuzzed garbage.
:func:`gather_evidence` does the scraping (each attempt replica's
``/admin/requests?request_id=`` and the involved replicas'
``/admin/engine`` ledgers, over the same unauthenticated replica
clients the prober uses). Every scrape failure degrades the trace to
``partial: true`` with the gap named in ``evidence_gaps`` — a trace
assembled while a replica is mid-restart is partial WITH evidence,
never a 500.

The latency decomposition answers the triage question directly: of the
end-to-end ``elapsed_ms`` the router measured, how much was router
overhead (admission + selection + failed attempts), replica queue wait,
device TTFT, and stream delivery. The same stages back the
``gofr_tpu_router_hop_seconds{stage}`` histogram in aggregate.
"""

from __future__ import annotations

from typing import Any, Optional

# bounded scrape page: a trace joins at most this many flight records
# per replica (a request causes one record per attempt it landed there)
_FLIGHTS_PER_REPLICA = 10


def assemble(
    request_id: str,
    route_record: dict[str, Any],
    flights: Optional[dict[str, list]] = None,
    transfers: Optional[list] = None,
    evidence_gaps: Optional[list] = None,
) -> dict[str, Any]:
    """Join one route record with its replica-side evidence into the
    causal timeline ``GET /admin/fleet/trace/<id>`` serves.

    ``flights`` maps replica name -> that replica's flight-record dicts
    for this request id (newest first, as ``/admin/requests`` returns
    them); ``transfers`` is the merged KV-ledger evidence;
    ``evidence_gaps`` names every scrape that failed. All three default
    to empty — an offline assembly over just the route record is valid
    (and is what the microbench measures)."""
    flights = flights or {}
    transfers = transfers or []
    gaps = list(evidence_gaps or [])
    attempts_in = route_record.get("attempts")
    if not isinstance(attempts_in, list):
        attempts_in = []
        gaps.append("route record carries no attempts list")
    attempts: list[dict[str, Any]] = []
    for index, entry in enumerate(attempts_in):
        if not isinstance(entry, dict):
            gaps.append(f"attempt {index}: malformed route entry")
            continue
        replica = entry.get("replica")
        merged = {
            "index": index,
            "kind": "resume" if entry.get("resume_from") is not None
            else "attempt",
            "flight": _match_flight(
                flights.get(replica) or [], route_record, index
            ),
        }
        merged.update(
            {k: v for k, v in entry.items() if not str(k).startswith("_")}
        )
        attempts.append(merged)
    served = next(
        (a for a in attempts if a.get("status") == 200 and a["flight"]),
        None,
    )
    for attempt in attempts:
        if attempt.get("status") == 200 and attempt["flight"] is None:
            replica = attempt.get("replica") or "?"
            gaps.append(
                f"attempt {attempt['index']}: no flight record scraped "
                f"from {replica} (ring evicted, replica restarted, or "
                "scrape failed)"
            )
    return {
        "request_id": request_id,
        "router": {
            k: route_record.get(k)
            for k in (
                "router_id", "ts", "method", "path", "tenant", "status",
                "outcome", "retries", "resumes", "stream", "resumable",
                "role", "kv_donor", "elapsed_ms",
            )
        },
        "attempts": attempts,
        "transfers": transfers,
        "latency": _decompose(route_record, served),
        "partial": bool(gaps),
        "evidence_gaps": gaps,
    }


def _match_flight(candidates: list, route_record: dict[str, Any],
                  index: int) -> Optional[dict[str, Any]]:
    """The flight record this route-record attempt caused: its origin
    block names this router and this attempt index (the hop stamp,
    round-tripped through the replica's contextvar). Fuzz-safe: any
    malformed candidate is skipped, never raised on."""
    router_id = route_record.get("router_id")
    fallback = None
    for flight in candidates:
        if not isinstance(flight, dict):
            continue
        origin = flight.get("origin")
        if not isinstance(origin, dict):
            continue
        if router_id is not None and origin.get("router") != router_id:
            continue
        if origin.get("attempt") == index:
            return flight
        if fallback is None:
            fallback = flight
    # a single-candidate scrape with a mismatched/absent attempt index
    # is still far better evidence than nothing — but only when the
    # route record has exactly one attempt to confuse it with
    if fallback is not None and len(route_record.get("attempts") or []) == 1:
        return fallback
    return None


def _decompose(route_record: dict[str, Any],
               served: Optional[dict[str, Any]]) -> dict[str, Any]:
    """Per-stage latency split of the router's end-to-end elapsed:
    router overhead (admission, selection, failed attempts, resume
    hunts), replica queue wait, device TTFT net of queue, and stream
    delivery (the remainder). Fields are None when the evidence that
    would pin them is missing — a partial trace decomposes partially,
    it does not invent numbers."""
    total = route_record.get("elapsed_ms")
    out: dict[str, Any] = {
        "total_ms": total,
        "router_overhead_ms": None,
        "replica_queue_ms": None,
        "device_ttft_ms": None,
        "stream_ms": None,
    }
    if not isinstance(total, (int, float)):
        return out
    upstream = 0.0
    for entry in route_record.get("attempts") or []:
        if isinstance(entry, dict) and isinstance(
            entry.get("elapsed_ms"), (int, float)
        ):
            upstream += entry["elapsed_ms"]
    out["router_overhead_ms"] = round(max(0.0, total - upstream), 1)
    flight = (served or {}).get("flight") or {}
    queue_s = flight.get("queue_wait_s")
    ttft_s = flight.get("ttft_s")
    if isinstance(queue_s, (int, float)):
        out["replica_queue_ms"] = round(queue_s * 1000, 1)
    if isinstance(ttft_s, (int, float)):
        net = ttft_s - (queue_s if isinstance(queue_s, (int, float)) else 0.0)
        out["device_ttft_ms"] = round(max(0.0, net) * 1000, 1)
        consumed = out["router_overhead_ms"] + (
            out["replica_queue_ms"] or 0.0
        ) + out["device_ttft_ms"]
        out["stream_ms"] = round(max(0.0, total - consumed), 1)
    return out


def gather_evidence(fleet: Any, request_id: str,
                    route_record: dict[str, Any],
                    timeout_s: float = 1.0) -> dict[str, Any]:
    """Scrape the replica-side evidence for one route record: flight
    records from every replica the attempts name, KV-transfer ledger
    entries from those replicas plus the named donor. Uses the same
    unauthenticated replica admin clients the prober uses (the fleet
    runs on a trusted segment). Returns the ``assemble`` keyword set;
    every failure becomes an ``evidence_gaps`` entry, never an
    exception — partial-with-evidence is the contract."""
    by_name = {r.name: r for r in fleet.replica_set.replicas}
    names: list[str] = []
    for entry in route_record.get("attempts") or []:
        if isinstance(entry, dict):
            replica = entry.get("replica")
            if replica and replica not in names:
                names.append(replica)
    donor = route_record.get("kv_donor")
    ledger_names = list(names)
    if donor and donor not in ledger_names:
        ledger_names.append(donor)
    flights: dict[str, list] = {}
    transfers: list[dict[str, Any]] = []
    gaps: list[str] = []
    for name in names:
        replica = by_name.get(name)
        if replica is None:
            gaps.append(f"{name}: replica no longer in the fleet")
            continue
        try:
            flights[name] = _scrape_flights(replica, request_id, timeout_s)
        except Exception as exc:
            gaps.append(f"{name}: flight scrape failed ({exc})")
    for name in ledger_names:
        replica = by_name.get(name)
        if replica is None:
            if name == donor:
                gaps.append(f"{name}: donor no longer in the fleet")
            continue
        try:
            transfers.extend(
                _scrape_transfers(replica, request_id, timeout_s)
            )
        except Exception as exc:
            gaps.append(f"{name}: transfer-ledger scrape failed ({exc})")
    return {
        "flights": flights, "transfers": transfers, "evidence_gaps": gaps,
    }


def _scrape_flights(replica: Any, request_id: str,
                    timeout_s: float) -> list[dict[str, Any]]:
    data = _admin_get(
        replica,
        f"/admin/requests?request_id={request_id}"
        f"&limit={_FLIGHTS_PER_REPLICA}",
        timeout_s,
    )
    requests = data.get("requests")
    return requests if isinstance(requests, list) else []


def _scrape_transfers(replica: Any, request_id: str,
                      timeout_s: float) -> list[dict[str, Any]]:
    data = _admin_get(replica, "/admin/engine", timeout_s)
    ledgers = data.get("kv_transfer")
    if not isinstance(ledgers, dict):
        return []
    out: list[dict[str, Any]] = []
    for side, key in (("donor", "served_recent"), ("receiver", "pulls_recent")):
        for entry in ledgers.get(key) or []:
            if (
                isinstance(entry, dict)
                and entry.get("request_id") == request_id
            ):
                out.append({"replica": replica.name, "side": side, **entry})
    return out


def _admin_get(replica: Any, target: str, timeout_s: float) -> dict[str, Any]:
    """One bounded replica admin GET, unwrapping the framework's
    ``{"data": ...}`` envelope (same shape the prober's engine scrape
    handles). Raises on any non-200/parse failure — the caller turns
    that into an evidence gap."""
    import json

    resp = replica.client.request(
        "GET", target,
        connect_timeout=timeout_s, read_timeout=timeout_s, retries=0,
    )
    if resp.status_code != 200:
        raise RuntimeError(f"HTTP {resp.status_code}")
    data = json.loads(resp.body.decode("utf-8"))
    if isinstance(data, dict) and isinstance(data.get("data"), dict):
        data = data["data"]
    if not isinstance(data, dict):
        raise RuntimeError("unexpected response shape")
    return data
