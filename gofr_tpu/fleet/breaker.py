"""Per-replica circuit breaker: consecutive-failure open, timed
half-open probe, close on probe success.

The breaker answers a different question than the health prober
(``fleet/replica.py``): the prober asks "does the replica SAY it is
ready", the breaker asks "did it actually SERVE when we last tried".
A replica can pass readiness probes while failing real requests (a
wedged device tunnel still answers host-side HTTP), so rotation
membership requires both signals.

States and transitions (the classic three-state machine):

- ``closed`` — traffic flows; ``failure_threshold`` CONSECUTIVE
  failures trip it to ``open`` (one success resets the streak).
- ``open`` — traffic is refused locally for ``cooldown_s``; the first
  :meth:`try_acquire` after the cooldown flips to ``half_open`` and is
  admitted as the single probe request.
- ``half_open`` — exactly one in-flight probe; success closes the
  breaker, failure re-opens it (and restarts the cooldown).

All clocks are monotonic; all state is lock-guarded and the lock is
never held across I/O (gofrlint GFL002/GFL004).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# the truthy grant try_acquire returns when admitting the caller AS the
# half-open probe — only a success reported with ``probe=True`` may
# close the breaker (a stale success from a request dispatched before
# the trip must not)
PROBE = "probe"

# numeric gauge encoding for gofr_tpu_router_breaker_state{replica}
STATE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0  # monotonic mark of the last trip
        self._probe_in_flight = False
        self._transitions = 0

    # -- admission ------------------------------------------------------------
    def try_acquire(self) -> Any:
        """May a request be dispatched through this breaker right now?
        Returns ``False`` (refused), ``True`` (normal traffic), or the
        truthy :data:`PROBE` grant — the caller was admitted as the ONE
        half-open probe and must report its outcome with
        ``record_success(probe=True)`` / :meth:`record_failure`."""
        notify: Optional[tuple[str, str]] = None
        with self._lock:
            allowed: Any = False
            if self._state == CLOSED:
                allowed = True
            elif self._state == OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    notify = self._transition_locked(HALF_OPEN)
                    self._probe_in_flight = True
                    allowed = PROBE
            else:  # HALF_OPEN: one probe at a time
                if not self._probe_in_flight:
                    self._probe_in_flight = True
                    allowed = PROBE
        self._notify(notify)
        return allowed

    # -- outcomes -------------------------------------------------------------
    def record_success(self, probe: bool = False) -> None:
        """``probe=True`` only from the caller whose ``try_acquire``
        returned :data:`PROBE`. Successes without the probe grant reset
        the failure streak but never close an OPEN or HALF_OPEN breaker
        — they are from requests dispatched BEFORE the trip (or long
        streams finishing), and letting stale evidence bypass the
        cooldown + single-probe discipline would flood traffic back
        onto a replica whose recent failures are fresher truth."""
        notify: Optional[tuple[str, str]] = None
        with self._lock:
            self._consecutive_failures = 0
            if probe:
                self._probe_in_flight = False
                if self._state == HALF_OPEN:
                    notify = self._transition_locked(CLOSED)
        self._notify(notify)

    def record_failure(self) -> None:
        notify: Optional[tuple[str, str]] = None
        with self._lock:
            self._consecutive_failures += 1
            self._probe_in_flight = False
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                notify = self._transition_locked(OPEN)
                self._opened_at = time.monotonic()
        self._notify(notify)

    # -- introspection --------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_s": self.cooldown_s,
                "transitions": self._transitions,
            }
            if self._state == OPEN:
                out["cooldown_remaining_s"] = round(max(
                    0.0,
                    self.cooldown_s - (time.monotonic() - self._opened_at),
                ), 3)
            return out

    # -- internals ------------------------------------------------------------
    def _transition_locked(self, to: str) -> tuple[str, str]:
        was = self._state
        self._state = to
        self._transitions += 1
        return was, to

    def _notify(self, edge: Optional[tuple[str, str]]) -> None:
        """Run the transition callback OUTSIDE the lock (it increments
        metrics, which take their own locks — GFL004)."""
        if edge is not None and self._on_transition is not None:
            try:
                self._on_transition(*edge)
            except Exception:  # gofrlint: disable=GFL006 — metrics callback must never poison breaker state
                pass
