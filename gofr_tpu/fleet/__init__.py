"""Fleet front door: a thin router process that fronts N engine
replicas and stays correct when they misbehave.

``wire_fleet(app)`` turns a plain ``gofr_tpu.new()`` app into the
router: it reads the ``FLEET_*`` config keys, builds the
:class:`~gofr_tpu.fleet.replica.ReplicaSet` (+ health prober), the
:class:`~gofr_tpu.fleet.admission.QuotaTable` (redis-backed when the
container has redis), and the
:class:`~gofr_tpu.fleet.router.FleetRouter`, registers the forwarded
serving routes plus ``GET /admin/fleet``, and hangs the router on
``container.fleet`` so readiness (``handler.py``) and graceful
shutdown (``app.py``) see it. ``tools/router.py`` is the process
entrypoint.

Config keys (all optional except ``FLEET_REPLICAS``; see
docs/advanced-guide/fleet.md for the full table):

- ``FLEET_REPLICAS`` — comma list of replica base URLs, optionally
  named: ``r0=http://host:8001,r1=http://host:8002`` (unnamed entries
  get ``r0``, ``r1``, ... in order).
- routing: ``FLEET_RETRIES`` (2), ``FLEET_DEADLINE_S`` (30),
  ``FLEET_CONNECT_TIMEOUT_S`` (2), ``FLEET_READ_TIMEOUT_S`` (30),
  ``FLEET_AFFINITY`` (on), ``FLEET_AFFINITY_MAX_SKEW`` (4).
- health: ``FLEET_PROBE_INTERVAL_S`` (1), ``FLEET_PROBE_TIMEOUT_S``
  (1), ``FLEET_PROBE_JITTER`` (0.2 — decorrelated per-replica jitter
  as a fraction of the interval; 0 restores the synchronized sweep),
  ``FLEET_PROBE_HEDGE_MS`` (0 = off), ``FLEET_OUT_AFTER`` (2),
  ``FLEET_PROBATION_PROBES`` (3).
- breaker: ``FLEET_BREAKER_THRESHOLD`` (5),
  ``FLEET_BREAKER_COOLDOWN_S`` (5).
- admission: ``FLEET_QUOTA_RPS`` (0 = off), ``FLEET_QUOTA_BURST``
  (2×rps), ``FLEET_QUOTA_CACHE_TTL_S`` (0.05 — short-TTL local lease
  cache over the redis bucket, the hot-key fix; 0 = a redis sync —
  two pipelined round trips — per request),
  ``FLEET_TRUST_TENANT_HEADER`` (off),
  ``FLEET_MAX_INFLIGHT`` (256), ``FLEET_SATURATION_QUEUE`` (64),
  ``FLEET_RETRY_AFTER_S`` (1).
- drain: ``FLEET_DRAIN_TIMEOUT_S`` (10).
- tracing: ``FLEET_TRACE_SCRAPE_TIMEOUT_S`` (1 — per-replica budget
  for the evidence scrapes behind ``GET /admin/fleet/trace/<id>``; a
  replica that cannot answer within it becomes an ``evidence_gaps``
  entry on a partial trace, not a stalled request).
- ``FLEET_ROUTES`` — the forwarded surface, comma-separated
  ``METHOD /path`` pairs (default: the OpenAI serving surface +
  ``/generate`` + ``/infer``).
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "CircuitBreaker", "FleetRouter", "QuotaTable", "Replica",
    "ReplicaSet", "affinity_order", "parse_replicas", "wire_fleet",
]

_EXPORTS = {
    "QuotaTable": "gofr_tpu.fleet.admission",
    "CircuitBreaker": "gofr_tpu.fleet.breaker",
    "Replica": "gofr_tpu.fleet.replica",
    "ReplicaSet": "gofr_tpu.fleet.replica",
    "affinity_order": "gofr_tpu.fleet.replica",
    "FleetRouter": "gofr_tpu.fleet.router",
}


def __getattr__(name):  # PEP 562: kvwire importers (every replica's
    # pull path) must not pay for the router stack
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module 'gofr_tpu.fleet' has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(module), name)

DEFAULT_ROUTES = (
    "POST /v1/completions,POST /v1/chat/completions,POST /v1/embeddings,"
    "GET /v1/models,POST /generate,POST /infer"
)


def parse_replicas(spec: str) -> list[tuple[str, str]]:
    """``FLEET_REPLICAS`` → ``[(name, url), ...]``. Entries are URLs or
    ``name=url``; unnamed entries are named ``r<index>``."""
    out: list[tuple[str, str]] = []
    seen: set[str] = set()
    for i, chunk in enumerate(spec.split(",")):
        chunk = chunk.strip()
        if not chunk:
            continue
        if "=" in chunk.split("://", 1)[0]:
            name, _, url = chunk.partition("=")
            name = name.strip()
        else:
            name, url = f"r{i}", chunk
        url = url.strip()
        if not url:
            raise ValueError(f"FLEET_REPLICAS entry '{chunk}' has no URL")
        if name in seen:
            raise ValueError(f"FLEET_REPLICAS names replica '{name}' twice")
        seen.add(name)
        out.append((name, url))
    return out


def wire_fleet(app: Any) -> FleetRouter:
    """Wire the fleet router onto ``app`` (see module docstring)."""
    from gofr_tpu.fleet.admission import QuotaTable
    from gofr_tpu.fleet.breaker import CircuitBreaker
    from gofr_tpu.fleet.replica import Replica, ReplicaSet
    from gofr_tpu.fleet.router import FleetRouter

    config = app.config
    container = app.container
    logger = app.logger
    spec = config.get("FLEET_REPLICAS") or ""
    replicas_cfg = parse_replicas(spec)
    if not replicas_cfg:
        raise ValueError(
            "FLEET_REPLICAS is required to run the fleet router "
            "(comma-separated replica base URLs)"
        )

    def _f(key: str, default: str) -> float:
        return float(config.get_or_default(key, default))

    def _i(key: str, default: str) -> int:
        return int(config.get_or_default(key, default))

    connect_t = _f("FLEET_CONNECT_TIMEOUT_S", "2")
    read_t = _f("FLEET_READ_TIMEOUT_S", "30")
    threshold = _i("FLEET_BREAKER_THRESHOLD", "5")
    cooldown = _f("FLEET_BREAKER_COOLDOWN_S", "5")
    replicas = [
        Replica(
            name, url, logger,
            connect_timeout=connect_t, read_timeout=read_t,
            breaker=CircuitBreaker(
                failure_threshold=threshold, cooldown_s=cooldown
            ),
        )
        for name, url in replicas_cfg
    ]
    replica_set = ReplicaSet(
        replicas, logger,
        probe_interval_s=_f("FLEET_PROBE_INTERVAL_S", "1"),
        probe_timeout_s=_f("FLEET_PROBE_TIMEOUT_S", "1"),
        probe_jitter=_f("FLEET_PROBE_JITTER", "0.2"),
        hedge_ms=_f("FLEET_PROBE_HEDGE_MS", "0"),
        out_after=_i("FLEET_OUT_AFTER", "2"),
        probation_probes=_i("FLEET_PROBATION_PROBES", "3"),
        saturation_queue=_i("FLEET_SATURATION_QUEUE", "64"),
        affinity_max_skew=_i("FLEET_AFFINITY_MAX_SKEW", "4"),
    )
    quota = QuotaTable(
        rate_rps=_f("FLEET_QUOTA_RPS", "0"),
        burst=_f("FLEET_QUOTA_BURST", "0"),
        redis=container.redis,
        logger=logger,
        metrics=container.metrics,
        cache_ttl_s=_f("FLEET_QUOTA_CACHE_TTL_S", "0.05"),
    )
    fleet = FleetRouter(
        logger, container.metrics, replica_set, quota,
        retries=_i("FLEET_RETRIES", "2"),
        deadline_s=_f("FLEET_DEADLINE_S", "30"),
        connect_timeout_s=connect_t,
        read_timeout_s=read_t,
        max_inflight=_i("FLEET_MAX_INFLIGHT", "256"),
        retry_after_s=_f("FLEET_RETRY_AFTER_S", "1"),
        # N routers run side by side (router HA): the id labels THIS
        # instance's /admin/fleet view; everything cross-instance is
        # redis-backed or stateless (see FleetRouter.router_id)
        router_id=config.get_or_default("FLEET_ROUTER_ID", ""),
    )
    if (config.get_or_default("FLEET_RESUME", "on") or "").lower() in (
        "off", "0", "false", "no"
    ):
        # resume off: mid-stream upstream failure truncates (pre-PR-9)
        fleet.resume_enabled = False
    fleet.max_resumes = max(0, _i("FLEET_MAX_RESUMES", "4"))
    if (config.get_or_default("FLEET_AFFINITY", "on") or "").lower() in (
        "off", "0", "false", "no"
    ):
        # affinity off: every request routes least-outstanding
        fleet.affinity_enabled = False
    if (config.get_or_default("FLEET_ROLE_ROUTING", "on") or "").lower() in (
        "off", "0", "false", "no"
    ):
        # role routing off: replicas' advertised FLEET_ROLE is ignored
        # and no X-KV-Donor hints are stamped (pre-disaggregation
        # behavior)
        fleet.role_routing = False
    if (config.get_or_default("FLEET_TRUST_TENANT_HEADER", "off") or "").lower() in (
        "on", "1", "true", "yes"
    ):
        # ONLY behind an authenticating gateway that stamps X-Tenant:
        # trusted from arbitrary clients it makes quotas mintable
        fleet.trust_tenant_header = True
    # the container's bounded tenant sketch: the router meters its own
    # admissions and shed verdicts per tenant (/admin/tenants answers
    # on the front door too)
    fleet.tenants = getattr(container, "tenants", None)
    routes = config.get_or_default("FLEET_ROUTES", DEFAULT_ROUTES)
    for entry in routes.split(","):
        entry = entry.strip()
        if not entry:
            continue
        method, _, pattern = entry.partition(" ")
        pattern = pattern.strip()
        if not pattern:
            raise ValueError(
                f"FLEET_ROUTES entry '{entry}' must be 'METHOD /path'"
            )
        app.add_route(method.upper(), pattern, fleet.handle)
    from gofr_tpu.handler import (
        fleet_admin_handler,
        fleet_overview_handler,
        fleet_trace_handler,
    )

    app.get("/admin/fleet", fleet_admin_handler)
    # fleet-wide causal trace for one request id (fleet/trace.py) and
    # the fleet rollup built from the prober's piggybacked scrapes
    app.get("/admin/fleet/trace/{id}", fleet_trace_handler)
    app.get("/admin/fleet/overview", fleet_overview_handler)
    container.fleet = fleet
    replica_set.start()
    logger.infof(
        "fleet router fronting %d replica(s): %s",
        len(replicas), ", ".join(f"{n}={u}" for n, u in replicas_cfg),
    )
    return fleet
