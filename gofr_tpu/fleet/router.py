"""The fleet front door: health-aware routing, resilient forwarding,
admission control, and graceful drain — one :class:`FleetRouter` object
wired onto a plain gofr app (``gofr_tpu.fleet.wire_fleet``).

Request path, in order:

1. **Admission** — draining? 503. Tenant over quota? 429 +
   ``Retry-After`` (exact token-refill time). Router at its in-flight
   cap, or every in-rotation replica reporting KV/queue saturation
   (the replica's ``pool_reject``/``kv_exhausted`` signals, scraped by
   the prober)? 429 + ``Retry-After`` — the queue is bounded by
   construction, overload is always an explicit signal upstream.
2. **Selection** — in-rotation replicas only (prober state machine),
   prefix-affinity first (rendezvous hash on the conversation key, so
   a follow-up turn lands on the replica holding its paged-KV prefix
   blocks), then least-outstanding with a rotating tie-break; the
   per-replica circuit breaker gets the final veto.
3. **Forwarding** — per-request deadline budget across attempts;
   bounded retries with decorrelated-jitter backoff for failures that
   produced no client-visible bytes (connect errors always; read
   timeouts and 5xx for requests not yet streamed); streaming requests
   pass SSE chunks through. DETERMINISTIC streams (an explicit seed,
   or temperature 0) stay recoverable even after bytes flowed: the
   router journals the last SSE event id it delivered
   (:class:`_StreamRelay`), and a mid-stream upstream failure — a
   dropped connection, a read timeout, or the replica's own error
   frame when its engine wedges — retries/fails over with
   ``X-Resume-From: <next id>`` and splices the continuation into the
   SAME client response, filtering by event id so the client sees zero
   missing and zero duplicated tokens. Non-deterministic streams keep
   the old contract (abort truncated).
4. **Accounting** — every decision rides the existing telemetry:
   ``gofr_tpu_router_*`` metrics, a bounded ring of per-request route
   records (the flight-recorder idiom one layer up), and the
   ``GET /admin/fleet`` snapshot.
"""

from __future__ import annotations

import json
import secrets
import threading
import time
from collections import deque
from typing import Any, Optional

from gofr_tpu.fleet import breaker as breaker_mod
from gofr_tpu.fleet.admission import QuotaTable, tenant_of
from gofr_tpu.fleet.replica import (
    HEALTHY,
    PROBATION,
    STATE_VALUES,
    ReplicaSet,
    affinity_order,
)
from gofr_tpu.http.response import Response
from gofr_tpu.service import ServiceCallError, _encode_query, backoff_delays
from gofr_tpu.telemetry import format_hop, sanitize_request_id
from gofr_tpu.tracing import current_span

_JSON = "application/json"


def mint_request_id() -> str:
    """A fresh fleet-wide request id (the router mints one when the
    client supplied none, or supplied garbage)."""
    return "req-" + secrets.token_hex(8)


class _ResumeSpec:
    """Everything a stream relay needs to re-issue its request on a
    failover: the wire request (method/target/headers/body), the
    absolute deadline, and the affinity key for candidate ordering."""

    __slots__ = ("method", "target", "headers", "body", "deadline",
                 "affinity", "budgeted")

    def __init__(self, method: str, target: str, headers: dict[str, str],
                 body: Any, deadline: float, affinity: str,
                 budgeted: bool = False):
        self.method = method
        self.target = target
        self.headers = headers
        self.body = body
        self.deadline = deadline
        self.affinity = affinity
        # True only when the CLIENT set a positive deadline: a
        # continuation re-stamps the remaining budget iff the original
        # attempt did (an opted-out stream must stay opted out)
        self.budgeted = budgeted

# request headers forwarded to the replica (hop-by-hop and router-local
# headers are stripped; the service client adds its own traceparent /
# correlation id so the replica's spans join the router's trace).
# x-priority forwards VERBATIM (the replica's brownout controller sheds
# by tier). x-request-deadline-ms also forwards verbatim by DEFAULT —
# an absent header, an explicit "0" opt-out, and a malformed value all
# reach the replica untouched (the 400 for garbage is the replica's to
# give) — but when the client set a positive budget, _forward OVERWRITES
# it per attempt with the REMAINING budget, so a retried hop never
# hands a replica more time than the client has left.
_FORWARD_HEADERS = (
    "content-type", "accept", "authorization", "x-tenant",
    "x-session-id", "x-affinity-key", "user-agent", "x-forwarded-for",
    "x-priority", "x-request-deadline-ms",
)
# response headers forwarded back to the client
_RETURN_HEADERS = ("content-type", "retry-after", "x-request-id")


def hash_affinity(key: str) -> str:
    """The display form of an affinity key: route records and
    ``/admin/fleet`` must never carry the raw key, which can be the
    user's prompt text."""
    import hashlib

    return "aff-" + hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def affinity_key_of(request: Any, body: Any) -> str:
    """The conversation/prefix key a request routes by: explicit
    ``X-Session-ID``/``X-Affinity-Key`` header first, then the OpenAI
    ``user`` field, else the conversation prefix itself (first user
    message / prompt head) — the same bytes the replica's prefix cache
    keys on."""
    key = request.header("X-Session-ID") or request.header("X-Affinity-Key")
    if key:
        return key
    if not isinstance(body, dict):
        return ""
    user = body.get("user")
    if isinstance(user, str) and user:
        return user
    messages = body.get("messages")
    if isinstance(messages, list) and messages:
        # the first USER message, not messages[0]: chat traffic shares
        # its system prompt, and keying on it would rendezvous the
        # whole fleet's load onto one replica
        for message in messages:
            if (isinstance(message, dict)
                    and message.get("role") == "user"
                    and isinstance(message.get("content"), str)
                    and message["content"]):
                return message["content"][:128]
        first = messages[0]
        if isinstance(first, dict) and isinstance(first.get("content"), str):
            return first["content"][:128]
    prompt = body.get("prompt")
    if isinstance(prompt, str) and prompt:
        return prompt[:128]
    if isinstance(prompt, list) and prompt:
        # token-id prompts key on their head, same as the prefix cache
        return ",".join(str(t) for t in prompt[:32])
    return ""


class FleetRouter:
    def __init__(
        self,
        logger: Any,
        metrics: Any,
        replica_set: ReplicaSet,
        quota: QuotaTable,
        retries: int = 2,
        deadline_s: float = 30.0,
        connect_timeout_s: float = 2.0,
        read_timeout_s: float = 30.0,
        max_inflight: int = 256,
        retry_after_s: float = 1.0,
        record_capacity: int = 256,
        router_id: str = "",
    ):
        self.logger = logger
        self.metrics = metrics
        self.replica_set = replica_set
        self.quota = quota
        self.retries = retries
        self.deadline_s = deadline_s
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s
        # router HA (no single point of failure): N router processes run
        # side by side over the same FLEET_REPLICAS. The router's state
        # is shardable by construction — quota is redis-backed (shared,
        # with per-instance short-TTL leases), affinity/KV-locality is
        # stateless rendezvous hashing (every router picks the same
        # replica), and the rest is EXPLICITLY per-instance: the
        # in-flight cap bounds THIS process (N routers = N * cap), the
        # route-record ring, breaker verdicts, and prober state are this
        # instance's local view, and a stream relay lives and dies with
        # the connection it serves (a router death mid-stream is the
        # CLIENT's retry — deterministic streams replay bit-identically
        # through any sibling). router_id (FLEET_ROUTER_ID, defaulting
        # to the process boot id) labels /admin/fleet so operators can
        # tell N instances apart.
        from gofr_tpu.telemetry import BOOT_ID

        self.router_id = router_id or f"router-{BOOT_ID}"
        # resumable streams: journal delivered SSE event ids and splice
        # a failover continuation into a broken deterministic stream
        # instead of truncating (FLEET_RESUME / FLEET_MAX_RESUMES —
        # wire_fleet sets both post-construction, like affinity)
        self.resume_enabled = True
        self.max_resumes = 4
        self.affinity_enabled = True
        # disaggregated prefill/decode (FLEET_ROLE_ROUTING): route
        # prefill-heavy work to prefill-tier replicas and decodes to
        # decode-tier ones, with KV-locality (prompt-hash rendezvous)
        # beating plain prefix affinity, and stamp X-KV-Donor with the
        # prefill replica that rendezvous-owns the prompt's KV. Every
        # tier decision DEGRADES to mixed routing when the tier is
        # empty or its breakers veto — role config can never make the
        # fleet serve less than it does without it.
        self.role_routing = True
        self.trust_tenant_header = False  # FLEET_TRUST_TENANT_HEADER
        # the container's bounded per-tenant usage sketch (wire_fleet
        # sets it post-construction, like the flags above): the router
        # meters its own admissions and shed verdicts per tenant
        self.tenants: Optional[Any] = None
        self._records: deque = deque(maxlen=record_capacity)
        self._records_lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Condition()
        self._draining = False
        self._init_metrics()
        self._wire_hooks()

    # -- metrics ---------------------------------------------------------------
    def _init_metrics(self) -> None:
        m = self.metrics
        self._req_total = m.counter(
            "gofr_tpu_router_requests_total",
            "forwarded requests by replica and outcome "
            "(ok | upstream_5xx | network_error | client_aborted)",
            labels=("replica", "outcome"),
        )
        self._retries_total = m.counter(
            "gofr_tpu_router_retries_total",
            "router retry attempts by failing replica and reason",
            labels=("replica", "reason"),
        )
        self._shed_total = m.counter(
            "gofr_tpu_router_shed_total",
            "requests shed at admission (429/503) by reason",
            labels=("reason",),
        )
        self._breaker_total = m.counter(
            "gofr_tpu_router_breaker_transitions_total",
            "circuit-breaker state transitions by replica and target state",
            labels=("replica", "to"),
        )
        self._breaker_gauge = m.gauge(
            "gofr_tpu_router_breaker_state",
            "breaker state per replica (0 closed, 1 half-open, 2 open)",
            labels=("replica",),
        )
        self._replica_gauge = m.gauge(
            "gofr_tpu_router_replica_state",
            "rotation state per replica (0 out, 1 probation, 2 healthy)",
            labels=("replica",),
        )
        self._outstanding_gauge = m.gauge(
            "gofr_tpu_router_outstanding_depth",
            "requests currently outstanding against each replica",
            labels=("replica",),
        )
        self._inflight_gauge = m.gauge(
            "gofr_tpu_router_inflight_depth",
            "requests currently inside the router (admitted, not finished)",
        )
        self._upstream_seconds = m.histogram(
            "gofr_tpu_router_upstream_seconds",
            "upstream attempt latency per replica (success or failure)",
            labels=("replica",),
        )
        self._replica_restarts = m.counter(
            "gofr_tpu_router_replica_restarts_total",
            "replica processes observed REBORN by the prober (ready "
            "boot_id changed): a supervisor respawned the process after "
            "a crash/SIGKILL; the replica re-enters through probation "
            "as `restarting`",
            labels=("replica",),
        )
        self._hop_seconds = m.histogram(
            "gofr_tpu_router_hop_seconds",
            "per-hop latency decomposition of one routed request: "
            "router (admission + selection overhead before the first "
            "upstream dispatch), upstream (one buffered attempt's "
            "round trip), stream (one streaming attempt's body "
            "duration), resume (a mid-stream failover continuation's "
            "splice latency) — the metric behind the per-stage "
            "breakdown /admin/fleet/trace/<id> shows for one request",
            labels=("stage",),
        )
        self._stream_resumes = m.counter(
            "gofr_tpu_router_stream_resumes_total",
            "mid-stream failover outcomes on resumable (deterministic) "
            "SSE streams: resumed (continuation spliced in), exhausted "
            "(deadline/attempts spent — truncated), refused (the "
            "replica rejected the resume — truncated)",
            labels=("outcome",),
        )

    def _wire_hooks(self) -> None:
        """Attach breaker-transition and rotation-state hooks so every
        decision is observable the moment it happens."""
        for replica in self.replica_set.replicas:
            self._replica_gauge.set(
                float(STATE_VALUES[replica.state]), replica=replica.name
            )
            self._breaker_gauge.set(
                float(breaker_mod.STATE_VALUES[replica.breaker.state]),
                replica=replica.name,
            )
            replica.breaker._on_transition = self._breaker_hook(replica.name)
        self.replica_set._on_state_change = self._rotation_hook
        self.replica_set._on_restart = self._restart_hook

    def _breaker_hook(self, name: str) -> Any:
        def hook(was: str, to: str) -> None:
            self._breaker_total.inc(replica=name, to=to)
            self._breaker_gauge.set(
                float(breaker_mod.STATE_VALUES[to]), replica=name
            )
            self.logger.infof("fleet breaker %s: %s -> %s", name, was, to)
        return hook

    def _rotation_hook(self, replica: Any, was: str, now: str) -> None:
        self._replica_gauge.set(
            float(STATE_VALUES[now]), replica=replica.name
        )
        self.logger.infof(
            "fleet replica %s: %s -> %s (%s)",
            replica.name, was, now, replica.last_probe_error or "ready",
        )

    def _restart_hook(self, replica: Any) -> None:
        self._replica_restarts.inc(replica=replica.name)
        self.logger.infof(
            "fleet replica %s: process restarted (boot_id %s, restart #%s)"
            " — restarting via probation",
            replica.name, replica.boot_id, replica.restarts,
        )

    # -- lifecycle -------------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def in_flight(self) -> int:
        # lock-free read (int attribute reads are GIL-atomic): admin
        # snapshots and drain logging must not contend with the
        # admission path for the condition's mutex
        return self._inflight

    def begin_drain(self) -> None:
        """Stop admitting; readiness flips to 503 (handler.py checks
        :attr:`draining`)."""
        self._draining = True
        self.logger.infof(
            "fleet drain: admission closed, %s in flight", self.in_flight
        )

    # the in-flight counter releases when the HANDLER finishes; the
    # server still has to flush that last response onto the socket, so
    # drain() lingers briefly before declaring the listener safe to stop
    DRAIN_GRACE_S = 0.25

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Graceful drain: stop admitting, then wait for the in-flight
        requests to finish. Returns True when fully drained."""
        self.begin_drain()
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._idle.wait(remaining)
            drained = self._inflight == 0
        if drained:
            time.sleep(self.DRAIN_GRACE_S)
        self.logger.infof(
            "fleet drain %s (%s left)",
            "complete" if drained else "TIMED OUT", self.in_flight,
        )
        return drained

    def close(self) -> None:
        self._draining = True
        self.replica_set.close()

    # -- admission -------------------------------------------------------------
    def _shed(self, status: int, reason: str, retry_after_s: float,
              detail: str, request_id: str = "", tenant: str = "") -> Response:
        self._shed_total.inc(reason=reason)
        if tenant and self.tenants is not None:
            # a router shed never reaches a replica's flight recorder,
            # so the tenant ledger meters it at the verdict
            self.tenants.shed(tenant)
        # the request id rides the shed body AND header: a 429/503 the
        # router refused is otherwise untraceable — no route forward,
        # no replica record, just a log line the client needs to quote.
        # The HASHED tenant id rides next to it: the subject of a quota
        # verdict should be able to quote itself to /admin/tenants.
        body = json.dumps({"error": {
            "message": detail, "reason": reason,
            "retry_after_s": round(retry_after_s, 3),
            "request_id": request_id or None,
            "tenant": tenant or None,
        }}).encode("utf-8")
        headers = {"Content-Type": _JSON,
                   "Retry-After": str(max(1, int(retry_after_s + 0.999)))}
        if request_id:
            headers["X-Gofr-Request-Id"] = request_id
        response = Response(status=status, headers=headers, body=body)
        response._shed_reason = reason
        return response

    def _admit(self, request: Any, tenant: str,
               request_id: str = "") -> Optional[Response]:
        """None = admitted AND the in-flight slot is HELD (the caller
        must ``_release()``); a Response = the shed verdict. Ordering:
        router-state sheds first, then the slot (check-and-increment
        atomically under the lock — a read-then-act gap would let a
        thundering herd overshoot the cap by up to the handler-pool
        size), then the quota LAST so router-side rejections never
        charge the tenant a token for a request the router itself
        refused."""
        if self._draining:
            return self._shed(
                503, "draining", self.retry_after_s,
                "router is draining; retry against another front door",
                request_id=request_id, tenant=tenant,
            )
        if self.replica_set.all_saturated():
            return self._shed(
                429, "kv_exhausted", self.retry_after_s,
                "every replica reports KV/queue saturation",
                request_id=request_id, tenant=tenant,
            )
        if not self.replica_set.in_rotation():
            return self._shed(
                503, "no_replicas", self.retry_after_s,
                "no replica in rotation",
                request_id=request_id, tenant=tenant,
            )
        if not self._try_acquire_slot():
            return self._shed(
                429, "inflight", self.retry_after_s,
                "router at its in-flight cap",
                request_id=request_id, tenant=tenant,
            )
        ok, retry_after = self.quota.take(tenant)
        if not ok:
            self._release()
            return self._shed(
                429, "quota", retry_after,
                f"tenant '{tenant}' over its request quota",
                request_id=request_id, tenant=tenant,
            )
        if self.tenants is not None:
            # admitted: one request on the router's own tenant ledger
            # (replica-side ledgers add tokens when the flight finishes)
            self.tenants.observe(tenant, requests=1)
        return None

    def _try_acquire_slot(self) -> bool:
        # the gauge write happens OUTSIDE the condition's mutex: the
        # metric registry has its own lock, and nesting it under _idle
        # put a foreign lock inside the hottest router mutex (lock-order
        # edge + hold time — both sanitizer findings at fleet scale).
        # Two concurrent updates may publish out of order; the depth
        # gauge self-corrects on the next admission, which is the right
        # trade for not serializing admission on metric bookkeeping.
        with self._idle:
            if self.max_inflight > 0 and self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            depth = self._inflight
        self._inflight_gauge.set(float(depth))
        return True

    # -- the forward handler ---------------------------------------------------
    def handle(self, ctx: Any) -> Response:
        """The transport handler registered for every forwarded route
        (sync: runs on the container's handler pool)."""
        request = ctx.request
        tenant = tenant_of(request, self.trust_tenant_header)
        # the fleet-wide correlation id: honor a sanitized client
        # X-Request-ID (length-bounded, charset-restricted — garbage
        # degrades to a minted id, never to a 4xx), else mint. The id
        # echoes on EVERY response — sheds included — and keys the
        # route record, every replica FlightRecord this request causes,
        # and /admin/fleet/trace/<id>. A client-supplied X-Gofr-Hop is
        # NOT consulted: hop provenance is the router's to assert (same
        # trust discipline as X-KV-Donor).
        request_id = sanitize_request_id(
            request.header("X-Request-ID")
            or request.header("X-Gofr-Request-Id")
        ) or mint_request_id()
        verdict = self._admit(request, tenant, request_id)
        if verdict is not None:
            # record construction stays OUTSIDE the ring lock: the lock
            # guards exactly one deque.append per request, so a shed
            # storm (the moment every request takes this path) never
            # serializes on dict building
            shed_record = {
                "ts": time.time(),  # gofrlint: wall-clock — route-record display timestamp
                "request_id": request_id,
                "method": request.method, "path": request.path,
                "tenant": tenant, "attempts": [], "retries": 0,
                "status": verdict.status,
                "outcome": f"shed:{verdict._shed_reason}",
            }
            with self._records_lock:
                self._records.append(shed_record)
            return verdict
        # reached here: _admit HOLDS the in-flight slot for this request
        body_json = self._body_json(request)
        affinity = (affinity_key_of(request, body_json)
                    if self.affinity_enabled else "")
        # disaggregated routing: classify the request's tier and, for
        # token-id prompts, derive the EXACT KV identity — prompt-hash
        # rendezvous then beats the PROMPT-HEAD affinity heuristic
        # (locality to actual cached blocks, not to a conversation
        # guess). An EXPLICIT client key (X-Session-ID / X-Affinity-Key
        # / the OpenAI user field) still wins: the client asked to pin,
        # and the donor hint carries KV locality anyway.
        role = self._classify_role(request.path) if self.role_routing else None
        kv_hash = (
            self._kv_hash_of(body_json)
            if self.role_routing and self.affinity_enabled else ""
        )
        if kv_hash and not self._explicit_affinity(request, body_json):
            affinity = kv_hash
        wants_stream = isinstance(body_json, dict) and bool(body_json.get("stream"))
        # resumable: deterministic streams (seed / greedy) can be
        # regenerated bit-identically, so a mid-stream upstream failure
        # is recoverable by event-id splicing instead of truncation
        resumable = (
            self.resume_enabled and wants_stream and self.max_resumes > 0
            and _deterministic_body(body_json)
        )
        try:
            response = self._forward(
                request, tenant, affinity, wants_stream,
                executor=ctx.container.handler_executor,
                resumable=resumable, role=role, kv_hash=kv_hash,
                request_id=request_id,
            )
            response.headers["X-Gofr-Request-Id"] = request_id
            return response
        finally:
            # streaming responses decrement in their own finally instead
            # (the handler returns before the body is pulled); _forward
            # flags that by setting _stream_owns_release
            if not getattr(request, "_stream_owns_release", False):
                self._release()

    def _release(self) -> None:
        with self._idle:
            self._inflight = max(0, self._inflight - 1)
            depth = self._inflight
            if depth == 0:
                self._idle.notify_all()
        # outside the mutex on purpose — see _try_acquire_slot
        self._inflight_gauge.set(float(depth))

    @staticmethod
    def _body_json(request: Any) -> Any:
        if not request.body:
            return None
        try:
            return json.loads(request.body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None

    def _target(self, request: Any) -> str:
        # parse_qs gives {key: [values]}; _encode_query repeats the key
        # per value, round-tripping the original query string
        query = _encode_query(request.query)
        return request.path + ("?" + query if query else "")

    @staticmethod
    def _forward_headers(request: Any) -> dict[str, str]:
        return {
            name: request.headers[name]
            for name in _FORWARD_HEADERS if name in request.headers
        }

    @staticmethod
    def _client_budget_s(request: Any) -> Optional[float]:
        """The client's own ``X-Request-Deadline-Ms`` budget in seconds
        (None when absent/malformed — the router's FLEET_DEADLINE_S
        then stands alone; a malformed header is the REPLICA's 400 to
        give, the router must not eat the request first)."""
        raw = request.header("X-Request-Deadline-Ms")
        if not raw:
            return None
        try:
            ms = int(raw)
        except ValueError:
            return None
        if ms <= 0:
            return None
        return ms / 1000.0

    @staticmethod
    def _classify_role(path: str) -> Optional[str]:
        """The replica tier a route prefers: prefill-only surfaces
        (embeddings, single-shot infer) want the prefill tier; token
        generation wants the decode tier; everything else (models
        listing, unknown routes) has no preference."""
        if path.endswith("/embeddings") or path.endswith("/infer"):
            return "prefill"
        if path.endswith("/completions") or path.endswith("/generate"):
            return "decode"
        return None

    @staticmethod
    def _explicit_affinity(request: Any, body: Any) -> bool:
        """True when the CLIENT pinned the conversation (session/
        affinity header or the OpenAI ``user`` field) — those pins
        outrank KV-hash rendezvous; only the prompt-head heuristic
        yields to it."""
        if request.header("X-Session-ID") or request.header("X-Affinity-Key"):
            return True
        return isinstance(body, dict) and bool(body.get("user"))

    @staticmethod
    def _kv_hash_of(body: Any) -> str:
        """The prompt's exact KV identity, derivable only for token-id
        prompts (text prompts tokenize replica-side; their locality
        stays with the affinity heuristics)."""
        if not isinstance(body, dict):
            return ""
        tokens = body.get("tokens")
        if not isinstance(tokens, list):
            tokens = body.get("prompt")
        if (
            isinstance(tokens, list) and tokens
            and all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in tokens
            )
        ):
            from gofr_tpu.fleet.kvwire import prompt_hash

            return prompt_hash(tokens)
        return ""

    def _kv_donor(self, kv_hash: str) -> Optional[Any]:
        """The prefill-tier replica that rendezvous-owns this prompt's
        KV — the X-KV-Donor stamp for decode-bound requests. None when
        no prefill replica is in rotation (a mixed fleet has no
        dedicated donors; locality then rides selection alone)."""
        if not kv_hash:
            return None
        tier = [
            r for r in self.replica_set.replicas
            if r.state == HEALTHY and r.role == "prefill"
        ]
        if not tier:
            return None
        ranked = affinity_order(kv_hash, [r.name for r in tier])
        return next(r for r in tier if r.name == ranked[0])

    def _forward(self, request: Any, tenant: str, affinity: str,
                 wants_stream: bool, executor: Any = None,
                 resumable: bool = False, role: Optional[str] = None,
                 kv_hash: str = "", request_id: str = "") -> Response:
        start = time.monotonic()
        # the router's SERVER span, captured ONCE: every attempt — and
        # every relay continuation, which re-reads the same headers dict
        # from a pool thread where the span contextvar is gone — stamps
        # this traceparent explicitly, so failover hops parent to the
        # ORIGINAL request span instead of starting fresh traces (the
        # service client's setdefault respects an existing stamp)
        span = current_span()
        # the effective budget is the TIGHTER of the router's own
        # forwarding deadline and the client's end-to-end deadline —
        # retrying past what the client will wait for is pure waste
        budget_s = self.deadline_s
        client_budget = self._client_budget_s(request)
        if client_budget is not None:
            budget_s = min(budget_s, client_budget)
        deadline = start + budget_s
        target = self._target(request)
        headers = self._forward_headers(request)
        record: dict[str, Any] = {
            "ts": time.time(),  # gofrlint: wall-clock — route-record display timestamp
            "request_id": request_id,
            "router_id": self.router_id,
            "method": request.method,
            "path": request.path,
            "tenant": tenant,
            # hashed: the raw key can be PROMPT TEXT (affinity_key_of
            # falls back to the message head) and route records serve
            # on /admin/fleet — same rule as the tenant hash
            "affinity_key": hash_affinity(affinity) if affinity else None,
            "stream": wants_stream,
            "resumable": resumable,
            "resumes": 0,
            # disaggregated routing evidence: the tier asked for, and
            # which replica (if any) was named as the KV donor
            "role": role,
            "kv_donor": None,
            "attempts": [],
            "outcome": "error",
            "status": 0,
            # monotonic start for the END-TO-END elapsed stamped at
            # finish ("_"-prefixed: stripped from the admin surface)
            "_start_mono": start,
        }
        # the donor is decided ONCE per request (the prefill replica
        # rendezvous-owning the prompt's KV), then stamped per attempt
        # so a failover hop still knows where the warm blocks live
        donor = (
            self._kv_donor(kv_hash) if role == "decode" else None
        )
        if donor is not None:
            record["kv_donor"] = donor.name
        tried: set[str] = set()
        delays = backoff_delays(self.retries)
        response: Optional[Response] = None
        attempts = 0
        while attempts <= self.retries:
            # budget check BEFORE the pick: _pick may claim a breaker's
            # single half-open probe slot, and only _attempt releases it
            # (via record_success/record_failure) — breaking between the
            # two would wedge that breaker half-open forever
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            picked = self._pick(affinity, tried, role=role)
            if picked is None:
                break
            replica, is_probe = picked
            # the donor hint: stamped only when a DIFFERENT replica
            # holds the warm blocks (pulling from yourself is a no-op
            # the replica would skip anyway, but why ask)
            if donor is not None and donor.name != replica.name:
                headers["X-KV-Donor"] = donor.address
            else:
                headers.pop("X-KV-Donor", None)
            # hop provenance, re-stamped per attempt: which router,
            # which failover attempt (0-based), resume 0 (continuations
            # re-stamp their own index in _StreamRelay._try_resume).
            # Client copies of these headers never reach here — they
            # are not in _FORWARD_HEADERS — so the replica can trust
            # the stamp the way it trusts X-KV-Donor.
            headers["X-Gofr-Request-Id"] = request_id
            headers["X-Gofr-Hop"] = format_hop(self.router_id, attempts, 0)
            if span is not None:
                headers["traceparent"] = span.traceparent()
            if attempts == 0:
                # router-overhead stage: admission, body parse, and
                # selection paid before the FIRST upstream dispatch
                self._hop_seconds.observe(
                    time.monotonic() - start, stage="router"
                )
            if record["attempts"]:
                # a retry is now CERTAIN (a replica was found and will
                # be attempted): count it against the attempt it redoes
                prev = record["attempts"][-1]
                self._retries_total.inc(
                    replica=prev["replica"],
                    reason=prev.get("reason") or "error",
                )
            attempts += 1
            tried.add(replica.name)
            # deadline propagation: each attempt hands the replica the
            # REMAINING budget (floored at 1 ms) — a second attempt
            # after a 2 s failure sees a budget 2 s smaller, so no hop
            # is ever granted more time than the client has left. Only
            # when the client SET a budget: no header / "0" / garbage
            # forward verbatim (the replica's default or 400 applies) —
            # the router must never mint a deadline the client didn't ask for
            if client_budget is not None:
                headers["X-Request-Deadline-Ms"] = str(
                    max(1, int(remaining * 1000))
                )
            resume = (
                _ResumeSpec(request.method, target, headers,
                            request.body or None, deadline, affinity,
                            budgeted=client_budget is not None)
                if resumable else None
            )
            response = self._attempt(
                replica, request, target, headers, wants_stream,
                remaining, record, executor, is_probe, resume=resume,
            )
            if response is not None:
                if response.stream is None:
                    # streaming responses finish their record (and the
                    # in-flight release) when the body completes
                    self._finish_record(record, response.status)
                return response
            delay = next(delays, None)
            if delay is None or time.monotonic() + delay >= deadline:
                break
            time.sleep(delay)
        # nothing served: every candidate failed, refused, or timed out
        last = record["attempts"][-1] if record["attempts"] else None
        detail = (last or {}).get("error") or "no replica could serve the request"
        self._finish_record(record, 502)
        body = json.dumps({"error": {
            "message": f"fleet forward failed after {attempts} attempt(s): {detail}",
            "request_id": request_id or None,
        }}).encode("utf-8")
        return Response(
            status=502,
            headers={"Content-Type": _JSON,
                     "Retry-After": str(max(1, int(self.retry_after_s)))},
            body=body,
        )

    def _pick(self, affinity: str, tried: set[str],
              role: Optional[str] = None) -> Optional[tuple[Any, bool]]:
        """First candidate whose breaker admits the request, plus
        whether this dispatch IS that breaker's half-open probe (its
        success report must carry the probe grant). Pass order: the
        requested role tier first, then role-free (an empty tier OR a
        tier whose breakers all veto must degrade to mixed routing,
        never to a 502 the un-roled fleet would have served), then
        already-tried replicas as the last resort (a 2-replica fleet
        with one dead replica must still retry the healthy one rather
        than give up). Re-testing a breaker across passes is harmless:
        a closed breaker grants again (we returned the first time), a
        vetoing one vetoes again."""
        passes: list[tuple[Optional[set[str]], Optional[str]]] = []
        if role is not None:
            passes.append((tried, role))
        passes.append((tried, None))
        if tried:
            passes.append((None, None))
        for exclude, tier in passes:
            for replica in self.replica_set.candidates(
                affinity, exclude=exclude, role=tier
            ):
                grant = replica.breaker.try_acquire()
                if grant:
                    return replica, grant == breaker_mod.PROBE
        return None

    def _attempt(
        self,
        replica: Any,
        request: Any,
        target: str,
        headers: dict[str, str],
        wants_stream: bool,
        remaining_s: float,
        record: dict[str, Any],
        executor: Any = None,
        is_probe: bool = False,
        resume: Optional[_ResumeSpec] = None,
    ) -> Optional[Response]:
        """One forward attempt. Returns the client-facing Response, or
        None when the failure is retryable (breaker/metrics/record
        already updated)."""
        entry: dict[str, Any] = {"replica": replica.name, "status": None,
                                 "error": None, "elapsed_ms": 0}
        record["attempts"].append(entry)
        depth = replica.mark_dispatch()
        self._outstanding_gauge.set(float(depth), replica=replica.name)
        attempt_start = time.monotonic()
        read_timeout = min(self.read_timeout_s, remaining_s)
        streaming: Optional[Any] = None
        try:
            if wants_stream:
                streaming = replica.client.stream(
                    request.method, target, body=request.body or None,
                    headers=headers,
                    connect_timeout=min(self.connect_timeout_s, remaining_s),
                    read_timeout=read_timeout,
                )
                status = streaming.status_code
                if status == 200:
                    if resume is not None:
                        # committed, but NOT final: the relay journals
                        # delivered event ids and can splice a failover
                        # continuation into this very response
                        return self._relay_response(
                            replica, request, streaming, entry,
                            attempt_start, record, executor, is_probe,
                            resume,
                        )
                    # committed: from here the bytes flow to the client
                    # and the request stops being retryable
                    return self._stream_response(
                        replica, request, streaming, entry, attempt_start,
                        record, executor, is_probe,
                    )
                # bounded drain: an untrusted replica dripping its
                # error body must not pin this thread past the budget
                payload = streaming.read(budget_s=read_timeout)
                resp_headers = streaming.headers
                streaming = None
            else:
                resp = replica.client.request(
                    request.method, target, body=request.body or None,
                    headers=headers,
                    connect_timeout=min(self.connect_timeout_s, remaining_s),
                    read_timeout=read_timeout,
                    retries=0,
                )
                status, payload, resp_headers = (
                    resp.status_code, resp.body, resp.headers
                )
        except ServiceCallError as exc:
            return self._note_failure(
                replica, entry, attempt_start, "network", str(exc.cause)
            )
        except Exception as exc:
            # a mid-read socket timeout / reset from StreamingServiceResponse
            # arrives unwrapped; the connection closed with it
            if streaming is not None:
                streaming.close()
            return self._note_failure(
                replica, entry, attempt_start, "read", str(exc)
            )
        elapsed = time.monotonic() - attempt_start
        entry["status"] = status
        entry["elapsed_ms"] = round(elapsed * 1000, 1)
        self._upstream_seconds.observe(elapsed, replica=replica.name)
        self._hop_seconds.observe(elapsed, stage="upstream")
        self._finish_attempt(replica)
        if status >= 500:
            replica.breaker.record_failure()
            self._req_total.inc(replica=replica.name, outcome="upstream_5xx")
            entry["error"] = f"upstream {status}"
            entry["reason"] = f"status_{status}"
            return None  # retryable: no bytes reached the client
        replica.breaker.record_success(probe=is_probe)
        self._req_total.inc(replica=replica.name, outcome="ok")
        out_headers = _filter_return_headers(resp_headers)
        if status == 429:
            # echo the replica's overload verdict upstream, always with
            # a backoff hint (never an unbounded queue)
            out_headers.setdefault(
                "Retry-After", str(max(1, int(self.retry_after_s)))
            )
            self._shed_total.inc(reason="upstream_429")
        return Response(status=status, headers=out_headers, body=payload)

    def _note_failure(self, replica: Any, entry: dict, attempt_start: float,
                      reason: str, detail: str) -> None:
        elapsed = time.monotonic() - attempt_start
        entry["error"] = detail
        entry["reason"] = reason
        entry["elapsed_ms"] = round(elapsed * 1000, 1)
        self._upstream_seconds.observe(elapsed, replica=replica.name)
        self._hop_seconds.observe(elapsed, stage="upstream")
        self._finish_attempt(replica)
        replica.breaker.record_failure()
        self._req_total.inc(replica=replica.name, outcome="network_error")
        return None

    def _finish_attempt(self, replica: Any) -> None:
        depth = replica.mark_done()
        self._outstanding_gauge.set(float(depth), replica=replica.name)

    def _stream_response(
        self,
        replica: Any,
        request: Any,
        streaming: Any,
        entry: dict[str, Any],
        attempt_start: float,
        record: dict[str, Any],
        executor: Any = None,
        is_probe: bool = False,
    ) -> Response:
        """Wrap the upstream chunk iterator for SSE passthrough. The
        handler returns immediately; accounting (outstanding, breaker,
        in-flight release, route record) completes when the body
        finishes — through an IDEMPOTENT finalizer invoked from both
        the chunk generator's ``finally`` and the async bridge's, so a
        cancelled connection task (drain, client gone, shutdown) can
        never leave the in-flight counter elevated or a half-open
        breaker probe slot claimed, even if the sync generator is
        mid-``next`` on a pool thread or was never started at all."""
        request._stream_owns_release = True
        entry["status"] = 200
        finalizer = _StreamFinalizer(
            self, replica, streaming, entry, record, attempt_start, is_probe
        )

        def chunks() -> Any:
            try:
                for chunk in streaming.iter_chunks():
                    yield chunk
            except Exception:
                finalizer.finish("upstream_error")
                raise  # the server aborts the client connection (truncated)
            finally:
                finalizer.finish("ok")

        return Response(
            status=200,
            headers=_filter_return_headers(streaming.headers),
            stream=_sync_pull(chunks(), executor, finalizer),
        )

    def _relay_response(
        self,
        replica: Any,
        request: Any,
        streaming: Any,
        entry: dict[str, Any],
        attempt_start: float,
        record: dict[str, Any],
        executor: Any,
        is_probe: bool,
        resume: _ResumeSpec,
    ) -> Response:
        """Resumable SSE passthrough: like ``_stream_response`` but the
        relay owns a retry loop — a mid-stream upstream failure resumes
        from the last delivered event id instead of truncating."""
        request._stream_owns_release = True
        entry["status"] = 200
        relay = _StreamRelay(
            self, replica, streaming, entry, record, attempt_start,
            is_probe, resume,
        )
        return Response(
            status=200,
            headers=_filter_return_headers(streaming.headers),
            stream=_sync_pull(relay.chunks(), executor, relay),
        )

    def _finish_record(self, record: dict[str, Any], status: int) -> None:
        record["status"] = status
        record["retries"] = max(0, len(record["attempts"]) - 1)
        start_mono = record.get("_start_mono")
        if start_mono is not None and "elapsed_ms" not in record:
            # end-to-end router-side latency: the minuend the trace
            # assembly decomposes into router/queue/TTFT/stream stages
            record["elapsed_ms"] = round(
                (time.monotonic() - start_mono) * 1000, 1
            )
        # outcome follows the status CLASS — a forwarded 429 or 404 is
        # not a successful route, and an operator triaging overload
        # from these records must see it agree with the shed metrics
        if status == 499:
            record["outcome"] = "aborted"
        elif status == 429:
            record["outcome"] = "shed_upstream"
        elif 200 <= status < 400:
            record["outcome"] = "ok"
        elif 400 <= status < 500:
            record["outcome"] = "client_error"
        else:
            record["outcome"] = "error"
        if record.get("_stored"):
            return
        record["_stored"] = True
        with self._records_lock:
            self._records.append(record)

    # -- admin surface ---------------------------------------------------------
    def records(self, limit: int = 50,
                request_id: Optional[str] = None) -> list[dict[str, Any]]:
        """Most-recent-first route records. ``request_id`` filters to
        the records that carried that id (the whole ring is scanned
        then — an id lookup must not miss a match because 50 newer
        requests landed)."""
        with self._records_lock:
            recent = (
                list(self._records) if request_id is not None
                else list(self._records)[-limit:]
            )
        out = []
        for r in reversed(recent):
            if request_id is not None and r.get("request_id") != request_id:
                continue
            out.append({k: v for k, v in r.items() if not k.startswith("_")})
            if len(out) >= limit:
                break
        return out

    def snapshot(self) -> dict[str, Any]:
        """``GET /admin/fleet``: the whole front door on one page. The
        view is THIS router instance's (in-flight, records, breaker and
        rotation verdicts are per-instance by design — see router_id)."""
        return {
            "router_id": self.router_id,
            "draining": self._draining,
            "in_flight": self.in_flight,
            "max_inflight": self.max_inflight,
            "retries": self.retries,
            "deadline_s": self.deadline_s,
            "role_routing": self.role_routing,
            "quota": self.quota.stats(),
            "replica_set": self.replica_set.snapshot(),
            "routes": self.records(limit=50),
        }


class _StreamFinalizer:
    """Idempotent completion accounting for one proxied stream. Invoked
    from the chunk generator's ``finally``, the async bridge's
    ``finally``, or both in either order — the FIRST call wins. Keeping
    it out of the generators means a generator that is cancelled
    mid-``next`` (``close()`` would raise 'generator already
    executing') or finalized before its first pull (its body — and
    ``finally`` — never ran) still releases everything."""

    def __init__(self, router: "FleetRouter", replica: Any, streaming: Any,
                 entry: dict[str, Any], record: dict[str, Any],
                 attempt_start: float, is_probe: bool = False):
        self._router = router
        self._replica = replica
        self._streaming = streaming
        self._entry = entry
        self._record = record
        self._attempt_start = attempt_start
        self._is_probe = is_probe
        self._done = False
        self._lock = threading.Lock()

    def finish(self, outcome: str) -> None:
        """``outcome``: "ok" (body completed), "upstream_error" (the
        REPLICA broke the stream — breaker failure), or "aborted" (the
        CLIENT walked away / the connection task was cancelled — the
        replica was serving fine, so its breaker records a success:
        punishing replicas for client disconnects would open breakers
        on a healthy fleet, and a half-open probe slot must still be
        released either way)."""
        with self._lock:
            if self._done:
                return
            self._done = True
        router, replica, entry = self._router, self._replica, self._entry
        # closing the upstream also unblocks a pool thread still parked
        # in next() on this stream — its read errors out and returns
        self._streaming.close()
        elapsed = time.monotonic() - self._attempt_start
        entry["elapsed_ms"] = round(elapsed * 1000, 1)
        router._upstream_seconds.observe(elapsed, replica=replica.name)
        router._hop_seconds.observe(elapsed, stage="stream")
        router._finish_attempt(replica)
        if outcome == "upstream_error":
            entry["error"] = "stream aborted mid-body"
            replica.breaker.record_failure()
            router._req_total.inc(replica=replica.name, outcome="network_error")
            router._finish_record(self._record, 499)
        elif outcome == "aborted":
            entry["error"] = "client abandoned the stream"
            replica.breaker.record_success(probe=self._is_probe)
            router._req_total.inc(replica=replica.name, outcome="client_aborted")
            router._finish_record(self._record, 499)
        else:
            replica.breaker.record_success(probe=self._is_probe)
            router._req_total.inc(replica=replica.name, outcome="ok")
            router._finish_record(self._record, 200)
        router._release()


def _deterministic_body(body: Any) -> bool:
    """True when the request's stream can be REGENERATED bit-identically
    (the resume precondition): an explicit seed, or explicit greedy
    sampling (temperature 0). Anything else — including the server-side
    default temperature, which this router must not assume — keeps the
    non-resumable truncate-on-failure contract."""
    if not isinstance(body, dict):
        return False
    if body.get("seed") is not None:
        return True
    temperature = body.get("temperature")
    return isinstance(temperature, (int, float)) and float(temperature) == 0.0


class _SSEEventScanner:
    """Incremental SSE event framer: feed raw chunks, get back complete
    ``(block_bytes, event_id, is_error)`` events. ``block_bytes`` is the
    verbatim wire slice (passthrough stays byte-identical); ``event_id``
    is the parsed ``id:`` line (None when absent); ``is_error`` flags
    the engine's error frame (``data: {"error": ...}``) — the signal
    that a replica's generation died mid-stream even though the HTTP
    stream ended 'cleanly'."""

    MAX_BUFFER = 1 << 20  # a frame larger than 1 MiB is not ours

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> list[tuple[bytes, Optional[int], bool]]:
        self._buf += chunk
        if len(self._buf) > self.MAX_BUFFER:
            raise ValueError("SSE frame exceeds the relay buffer bound")
        events: list[tuple[bytes, Optional[int], bool]] = []
        while True:
            idx = self._buf.find(b"\n\n")
            if idx < 0:
                return events
            block, self._buf = self._buf[:idx + 2], self._buf[idx + 2:]
            event_id: Optional[int] = None
            is_error = False
            for line in block.split(b"\n"):
                line = line.rstrip(b"\r")
                if line.startswith(b"id:"):
                    try:
                        event_id = int(line[3:].strip())
                    except ValueError:
                        pass
                elif line.startswith(b"data:") and line[5:].lstrip(
                ).startswith(b'{"error"'):
                    is_error = True
            events.append((block, event_id, is_error))


class _UpstreamStreamError(Exception):
    """A proxied stream's upstream died mid-body (transport failure or
    the replica's own error frame)."""


class _StreamRelay:
    """Resumable proxied stream: one client response spliced together
    from up to ``max_resumes + 1`` upstream attempts.

    The relay forwards complete SSE events, journaling the next
    expected event id as it goes. When the CURRENT upstream fails — a
    socket error, a read timeout, or the replica's in-band error frame
    (a wedged engine ends its stream with ``data: {"error": ...}``, not
    a reset) — the relay settles that attempt's accounting (breaker
    failure, outstanding depth, route record) and hunts for a
    continuation: the ORIGINATING replica first (it holds the
    generation journal, and probation counts as "coming back"), then
    any healthy candidate, re-issuing the request with
    ``X-Resume-From: <next id>``. Continuation events are filtered by
    id, so a replica that ignores the resume header and regenerates
    from zero still splices correctly — the deterministic-body
    precondition guarantees the regenerated frames match.

    Idempotent ``finish``-style finalization mirrors
    :class:`_StreamFinalizer` (the async bridge calls
    ``finish("aborted")`` on client disconnect)."""

    def __init__(self, router: "FleetRouter", replica: Any, streaming: Any,
                 entry: dict[str, Any], record: dict[str, Any],
                 attempt_start: float, is_probe: bool, resume: _ResumeSpec):
        self._router = router
        self._replica = replica          # current upstream's replica
        self._origin = replica           # served the original prefix
        self._streaming = streaming
        self._entry = entry              # current attempt's route entry
        self._record = record
        self._attempt_start = attempt_start
        self._is_probe = is_probe
        self._resume = resume
        self._scanner = _SSEEventScanner()
        self._next_id = 0         # next event id the client expects
        self._saw_ids = False     # the upstream actually numbers frames
        self._delivered = 0       # events actually forwarded to the client
        self._resumed = False     # current upstream is a continuation
        self._resumes = 0
        self._attempt_settled = False
        self._done = False
        self._lock = threading.Lock()

    # -- the client-facing chunk generator -------------------------------------
    def chunks(self) -> Any:
        while True:
            try:
                failed = False
                for chunk in self._streaming.iter_chunks():
                    for block in self._drain(chunk):
                        yield block
                # the upstream closed; an error frame mid-buffer still
                # counts as a failure (flagged by _drain via exception)
                self._settle_attempt("ok")
                self._finalize("ok")
                return
            except GeneratorExit:
                # client gone: _sync_pull finalizes via finish(); close
                # the CURRENT upstream here too — a continuation opened
                # after the abort would otherwise leak until GC
                self._streaming.close()
                raise
            except _UpstreamStreamError as exc:
                failed = str(exc)
            except Exception as exc:
                failed = f"{type(exc).__name__}: {exc}"
            self._settle_attempt("upstream_error", failed)
            if not self._try_resume():
                self._finalize("upstream_error")
                raise _UpstreamStreamError(
                    f"stream failed and could not resume: {failed}"
                )

    def _drain(self, chunk: bytes) -> list[bytes]:
        """Complete events from one raw chunk, filtered for delivery.
        Raises :class:`_UpstreamStreamError` on the replica's in-band
        error frame — it must never reach the client (the relay's whole
        point is to replace it with a continuation)."""
        out: list[bytes] = []
        for block, event_id, is_error in self._scanner.feed(chunk):
            if is_error:
                raise _UpstreamStreamError("replica error frame")
            if event_id is not None:
                self._saw_ids = True
                if event_id < self._next_id:
                    continue  # continuation replaying delivered events
                self._next_id = event_id + 1
            elif self._resumed:
                # id-less frames are only trustworthy from the original
                # attempt (a regenerating continuation re-emits them)
                continue
            out.append(block)
        self._delivered += len(out)
        return out

    # -- per-attempt accounting ------------------------------------------------
    def _settle_attempt(self, outcome: str, detail: str = "") -> None:
        """Close the CURRENT upstream attempt's books (idempotent per
        attempt): outstanding depth, latency histogram, breaker verdict,
        request counter. The guard is LOCKED: a client abort (event
        loop) and an upstream failure (pool thread) can race here, and
        a double settle would double-record breaker verdicts."""
        with self._lock:
            if self._attempt_settled:
                return
            self._attempt_settled = True
        router, replica = self._router, self._replica
        self._streaming.close()
        elapsed = time.monotonic() - self._attempt_start
        self._entry["elapsed_ms"] = round(elapsed * 1000, 1)
        router._upstream_seconds.observe(elapsed, replica=replica.name)
        router._hop_seconds.observe(elapsed, stage="stream")
        router._finish_attempt(replica)
        if outcome == "upstream_error":
            self._entry["error"] = detail or "stream aborted mid-body"
            self._entry["reason"] = "stream"
            replica.breaker.record_failure()
            router._req_total.inc(replica=replica.name, outcome="network_error")
        elif outcome == "aborted":
            self._entry["error"] = "client abandoned the stream"
            replica.breaker.record_success(probe=self._is_probe)
            router._req_total.inc(replica=replica.name, outcome="client_aborted")
        else:
            replica.breaker.record_success(probe=self._is_probe)
            router._req_total.inc(replica=replica.name, outcome="ok")

    def _install_attempt(self, replica: Any, streaming: Any,
                         entry: dict[str, Any], attempt_start: float,
                         is_probe: bool) -> bool:
        """Adopt a continuation upstream as the current attempt. Under
        the relay lock, and REFUSED once finalized: a client abort that
        landed while the hunt was mid-connect must not adopt (and then
        never settle) a fresh upstream — its outstanding mark and
        connection would leak forever."""
        with self._lock:
            if self._done:
                return False
            self._replica = replica
            self._streaming = streaming
            self._entry = entry
            self._attempt_start = attempt_start
            self._is_probe = is_probe
            self._scanner = _SSEEventScanner()
            # a continuation opened before ANYTHING reached the client
            # is indistinguishable from a fresh original attempt, and
            # must deliver like one: with _resumed set, _drain drops
            # id-less frames (only trustworthy from the original), so
            # an id-less continuation of a died-at-zero stream would
            # have every frame dropped and settle as a silently EMPTY
            # "ok" — exactly the truncation-masquerading-as-success the
            # resume contract exists to prevent
            self._resumed = self._saw_ids or self._delivered > 0
            self._attempt_settled = False
        return True

    # -- the resume hunt -------------------------------------------------------
    def _pick_resume_target(
        self, tried: set[str]
    ) -> Optional[tuple[Any, bool]]:
        """The originating replica first — it holds the generation
        journal (teacher-forced resume is nearly free there), and its
        PROBATION state counts as "coming back" rather than hard-out —
        then any healthy candidate the breaker admits. Replicas that
        already failed THIS hunt (``tried``) are skipped on the first
        pass and allowed back only as a last resort: the prober needs
        out_after×interval to evict a drained replica, and during that
        window the dead origin still LOOKS healthy — re-picking it
        every round burned the whole resume budget on connection
        refusals in milliseconds (the fleetsim harness surfaced exactly
        that: drained-mid-stream requests exhausting 4 resumes in 50 ms
        while healthy replicas sat idle)."""
        candidates: list[Any] = []
        if self._origin.state in (HEALTHY, PROBATION):
            candidates.append(self._origin)
        candidates.extend(
            r for r in self._router.replica_set.candidates(
                self._resume.affinity
            )
            if r.name != self._origin.name
        )
        for skip_tried in (True, False) if tried else (False,):
            for replica in candidates:
                if skip_tried and replica.name in tried:
                    continue
                grant = replica.breaker.try_acquire()
                if grant:
                    return replica, grant == breaker_mod.PROBE
        return None

    def _try_resume(self) -> bool:
        router = self._router
        if not self._saw_ids and self._delivered:
            # id-less frames already reached the client (e.g. a fan-out
            # stream): without ids a continuation cannot be spliced —
            # its frames would all be dropped and the truncation would
            # masquerade as success. Keep the abort contract. A stream
            # that died before ANY event was delivered is different:
            # resuming from 0 is trivially safe (nothing to splice
            # against), and refusing it turned every
            # wedge-before-first-token into a truncated client stream —
            # the fleetsim harness surfaced exactly that cohort.
            router._stream_resumes.inc(outcome="refused")
            return False
        # failed-attempt pacing, mirroring the forward retry loop: a
        # decorrelated-jitter sleep between failed continuations gives
        # the prober time to evict a dead origin (and a transient 5xx
        # burst time to pass) instead of spending the whole resume
        # budget inside one failure window
        tried: set[str] = set()
        delays = backoff_delays(router.max_resumes)
        while True:
            with self._lock:
                if self._done:
                    return False  # client already abandoned the stream
            remaining = self._resume.deadline - time.monotonic()
            if remaining <= 0.05 or self._resumes >= router.max_resumes:
                router._stream_resumes.inc(outcome="exhausted")
                return False
            picked = self._pick_resume_target(tried)
            if picked is None:
                # nothing admitted right now: the origin may be mid-
                # recovery (probation arrives within a probe interval)
                time.sleep(min(0.1, remaining))
                continue
            replica, is_probe = picked
            tried.add(replica.name)
            self._resumes += 1
            self._record["resumes"] = self._resumes
            router._retries_total.inc(
                replica=self._replica.name, reason="stream_resume"
            )
            headers = dict(self._resume.headers)
            headers["X-Resume-From"] = str(self._next_id)
            # hop provenance for the continuation: same router, the
            # attempt index this entry lands at, and the event id it
            # resumes from — the replica's FlightRecord origin block
            # then distinguishes "a fresh attempt" from "a splice".
            # traceparent rides _resume.headers untouched (stamped once
            # in _forward), so the continuation parents to the ORIGINAL
            # request span even from this pool thread.
            headers["X-Gofr-Hop"] = format_hop(
                router.router_id, len(self._record["attempts"]),
                self._next_id,
            )
            # a budgeted continuation gets the remaining budget, never
            # the original attempt's stale stamp; an opted-out stream
            # stays opted out
            if self._resume.budgeted:
                headers["X-Request-Deadline-Ms"] = str(
                    max(1, int(remaining * 1000))
                )
            entry: dict[str, Any] = {
                "replica": replica.name, "status": None, "error": None,
                "elapsed_ms": 0, "resume_from": self._next_id,
            }
            self._record["attempts"].append(entry)
            depth = replica.mark_dispatch()
            router._outstanding_gauge.set(float(depth), replica=replica.name)
            attempt_start = time.monotonic()
            try:
                streaming = replica.client.stream(
                    self._resume.method, self._resume.target,
                    body=self._resume.body, headers=headers,
                    connect_timeout=min(router.connect_timeout_s, remaining),
                    read_timeout=min(router.read_timeout_s, remaining),
                )
            except Exception as exc:
                entry["error"] = str(exc)
                entry["elapsed_ms"] = round(
                    (time.monotonic() - attempt_start) * 1000, 1
                )
                router._finish_attempt(replica)
                replica.breaker.record_failure()
                router._req_total.inc(
                    replica=replica.name, outcome="network_error"
                )
                self._hunt_pause(delays)
                continue
            status = streaming.status_code
            if status == 200:
                entry["status"] = 200
                if not self._install_attempt(
                    replica, streaming, entry, attempt_start, is_probe
                ):
                    # the client aborted while we connected: settle this
                    # never-adopted upstream and stop hunting
                    streaming.close()
                    router._finish_attempt(replica)
                    replica.breaker.record_success(probe=is_probe)
                    return False
                router._stream_resumes.inc(outcome="resumed")
                router._hop_seconds.observe(
                    time.monotonic() - attempt_start, stage="resume"
                )
                return True
            # non-200: drain bounded, close, judge
            try:
                streaming.read(budget_s=min(2.0, remaining))
            except Exception:
                pass  # the error body is best-effort evidence only
            streaming.close()
            entry["status"] = status
            entry["elapsed_ms"] = round(
                (time.monotonic() - attempt_start) * 1000, 1
            )
            router._finish_attempt(replica)
            if status >= 500:
                replica.breaker.record_failure()
                router._req_total.inc(
                    replica=replica.name, outcome="upstream_5xx"
                )
                self._hunt_pause(delays)
                continue
            # 4xx: the replica is healthy but refuses the resume
            # (non-resumable shape, journal gone AND determinism
            # rejected, …) — continuing elsewhere cannot help
            replica.breaker.record_success(probe=is_probe)
            router._stream_resumes.inc(outcome="refused")
            return False

    def _hunt_pause(self, delays: Any) -> None:
        """Sleep the hunt's next decorrelated-jitter delay, clipped to
        the remaining deadline (a hunt never sleeps past its budget —
        the loop head turns that into a clean ``exhausted``)."""
        delay = next(delays, None)
        if delay is None:
            return
        remaining = self._resume.deadline - time.monotonic()
        if remaining > 0.05:
            time.sleep(min(delay, remaining - 0.05))

    # -- terminal accounting ---------------------------------------------------
    def finish(self, outcome: str) -> None:
        """Async-bridge finalizer hook (client disconnect / task
        cancellation). After a NORMAL completion ``_finalize`` already
        ran — the idempotency guard makes this a no-op then."""
        del outcome  # the bridge only ever reports an abort
        self._settle_attempt_safe("aborted")
        self._finalize("aborted")

    def _settle_attempt_safe(self, outcome: str) -> None:
        with self._lock:
            if self._done:
                return
        self._settle_attempt(outcome)

    def _finalize(self, outcome: str) -> None:
        with self._lock:
            if self._done:
                return
            self._done = True
        router = self._router
        if outcome == "ok":
            router._finish_record(self._record, 200)
        else:
            router._finish_record(self._record, 499)
        router._release()


def _filter_return_headers(headers: dict[str, str]) -> dict[str, str]:
    """The response-header allowlist applied to BOTH the buffered and
    streaming forward paths."""
    return {
        name.title(): value
        for name, value in ((k.lower(), v) for k, v in headers.items())
        if name in _RETURN_HEADERS
    }


async def _sync_pull(iterator: Any, executor: Any = None,
                     finalizer: Any = None) -> Any:
    """Bridge a sync chunk generator onto the event loop: each ``next``
    is pulled on the container's I/O-sized handler pool so a slow
    upstream never stalls other connections (same rationale as the
    responder's Stream path — asyncio's cpu_count+4 default executor
    caps concurrent proxied streams on small VMs).

    The ``finally`` runs when this async generator is finalized (client
    disconnect / task cancellation → the loop's async-gen finalizer →
    GeneratorExit here): it settles the stream's accounting through the
    idempotent ``finalizer`` DIRECTLY — never through the sync
    generator, which may be suspended mid-``next`` on a pool thread.
    All inline work is socket close + metric writes, no blocking I/O."""
    import asyncio

    loop = asyncio.get_running_loop()
    it = iter(iterator)
    sentinel = object()
    try:
        while True:
            item = await loop.run_in_executor(executor, next, it, sentinel)
            if item is sentinel:
                break
            yield item
    finally:
        if finalizer is not None:
            # an abandoned stream is a CLIENT-side outcome, not a
            # replica failure; a normally-finished (or upstream-failed)
            # stream already settled — finish is then a no-op
            finalizer.finish("aborted")
        try:
            close = getattr(it, "close", None)
            if close is not None:
                close()
        except ValueError:
            pass  # generator mid-next on a pool thread; it exits on its own
