"""The cross-replica paged-KV wire format + the per-request donor hint.

Disaggregated prefill/decode (ROADMAP 1) hands WARM KV between
replicas: a prefill (or previously-visited) replica serves its cached
block tables over ``GET /admin/kv/<prompt_hash>`` and a decode replica
aliases them straight into its own BlockPool instead of re-prefilling.
A KV transfer is a new distributed failure surface — the donor can
wedge mid-send, the payload can be truncated or bit-flipped in flight,
the entry can be evicted between advertise and pull — so the format is
built to make every failure DETECTABLE by the receiver, which then
falls back to local chunked prefill (the request always completes):

- a versioned magic + JSON header (prompt hash, sampling identity,
  arena wire spec, block count, entry meta) — version/spec skew between
  mismatched replicas is caught before any payload is trusted;
- per-block frames, each carrying its own CRC32 — a flipped byte is
  caught at the block it corrupts, never installed;
- a mandatory trailer frame carrying the block count — a mid-stream
  disconnect (donor killed, socket cut) leaves the trailer missing and
  the partial read is detected instead of half-installed.

The payload encoding is the ARENA's: :class:`HostTokenArena` ships
token ids (the echo runner's "KV"), so the whole protocol — pull,
verify, ingest, alias, fall back — runs compile-free in tier-1;
:class:`JaxKVArena` ships raw per-block k/v bytes. Both sides compare
``wire_spec()`` dicts, so a block-size or dtype mismatch is a clean
version-skew refusal, not silent corruption.

Import-light on purpose (stdlib + numpy): the router, the handlers,
and ``tpu/kv_blocks.py`` all import this module without paying for the
rest of the fleet package — use ``from gofr_tpu.fleet import kvwire``
style imports, never through ``gofr_tpu.fleet``'s __init__ exports.
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import struct
import zlib
from typing import Any, Iterable, Iterator, Optional

import numpy as np

WIRE_VERSION = 1
MAGIC = b"GKV1"
# trailer frame index: no real block table reaches 2**32 - 1 entries
END_INDEX = 0xFFFFFFFF
_U32 = struct.Struct("<I")
_FRAME_HEAD = struct.Struct("<III")  # index, payload_len, crc32
# a single block's payload is bounded by the arena's block_bytes (a few
# MiB for real models); anything past this is a framing error, not data
MAX_BLOCK_BYTES = 1 << 26
MAX_HEADER_BYTES = 1 << 16

CONTENT_TYPE = "application/x-gofr-kv"

TRANSFER_OUTCOMES = ("ok", "timeout", "corrupt", "evicted", "fallback")


class KVWireError(Exception):
    """The transfer stream cannot be trusted; the receiver falls back
    to local prefill. ``outcome`` is the
    ``gofr_tpu_kv_transfer_total{outcome}`` label the failure counts
    under."""

    outcome = "corrupt"


class VersionSkew(KVWireError):
    """The peers speak different wire versions or incompatible arena
    specs (block size, payload kind, dtype/shape) — counted as
    ``corrupt``: the bytes are not installable here, whatever they
    meant to the sender."""


class ChecksumMismatch(KVWireError):
    """A block frame's payload does not match its CRC (or frames arrive
    out of order / oversized) — the transport flipped bytes."""


class Truncated(KVWireError):
    """The stream ended before the trailer frame: the donor died (or
    was killed) mid-send, or an intermediary cut the body."""


def prompt_hash(ids: Any) -> str:
    """The transfer identity of a token sequence: sha256 over its int32
    bytes — EXACTLY the bytes the paged prefix caches key on
    (``ids.tobytes()``), so a donor's cache scan and a receiver's local
    recompute agree without ever shipping the raw prompt (prompts are
    user data; only the hash rides URLs and route records)."""
    ids = np.asarray(ids, np.int32).reshape(-1)
    return hashlib.sha256(ids.tobytes()).hexdigest()[:32]


def hash_of_key(key: bytes) -> str:
    """:func:`prompt_hash` for an already-encoded cache key."""
    return hashlib.sha256(key).hexdigest()[:32]


def transfer_counter(metrics: Any) -> Any:
    """The ONE registration of ``gofr_tpu_kv_transfer_total`` (same
    single-home contract as the deadline counters): the receiving end
    counts each pull's outcome — ok, timeout (donor unreachable/stalled
    past the budget), corrupt (checksum/version/truncation), evicted
    (donor 404: the entry vanished between advertise and pull) — plus
    one ``fallback`` increment whenever the request proceeds on local
    prefill instead."""
    return metrics.counter(
        "gofr_tpu_kv_transfer_total",
        "cross-replica KV-transfer pulls by outcome (ok | timeout | "
        "corrupt | evicted), plus fallback (request completed via "
        "local prefill after a failed pull)",
        labels=("outcome",),
    )


# -- the donor hint ----------------------------------------------------------
# The fleet router stamps X-KV-Donor on decode-bound requests: the base
# URL of the replica that rendezvous-owns the prompt's KV. It travels
# to the device layer exactly like the deadline: a contextvar activated
# at admission, read once by TPU.generate before paged admission.
_kv_hint: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "gofr_kv_donor_hint", default=None
)


def current_kv_hint() -> Optional[str]:
    """The in-flight request's KV-donor base URL, if admission parsed
    one."""
    return _kv_hint.get()


def activate_kv_hint(hint: Optional[str]) -> Any:
    """Bind the donor hint (None clears); handlers run inside a
    per-request copied context, so nothing leaks past the request."""
    return _kv_hint.set(hint)


def parse_kv_hint(raw: Optional[str]) -> Optional[str]:
    """Validate an ``X-KV-Donor`` header into a donor base URL. Only a
    plain ``http(s)://host[:port]`` shape is accepted — the header
    names a PEER REPLICA, and a replica must never be steerable into
    fetching arbitrary URLs (paths, userinfo, or schemes are rejected,
    not sanitized). Garbage returns None: a malformed hint degrades to
    local prefill, never to a 4xx."""
    if not raw:
        return None
    raw = raw.strip()
    if len(raw) > 256:
        return None
    from urllib.parse import urlsplit

    try:
        parts = urlsplit(raw)
    except ValueError:
        return None
    if parts.scheme not in ("http", "https"):
        return None
    if not parts.hostname or parts.username or parts.password:
        return None
    if parts.path not in ("", "/") or parts.query or parts.fragment:
        return None
    try:
        port = parts.port  # raises on garbage like :abc
    except ValueError:
        return None
    host = parts.hostname
    if ":" in host:  # bare IPv6 needs brackets back
        host = f"[{host}]"
    return f"{parts.scheme}://{host}" + (f":{port}" if port else "")


# -- encoding ----------------------------------------------------------------

def encode_header(spec: dict) -> bytes:
    """``MAGIC + u32 length + header json``. ``spec`` must carry
    ``version`` (stamped here), the prompt hash, the arena
    ``wire_spec()`` fields, ``length``, ``n_blocks``, and the entry
    ``meta``."""
    payload = dict(spec)
    payload["version"] = WIRE_VERSION
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_HEADER_BYTES:
        raise ValueError(f"wire header {len(body)}B exceeds the bound")
    return MAGIC + _U32.pack(len(body)) + body


def encode_block(index: int, payload: bytes) -> bytes:
    """One block frame: ``u32 index + u32 len + u32 crc + payload``."""
    if len(payload) > MAX_BLOCK_BYTES:
        raise ValueError(f"block payload {len(payload)}B exceeds the bound")
    return _FRAME_HEAD.pack(index, len(payload), zlib.crc32(payload)) + payload


def encode_trailer(n_blocks: int) -> bytes:
    """The end-of-stream frame: index ``END_INDEX``, payload = the
    block count the receiver must have seen. Its ABSENCE is how a
    partial read is detected."""
    payload = _U32.pack(n_blocks)
    return _FRAME_HEAD.pack(
        END_INDEX, len(payload), zlib.crc32(payload)
    ) + payload


def encode_entry(spec: dict, payloads: Iterable[bytes]) -> Iterator[bytes]:
    """Frame a whole entry (header, blocks in order, trailer) — the
    bench/test convenience; the serving handler streams the same frames
    lazily so pins release on socket close."""
    yield encode_header(spec)
    n = 0
    for payload in payloads:
        yield encode_block(n, payload)
        n += 1
    yield encode_trailer(n)


# -- decoding ----------------------------------------------------------------

class WireDecoder:
    """Incremental decoder: feed raw chunks as they arrive off the
    socket, collect events — chunk boundaries never align with frame
    boundaries on a real wire. Events are ``("header", dict)``,
    ``("block", index, payload)``, ``("end", n_blocks)``. Every
    integrity failure raises a :class:`KVWireError` subclass
    immediately; :meth:`finish` raises :class:`Truncated` unless the
    trailer arrived."""

    def __init__(self, max_blocks: Optional[int] = None) -> None:
        # bytearray + consumed-offset, compacted when the consumed
        # prefix dominates: feed() stays O(bytes) end to end — a
        # `bytes += chunk` buffer re-copies every buffered byte per
        # chunk, which on MiB device blocks arriving in 8 KiB reads is
        # exactly the transfer latency the bench gate measures
        self._buf = bytearray()
        self._pos = 0
        self._header: Optional[dict] = None
        self._blocks_seen = 0
        self._ended = False
        # receiver-side bound: the caller knows how many blocks the
        # prompt can legitimately need; a donor claiming more is
        # refused at the header, before any payload is buffered
        self._max_blocks = max_blocks
        self._expect_blocks: Optional[int] = None

    def _remaining(self) -> int:
        return len(self._buf) - self._pos

    def feed(self, chunk: bytes) -> list:
        if chunk:
            self._buf += chunk
        events: list = []
        while True:
            event = self._next_event()
            if event is None:
                if self._pos and self._pos * 2 >= len(self._buf):
                    del self._buf[:self._pos]
                    self._pos = 0
                return events
            events.append(event)

    def _next_event(self) -> Optional[tuple]:
        if self._ended and self._remaining():
            raise ChecksumMismatch("bytes after the trailer frame")
        if self._header is None:
            return self._parse_header()
        if self._remaining() < _FRAME_HEAD.size:
            return None
        index, length, crc = _FRAME_HEAD.unpack_from(self._buf, self._pos)
        if (
            index != END_INDEX
            and self._expect_blocks is not None
            and index >= self._expect_blocks
        ):
            # refuse BEFORE buffering the payload: without this a donor
            # could stream unbounded frames past the header's claim and
            # balloon receiver memory until the post-hoc count check
            raise ChecksumMismatch(
                f"frame {index} beyond the header's "
                f"{self._expect_blocks}-block claim"
            )
        if length > MAX_BLOCK_BYTES:
            raise ChecksumMismatch(
                f"frame {index} claims {length}B (bound {MAX_BLOCK_BYTES})"
            )
        if self._remaining() < _FRAME_HEAD.size + length:
            return None
        start = self._pos + _FRAME_HEAD.size
        payload = bytes(self._buf[start:start + length])
        self._pos = start + length
        if zlib.crc32(payload) != crc:
            raise ChecksumMismatch(f"frame {index} failed its CRC")
        if index == END_INDEX:
            if len(payload) != _U32.size:
                # a CRC-valid but mis-sized trailer must stay inside
                # the KVWireError contract, not escape as struct.error
                raise ChecksumMismatch(
                    f"trailer payload is {length}B (expected {_U32.size})"
                )
            (count,) = _U32.unpack(payload)
            if count != self._blocks_seen:
                raise Truncated(
                    f"trailer promises {count} blocks, saw {self._blocks_seen}"
                )
            if (
                self._expect_blocks is not None
                and count != self._expect_blocks
            ):
                raise Truncated(
                    f"trailer count {count} short of the header's "
                    f"{self._expect_blocks}-block claim"
                )
            self._ended = True
            return ("end", count)
        if index != self._blocks_seen:
            raise ChecksumMismatch(
                f"frame {index} arrived out of order (expected "
                f"{self._blocks_seen})"
            )
        self._blocks_seen += 1
        return ("block", index, payload)

    def _parse_header(self) -> Optional[tuple]:
        if self._remaining() < len(MAGIC) + _U32.size:
            return None
        magic = bytes(self._buf[self._pos:self._pos + len(MAGIC)])
        if magic != MAGIC:
            raise VersionSkew(
                f"bad magic {magic!r} (speaking {MAGIC.decode()}?)"
            )
        (length,) = _U32.unpack_from(self._buf, self._pos + len(MAGIC))
        if length > MAX_HEADER_BYTES:
            raise VersionSkew(f"header claims {length}B (bound exceeded)")
        if self._remaining() < len(MAGIC) + _U32.size + length:
            return None
        start = self._pos + len(MAGIC) + _U32.size
        body = bytes(self._buf[start:start + length])
        self._pos = start + length
        try:
            header = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise VersionSkew(f"unparseable header: {exc}") from exc
        if not isinstance(header, dict):
            raise VersionSkew("header is not an object")
        if header.get("version") != WIRE_VERSION:
            raise VersionSkew(
                f"wire version {header.get('version')!r} "
                f"(this replica speaks {WIRE_VERSION})"
            )
        n_blocks = header.get("n_blocks")
        if (
            not isinstance(n_blocks, int)
            or isinstance(n_blocks, bool)
            or n_blocks < 0
        ):
            raise VersionSkew(f"header n_blocks {n_blocks!r} is not a count")
        if self._max_blocks is not None and n_blocks > self._max_blocks:
            raise VersionSkew(
                f"header claims {n_blocks} blocks; this receiver expects "
                f"at most {self._max_blocks}"
            )
        self._expect_blocks = n_blocks
        self._header = header
        return ("header", header)

    @property
    def complete(self) -> bool:
        return self._ended

    def finish(self) -> None:
        if not self._ended:
            raise Truncated(
                "stream ended before the trailer frame "
                f"({self._blocks_seen} blocks received)"
            )


def decode_stream(
    chunks: Iterable[bytes], max_blocks: Optional[int] = None
) -> tuple[dict, list[bytes]]:
    """Decode a whole pull: returns ``(header, ordered block payloads)``
    or raises a :class:`KVWireError` subclass the moment the stream
    stops being trustworthy. Pass ``max_blocks`` (the count the prompt
    can legitimately need) so an over-claiming donor is refused at the
    header instead of buffered."""
    decoder = WireDecoder(max_blocks=max_blocks)
    header: Optional[dict] = None
    payloads: list[bytes] = []
    for chunk in chunks:
        for event in decoder.feed(chunk):
            if event[0] == "header":
                header = event[1]
            elif event[0] == "block":
                payloads.append(event[2])
    decoder.finish()
    assert header is not None  # finish() raised otherwise
    return header, payloads


def check_spec(header: dict, local_spec: dict) -> None:
    """Compare the donor's arena wire spec against the local arena's;
    any divergence is :class:`VersionSkew` (the payload cannot be
    installed here)."""
    for field, want in local_spec.items():
        got = header.get(field)
        if got != want:
            raise VersionSkew(
                f"arena spec mismatch on {field!r}: donor sent {got!r}, "
                f"local arena wants {want!r}"
            )
