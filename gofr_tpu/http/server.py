"""From-scratch asyncio HTTP/1.1 server.

Parity: /root/reference/pkg/gofr/httpServer.go:12-36 (net/http server around
the router, 5s header read timeout). Built on asyncio rather than a
third-party stack so the TPU batching queue and request futures share one
event loop (SURVEY.md §7 hard part (b): deadline-based batch flush without
destroying p50 TTFT).

Features: keep-alive, Content-Length and chunked request bodies, chunked
streaming responses (SSE), HEAD handling, header-size limits, per-connection
read timeouts.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from typing import Any, Optional

from gofr_tpu.http.request import Request
from gofr_tpu.http.response import Response
from gofr_tpu.http.router import Router

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024
READ_HEADER_TIMEOUT = 5.0  # parity: httpServer.go:32 ReadHeaderTimeout 5s
READ_BODY_TIMEOUT = 60.0  # slow-body (slowloris) guard


class _BodyError(Exception):
    def __init__(self, status: int, body: bytes):
        super().__init__(body.decode())
        self.status = status
        self.body = body

_STATUS_TEXT = {
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    301: "Moved Permanently", 302: "Found", 304: "Not Modified",
    400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 501: "Not Implemented",
    502: "Bad Gateway", 503: "Service Unavailable",
    504: "Gateway Timeout",
}


class HTTPServer:
    """Serves a Router on a port. ``run()`` blocks; ``run_in_thread()``
    starts a daemon thread and returns once the socket is listening (the
    test-friendly shape the reference gets from httptest)."""

    def __init__(self, router: Router, port: int, logger: Any = None, host: str = "0.0.0.0"):
        self.router = router
        self.port = port
        self.host = host
        self.logger = logger
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        asyncio.run(self.serve())

    async def serve(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port,
            reuse_address=True, backlog=1024,
        )
        self._ready.set()
        if self.logger:
            self.logger.infof("starting HTTP server on port %s", self.port)
        async with self._server:
            await self._server.serve_forever()

    def run_in_thread(self) -> "HTTPServer":
        self._thread = threading.Thread(target=self._run_quiet, daemon=True, name="gofr-http")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError(f"HTTP server failed to start on port {self.port}")
        return self

    def _run_quiet(self) -> None:
        try:
            self.run()
        except asyncio.CancelledError:
            pass

    def shutdown(self) -> None:
        loop = self._loop
        if loop and loop.is_running():
            loop.call_soon_threadsafe(self._shutdown_in_loop)
        if self._thread:
            self._thread.join(timeout=5)

    def _shutdown_in_loop(self) -> None:
        if self._server:
            self._server.close()
        for task in asyncio.all_tasks(self._loop):
            task.cancel()

    # -- connection handling ------------------------------------------------
    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        remote = peer[0] if isinstance(peer, tuple) else ""
        try:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        try:
            while True:
                keep_alive = await self._handle_one(reader, writer, remote)
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.TimeoutError):
            pass  # routine client disconnects (reset, broken pipe, abort)
        except asyncio.LimitOverrunError:
            await self._write_simple(writer, 431, b'{"error":{"message":"headers too large"}}')
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _handle_one(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, remote: str
    ) -> bool:
        try:
            header_block = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout=READ_HEADER_TIMEOUT
            )
        except asyncio.TimeoutError:
            return False
        if len(header_block) > MAX_HEADER_BYTES:
            await self._write_simple(writer, 431, b'{"error":{"message":"headers too large"}}')
            return False

        try:
            method, target, version, headers = _parse_head(header_block)
        except ValueError:
            await self._write_simple(writer, 400, b'{"error":{"message":"malformed request"}}')
            return False

        body = b""
        te = headers.get("transfer-encoding", "").lower()
        if "chunked" in te:
            try:
                body = await asyncio.wait_for(_read_chunked(reader), timeout=READ_BODY_TIMEOUT)
            except _BodyError as exc:
                await self._write_simple(writer, exc.status, exc.body)
                return False
            except asyncio.TimeoutError:
                await self._write_simple(
                    writer, 408, b'{"error":{"message":"body read timed out"}}')
                return False
        else:
            length = headers.get("content-length")
            if length:
                try:
                    n = int(length)
                except ValueError:
                    await self._write_simple(
                        writer, 400, b'{"error":{"message":"bad content-length"}}')
                    return False
                if n > MAX_BODY_BYTES:
                    await self._write_simple(
                        writer, 413, b'{"error":{"message":"payload too large"}}')
                    return False
                if n:
                    try:
                        body = await asyncio.wait_for(
                            reader.readexactly(n), timeout=READ_BODY_TIMEOUT
                        )
                    except asyncio.TimeoutError:
                        await self._write_simple(
                            writer, 408, b'{"error":{"message":"body read timed out"}}'
                        )
                        return False

        request = Request(method, target, headers, body, remote)
        try:
            response = await self.router.dispatcher()(request)
        except Exception:  # last-resort guard; logging middleware recovers first
            response = Response(
                status=500,
                headers={"Content-Type": "application/json"},
                body=b'{"error":{"message":"some unexpected error has occurred"}}',
            )

        want_keep_alive = (
            version != "HTTP/1.0"
            and headers.get("connection", "").lower() != "close"
        )
        head_only = method == "HEAD"
        await self._write_response(writer, response, want_keep_alive, head_only)
        return want_keep_alive and response.stream is None

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        response: Response,
        keep_alive: bool,
        head_only: bool,
    ) -> None:
        status = response.status
        reason = _STATUS_TEXT.get(status, "Unknown")
        lines = [f"HTTP/1.1 {status} {reason}"]
        headers = dict(response.headers)
        headers.setdefault("Server", "gofr-tpu")
        if response.stream is not None and not head_only:
            headers["Transfer-Encoding"] = "chunked"
            headers.pop("Content-Length", None)
        else:
            # HEAD advertises the length GET would return (RFC 9110 §9.3.2)
            headers["Content-Length"] = str(len(response.body))
        headers["Connection"] = "keep-alive" if keep_alive and response.stream is None else "close"
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        writer.write(head)
        if head_only:
            await writer.drain()
            return
        if response.stream is not None:
            try:
                async for chunk in response.stream:
                    if not chunk:
                        continue
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    await writer.drain()
            except Exception as exc:
                # Abort WITHOUT the terminal chunk so the client sees a
                # truncated chunked body (distinguishable from completion).
                if self.logger:
                    self.logger.errorf("response stream aborted: %r", exc)
                transport = writer.transport
                if transport is not None:
                    transport.abort()
                # close the response stream NOW, not at GC: its finally
                # (the responder's client-abort hook) trips the
                # generation's stop event, so an abandoned stream frees
                # its decode slot and paged-KV blocks within one chunk
                # instead of decoding to max_tokens unread
                aclose = getattr(response.stream, "aclose", None)
                if aclose is not None:
                    try:
                        await aclose()
                    except Exception:
                        pass  # teardown best-effort; the abort already won
                return
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        else:
            writer.write(response.body)
            await writer.drain()

    async def _write_simple(self, writer: asyncio.StreamWriter, status: int, body: bytes) -> None:
        try:
            await self._write_response(
                writer,
                Response(status=status, headers={"Content-Type": "application/json"}, body=body),
                keep_alive=False,
                head_only=False,
            )
        except (ConnectionResetError, BrokenPipeError):
            pass


def _parse_head(block: bytes) -> tuple[str, str, str, dict[str, str]]:
    text = block.decode("latin-1")
    lines = text.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3:
        raise ValueError("bad request line")
    method, target, version = parts
    if not version.startswith("HTTP/"):
        raise ValueError("bad version")
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        if ":" not in line:
            raise ValueError("bad header")
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    return method.upper(), target, version, headers


async def _read_chunked(reader: asyncio.StreamReader) -> bytes:
    chunks: list[bytes] = []
    total = 0
    while True:
        size_line = await reader.readuntil(b"\r\n")
        try:
            size = int(size_line.strip().split(b";")[0], 16)
        except ValueError:
            raise _BodyError(400, b'{"error":{"message":"bad chunk size"}}') from None
        if size == 0:
            await reader.readuntil(b"\r\n")  # trailing CRLF (no trailer support)
            break
        total += size
        if total > MAX_BODY_BYTES:
            raise _BodyError(413, b'{"error":{"message":"payload too large"}}')
        chunks.append(await reader.readexactly(size))
        await reader.readexactly(2)  # CRLF after each chunk
    return b"".join(chunks)
