"""HTTP transport: asyncio HTTP/1.1 server, router, middleware,
request/responder, and response types.

Parity: /root/reference/pkg/gofr/http/ (router.go, request.go, responder.go,
middleware/, response/). The server itself is built from scratch on asyncio
instead of wrapping a third-party stack — the TPU-native hot path (dynamic
batching in front of device execution) wants the event loop in-framework so
request futures and batch flush deadlines share one scheduler.
"""

from gofr_tpu.http.request import Request
from gofr_tpu.http.response import File, Raw, Response, Stream
from gofr_tpu.http.responder import respond
from gofr_tpu.http.router import Router
from gofr_tpu.http.server import HTTPServer

__all__ = ["Request", "Response", "Raw", "File", "Stream", "respond", "Router", "HTTPServer"]
