"""HTTP middleware: tracing, access-logging with panic recovery, CORS.

Parity: /root/reference/pkg/gofr/http/middleware/ —
- tracer.go:11-23: root SERVER span named "METHOD /path";
- logger.go:24-114: timed RequestLog (trace id, method, uri, ip, status,
  response time µs), X-Correlation-ID response header from the trace id
  (:46-47), client IP from X-Forwarded-For (:72-84), and panic recovery
  returning a JSON 500 with a logged stack trace (:91-114);
- cors.go:5-19: permissive wildcard CORS with OPTIONS short-circuit.

Middleware compose as ``mw(next_endpoint) -> endpoint`` over async endpoints
(installed by the router, router.go:19-23 parity).
"""

from __future__ import annotations

import time
import traceback
from dataclasses import dataclass
from typing import Any

from gofr_tpu.http.request import Request
from gofr_tpu.http.response import Response
from gofr_tpu.http.router import Endpoint
from gofr_tpu.tracing import SERVER, get_tracer


@dataclass
class RequestLog:
    """Typed access-log entry (parity: middleware/logger.go:24-33)."""

    trace_id: str
    method: str
    uri: str
    ip: str
    status: int
    response_time_us: int
    user_agent: str = ""

    def pretty_terminal(self) -> str:
        color = 32 if self.status < 400 else (33 if self.status < 500 else 31)
        return (
            f"\x1b[{color}m{self.status}\x1b[0m "
            f"{self.method:<7s} {self.uri} {self.response_time_us}µs {self.ip}"
        )

    def log_fields(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "method": self.method,
            "uri": self.uri,
            "ip": self.ip,
            "status": self.status,
            "response_time_us": self.response_time_us,
            "user_agent": self.user_agent,
        }


def client_ip(request: Request) -> str:
    """Parity: middleware/logger.go:72-84 — first X-Forwarded-For hop."""
    fwd = request.header("x-forwarded-for")
    if fwd:
        return fwd.split(",")[0].strip()
    return request.remote_addr


def tracer_middleware(next_ep: Endpoint) -> Endpoint:
    """Root server span per request (parity: middleware/tracer.go:11-23)."""

    async def endpoint(request: Request) -> Response:
        tracer = get_tracer()
        span = tracer.start_span(
            f"{request.method} {request.path}",
            kind=SERVER,
            traceparent=request.header("traceparent"),
        )
        try:
            response = await next_ep(request)
            span.set_tag("http.status_code", response.status)
            return response
        finally:
            span.__exit__(None, None, None)

    return endpoint


def logging_middleware(logger: Any) -> Any:
    """Access log + recovery (parity: middleware/logger.go:41-114)."""

    def middleware(next_ep: Endpoint) -> Endpoint:
        async def endpoint(request: Request) -> Response:
            from gofr_tpu.tracing import current_trace_id

            start = time.perf_counter()
            trace_id = current_trace_id() or ""
            try:
                response = await next_ep(request)
            except Exception:
                # Panic recovery: JSON 500 + stack trace log (logger.go:91-114).
                logger.error(
                    {"error": "panic recovered",
                     "stack": traceback.format_exc(), "trace_id": trace_id}
                )
                response = Response(
                    status=500,
                    headers={"Content-Type": "application/json"},
                    body=b'{"error":{"message":"some unexpected error has occurred"}}',
                )
            elapsed_us = int((time.perf_counter() - start) * 1e6)
            if trace_id:
                response.headers.setdefault("X-Correlation-ID", trace_id)
            logger.info(
                RequestLog(
                    trace_id=trace_id,
                    method=request.method,
                    uri=request.target,
                    ip=client_ip(request),
                    status=response.status,
                    response_time_us=elapsed_us,
                    user_agent=request.header("user-agent"),
                )
            )
            return response

        return endpoint

    return middleware


def cors_middleware(next_ep: Endpoint) -> Endpoint:
    """Permissive CORS (parity: middleware/cors.go:5-19)."""

    async def endpoint(request: Request) -> Response:
        if request.method == "OPTIONS":
            return Response(status=200, headers=dict(_CORS_HEADERS))
        response = await next_ep(request)
        response.headers.setdefault("Access-Control-Allow-Origin", "*")
        return response

    return endpoint


_CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, PUT, PATCH, DELETE, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type, Authorization, Traceparent",
}


def metrics_middleware(registry: Any) -> Any:
    """TPU-native addition: request counters + latency histogram for every
    route (the reference has no metrics subsystem, SURVEY.md §5).

    The ``path`` label is the MATCHED ROUTE PATTERN the router records on
    the request (``/greet/{name}``, bounded cardinality) — never the raw
    URL, which would mint one series per distinct path-param value.
    Unrouted requests (404s) share one ``unmatched`` label. Exceptions
    escaping the inner chain count as status 500 instead of silently
    bypassing the counters (the outer logging middleware still converts
    them into the JSON 500)."""

    requests_total = registry.counter(
        "gofr_http_requests_total", "HTTP requests",
        labels=("method", "path", "status"),
    )
    duration = registry.histogram(
        "gofr_http_request_duration_seconds", "HTTP request latency",
        labels=("path",),
    )

    def middleware(next_ep: Endpoint) -> Endpoint:
        async def endpoint(request: Request) -> Response:
            start = time.perf_counter()
            status = "500"
            try:
                response = await next_ep(request)
                status = str(response.status)
                return response
            finally:
                path = getattr(request, "route_pattern", None) or "unmatched"
                duration.observe(time.perf_counter() - start, path=path)
                requests_total.inc(
                    method=request.method, path=path, status=status
                )

        return endpoint

    return middleware
