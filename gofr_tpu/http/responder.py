"""Builds the wire response from a handler's (result, error) pair.

Parity: /root/reference/pkg/gofr/http/responder.go:11-62 — the
``{"data": ...}`` / ``{"error": {"message": ...}}`` JSON envelope (:59-62),
``Raw``/``File`` special-casing (:24-37), and status derived from the error
(:43-57 via gofr_tpu.errors.status_from_error). TPU-native addition:
``Stream`` results become chunked SSE responses for token decode endpoints.
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator, Optional

from gofr_tpu.errors import status_from_error
from gofr_tpu.http.response import File, Raw, Response, Stream

_JSON = "application/json"


def _json_bytes(payload: Any) -> bytes:
    return json.dumps(payload, default=_jsonable, separators=(",", ":")).encode("utf-8")


def _jsonable(obj: Any) -> Any:
    # numpy / jax arrays and scalars serialize as lists / python scalars
    if hasattr(obj, "tolist"):
        return obj.tolist()
    if hasattr(obj, "item") and getattr(obj, "shape", None) == ():
        return obj.item()
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    return str(obj)


def _frame_sse(item: Any, event_id: Optional[int] = None) -> bytes:
    if isinstance(item, bytes):
        data = item.decode("utf-8", "replace")
    elif isinstance(item, str):
        data = item
    else:
        data = json.dumps(item, default=_jsonable)
    prefix = f"id: {event_id}\n" if event_id is not None else ""
    return (prefix + "data: " + data + "\n\n").encode("utf-8")


async def _sse_iter(stream: Stream, executor: Any = None) -> AsyncIterator[bytes]:
    events = stream.events
    # resumable-stream numbering (Stream.ids): every frame carries a
    # monotonic SSE `id:` line anchored at id_offset, so a proxy (the
    # fleet router) can journal the last delivered offset and resume a
    # broken stream without missing or duplicated events
    next_id = stream.id_offset if stream.ids else None
    # client-abort detection: if this async generator is finalized
    # before the events exhausted — a write failure aborted the
    # connection, or the connection task was cancelled — the stream's
    # abort hook fires DIRECTLY (never via the events generator, which
    # may be suspended mid-next on a pool thread), so the generation's
    # stop event trips and its slot/KV free within one chunk
    completed = False
    try:
        if hasattr(events, "__aiter__"):
            async for item in events:  # type: ignore[union-attr]
                if stream.sse:
                    yield _frame_sse(item, next_id)
                    if next_id is not None:
                        next_id += 1
                else:
                    yield _to_bytes(item)
        else:
            # Sync generators (e.g. blocking token decode) must not stall the
            # event loop between yields; pull each item on a worker thread —
            # the CALLER-provided pool (container.handler_executor), because a
            # stream's blocking next() holds its thread for the full
            # inter-token wait and asyncio's cpu_count+4 default executor
            # caps concurrent streams at a handful on small serving VMs.
            import asyncio

            loop = asyncio.get_running_loop()
            iterator = iter(events)  # type: ignore[arg-type]
            sentinel = object()
            while True:
                item = await loop.run_in_executor(executor, next, iterator, sentinel)
                if item is sentinel:
                    break
                if stream.sse:
                    yield _frame_sse(item, next_id)
                    if next_id is not None:
                        next_id += 1
                else:
                    yield _to_bytes(item)
        completed = True
    finally:
        if not completed and stream.on_abort is not None:
            try:
                stream.on_abort()
            except Exception:
                pass  # an abort hook must never mask the teardown


def _to_bytes(item: Any) -> bytes:
    if isinstance(item, bytes):
        return item
    if isinstance(item, str):
        return item.encode("utf-8")
    return _json_bytes(item)


def respond(
    result: Any, error: Optional[BaseException], executor: Any = None
) -> Response:
    """Parity: http/responder.go:19-41 (Respond's type switch).
    ``executor``: thread pool for pulling sync Stream items (the handler
    adapter passes the container's I/O-sized pool)."""
    if error is not None:
        status = status_from_error(error)
        if status == 500 and not hasattr(error, "status_code"):
            # Hide internals for unexpected errors (parity: the reference's
            # recovery path returns a generic message, middleware/logger.go:104).
            message = "some unexpected error has occurred"
        else:
            message = str(error) or error.__class__.__name__
        payload: dict[str, Any] = {"message": message}
        # shed verdicts echo the HASHED tenant id the admission gate
        # derived (never the raw key), so a 429'd client can quote the
        # exact id /admin/tenants and /admin/requests?tenant= rank under
        tenant = getattr(error, "tenant", None)
        if tenant:
            payload["tenant"] = tenant
        body = _json_bytes({"error": payload})
        headers = {"Content-Type": _JSON}
        # overload verdicts (brownout 429s, admission sheds) carry an
        # explicit backoff hint — bounded-queue discipline end to end
        retry_after = getattr(error, "retry_after_s", None)
        if isinstance(retry_after, (int, float)) and retry_after > 0:
            headers["Retry-After"] = str(max(1, int(retry_after + 0.999)))
        return Response(status=status, headers=headers, body=body)

    if isinstance(result, Response):
        return result
    if isinstance(result, Raw):
        return Response(status=200, headers={"Content-Type": _JSON}, body=_json_bytes(result.data))
    if isinstance(result, File):
        return Response(
            status=200, headers={"Content-Type": result.content_type}, body=result.content
        )
    if isinstance(result, Stream):
        headers = {
            "Content-Type": result.content_type,
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        }
        return Response(status=200, headers=headers, stream=_sse_iter(result, executor))

    body = _json_bytes({"data": result})
    return Response(status=200, headers={"Content-Type": _JSON}, body=body)
