"""Response value types a handler can return.

Parity: /root/reference/pkg/gofr/http/response/raw.go:3-5 (``Raw`` bypasses
the envelope) and response/file.go:3-6 (``File`` sets Content-Type).
TPU-native additions (SURVEY.md §2 #6): ``Stream`` for server-sent-event
token decode streams, and ``Response`` as the wire-level struct middleware
operates on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Iterator, Optional, Union


@dataclass
class Response:
    """Wire-level response: what the server actually writes."""

    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # When set, body is ignored and chunks are written as they arrive
    # (chunked transfer encoding; used for SSE token streaming).
    stream: Optional[Union[Iterator[bytes], AsyncIterator[bytes]]] = None


@dataclass
class Raw:
    """Return from a handler to skip the ``{"data": ...}`` envelope; the
    payload is JSON-encoded as-is. Parity: http/response/raw.go:3-5."""

    data: Any


@dataclass
class File:
    """Return from a handler to send raw bytes with a Content-Type.
    Parity: http/response/file.go:3-6."""

    content: bytes
    content_type: str = "application/octet-stream"


@dataclass
class Stream:
    """Return from a handler to stream chunks (e.g. decoded tokens) to the
    client. ``events`` yields str or bytes; when ``sse`` is True each item is
    framed as a server-sent event ``data: <item>\\n\\n``.

    ``ids=True`` additionally numbers every frame with a monotonic SSE
    ``id:`` line (``id_offset`` + frame index) — the resumable-stream
    contract: the fleet router journals the last id it delivered to the
    client, and a mid-stream failover resumes from that offset instead
    of truncating (``X-Resume-From``). Frame ids are POSITIONS in the
    deterministic event sequence, so a regenerated stream renumbers
    identically and duplicates are filterable by id alone.

    ``on_abort`` (optional callable) fires when the stream is torn
    down BEFORE its events exhausted — a client disconnect (write
    failure) or connection-task cancellation. The responder invokes it
    directly (never through the events generator, which may be
    suspended mid-``next`` on a pool thread): handlers use it to trip
    the generation's stop event so an abandoned stream frees its
    decode slot and paged-KV blocks within one chunk."""

    events: Union[Iterator[Any], AsyncIterator[Any]]
    sse: bool = True
    content_type: str = "text/event-stream"
    ids: bool = False
    id_offset: int = 0
    on_abort: Optional[Any] = None
