"""Transport-agnostic request façade for HTTP.

Parity: /root/reference/pkg/gofr/http/request.go:16-67 — ``Param`` (query,
:28), ``PathParam`` (:36), ``Bind`` (JSON body unmarshal, :40), ``HostName``
honoring X-Forwarded-Proto (:49), and re-readable body (:58-66; trivially
true here since the body is held as bytes).
"""

from __future__ import annotations

import dataclasses
import json
import urllib.parse
from typing import Any, Optional


class Request:
    def __init__(
        self,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes = b"",
        remote_addr: str = "",
        path_params: Optional[dict[str, str]] = None,
    ):
        self.method = method.upper()
        self.target = target
        parsed = urllib.parse.urlsplit(target)
        self.path = parsed.path or "/"
        self.query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
        # header names are case-insensitive; store lowercase
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body
        self.remote_addr = remote_addr
        self.path_params: dict[str, str] = path_params or {}

    # -- the Request interface (parity: pkg/gofr/request.go:10-16) ----------
    def param(self, key: str) -> str:
        """First query parameter value, '' if absent (request.go:28)."""
        vals = self.query.get(key)
        return vals[0] if vals else ""

    def params(self, key: str) -> list[str]:
        return self.query.get(key, [])

    def path_param(self, key: str) -> str:
        return self.path_params.get(key, "")

    def bind(self, into: Any = None) -> Any:
        """JSON-decode the body (request.go:40). With ``into``:

        - a dataclass type -> constructed from matching fields;
        - a plain class -> instance with attributes set from the object;
        - None -> the decoded JSON value.
        """
        try:
            data = json.loads(self.body.decode("utf-8") or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            from gofr_tpu.errors import HTTPError

            raise HTTPError(400, "invalid request body") from exc
        if into is None:
            return data
        if not isinstance(data, dict):
            from gofr_tpu.errors import HTTPError

            raise HTTPError(400, "invalid request body: expected a JSON object")
        if dataclasses.is_dataclass(into) and isinstance(into, type):
            names = {f.name for f in dataclasses.fields(into)}
            try:
                return into(**{k: v for k, v in data.items() if k in names})
            except TypeError as exc:  # missing required fields is a client error
                from gofr_tpu.errors import HTTPError

                raise HTTPError(400, f"invalid request body: {exc}") from exc
        if isinstance(into, type):
            obj = into()
            for k, v in data.items():
                setattr(obj, k, v)
            return obj
        # pre-built object: set attributes in place
        for k, v in data.items():
            setattr(into, k, v)
        return into

    def header(self, name: str) -> str:
        return self.headers.get(name.lower(), "")

    def host_name(self) -> str:
        """Scheme + host, honoring X-Forwarded-Proto (request.go:49-56)."""
        proto = self.headers.get("x-forwarded-proto", "http")
        return f"{proto}://{self.headers.get('host', '')}"

    def context(self) -> "Request":
        return self
