"""Method + path router with ``{param}`` segments and a middleware chain.

Parity: /root/reference/pkg/gofr/http/router.go:13-33 — gorilla/mux-style
routes with path variables, middleware installed once at startup
(router.go:19-23), per-route span wrapping (router.go:31, done by the
middleware chain here). Matching is segment-wise against a precompiled
table; no regex on the hot path.
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Optional

from gofr_tpu.http.request import Request
from gofr_tpu.http.response import Response

# An endpoint is the fully-adapted async callable the server dispatches to.
Endpoint = Callable[[Request], Awaitable[Response]]
Middleware = Callable[[Endpoint], Endpoint]


class _Route:
    __slots__ = ("method", "segments", "endpoint", "pattern")

    def __init__(self, method: str, pattern: str, endpoint: Endpoint):
        self.method = method.upper()
        self.pattern = pattern
        self.segments = _split(pattern)
        self.endpoint = endpoint

    def match(self, segments: list[str]) -> Optional[dict[str, str]]:
        if len(segments) != len(self.segments):
            return None
        params: dict[str, str] = {}
        for want, got in zip(self.segments, segments):
            if want.startswith("{") and want.endswith("}"):
                params[want[1:-1]] = got
            elif want != got:
                return None
        return params


def _split(path: str) -> list[str]:
    # strict-slash off (router.go:17): /abc and /abc/ are the same route
    return [s for s in path.split("/") if s != ""]


class Router:
    def __init__(self) -> None:
        self._routes: list[_Route] = []
        self._middleware: list[Middleware] = []
        self._not_found: Optional[Endpoint] = None
        self._dispatch: Optional[Endpoint] = None

    def add(self, method: str, pattern: str, endpoint: Endpoint) -> None:
        self._routes.append(_Route(method, pattern, endpoint))
        self._dispatch = None  # route table changed; recompose

    def set_not_found(self, endpoint: Endpoint) -> None:
        """Catch-all handler (parity: handler.go:51 catchAllHandler)."""
        self._not_found = endpoint
        self._dispatch = None

    def use(self, *middleware: Middleware) -> None:
        """Install middleware, outermost first (router.go:19-23)."""
        self._middleware.extend(middleware)
        self._dispatch = None

    def routes(self) -> list[tuple[str, str]]:
        return [(r.method, r.pattern) for r in self._routes]

    async def _route_endpoint(self, request: Request) -> Response:
        segments = _split(request.path)
        method = "GET" if request.method == "HEAD" else request.method
        allowed: list[str] = []
        for route in self._routes:
            params = route.match(segments)
            if params is None:
                continue
            # the MATCHED ROUTE PATTERN (bounded cardinality), never the
            # raw URL: middleware (metrics path label) reads it after
            # dispatch. Set on the 405 path too — the path existed.
            request.route_pattern = route.pattern
            if route.method == method:
                request.path_params = params
                return await route.endpoint(request)
            allowed.append(route.method)
        if allowed:
            return Response(
                status=405,
                headers={"Allow": ", ".join(sorted(set(allowed))),
                         "Content-Type": "application/json"},
                body=b'{"error":{"message":"method not allowed"}}',
            )
        if self._not_found is not None:
            return await self._not_found(request)
        return Response(status=404)

    def dispatcher(self) -> Endpoint:
        """Compose middleware around routing; cached until routes change."""
        if self._dispatch is None:
            endpoint: Endpoint = self._route_endpoint
            for mw in reversed(self._middleware):
                endpoint = mw(endpoint)
            self._dispatch = endpoint
        return self._dispatch
