"""gRPC transport: server wiring with recovery + logging/tracing
interceptors, generated-stub registration, and a reflection-free JSON
service mode sharing the transport-agnostic handler signature.

Parity: /root/reference/pkg/gofr/grpc.go:16-47 (server with interceptor
chain recovery -> logging :23-27, listen/serve :32-46) and
grpc/log.go:15-50 (per-RPC span, RPCLog JSON entry, trace id as log id).

TPU-native additions: ``json_services`` lets handlers serve
application/json unary RPCs without protoc codegen (the environment ships
grpcio but not grpc_tools), and server-streaming RPCs are wrapped for token
decode streams.
"""

from __future__ import annotations

import json
import time
import traceback
from concurrent import futures
from dataclasses import dataclass
from typing import Any, Callable, Optional

import grpc

from gofr_tpu.context import Context
from gofr_tpu.errors import status_from_error
from gofr_tpu.tracing import SERVER, current_trace_id, get_tracer


@dataclass
class RPCLog:
    """Typed per-RPC log entry (parity: grpc/log.go:15-25)."""

    id: str
    method: str
    status: str
    response_time_us: int

    def pretty_terminal(self) -> str:
        color = 32 if self.status == "OK" else 31
        return (
            f"\x1b[{color}m{self.status}\x1b[0m "
            f"{self.method} {self.response_time_us}µs"
        )

    def log_fields(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "method": self.method,
            "status": self.status,
            "response_time_us": self.response_time_us,
        }


def _is_abort(exc: BaseException, context: Any) -> bool:
    """grpc's ServicerContext.abort() raises a bare ``Exception()`` after
    marking the context aborted; recovery must let deliberate aborts
    propagate instead of rewriting them to INTERNAL."""
    state = getattr(context, "_state", None)
    if state is not None and getattr(state, "aborted", False):
        return True
    return type(exc) is Exception and not exc.args


class _RecoveryLoggingInterceptor(grpc.ServerInterceptor):
    """Recovery -> logging chain as one interceptor (parity: grpc.go:23-27,
    grpc/log.go:27-50)."""

    def __init__(self, logger: Any):
        self.logger = logger

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method
        metadata = dict(handler_call_details.invocation_metadata or ())
        traceparent = metadata.get("traceparent")
        logger = self.logger

        def _span():
            return get_tracer().start_span(f"grpc {method}", kind=SERVER, traceparent=traceparent)

        if handler.unary_unary:
            inner = handler.unary_unary

            def unary_unary(request, context):
                start = time.perf_counter()
                abort_exc = None
                with _span():
                    trace_id = current_trace_id() or ""
                    try:
                        response = inner(request, context)
                        status = "OK"
                    except Exception as exc:
                        if _is_abort(exc, context):
                            status = "ABORTED"
                            abort_exc = exc
                        else:
                            logger.error(
                                {"error": "rpc panic recovered", "method": method,
                                 "stack": traceback.format_exc(), "trace_id": trace_id}
                            )
                            status = "INTERNAL"
                        response = None
                elapsed = int((time.perf_counter() - start) * 1e6)
                logger.info(RPCLog(trace_id, method, status, elapsed))
                if abort_exc is not None:
                    raise abort_exc
                if status != "OK":
                    context.abort(grpc.StatusCode.INTERNAL, "some unexpected error has occurred")
                return response

            return grpc.unary_unary_rpc_method_handler(
                unary_unary,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )

        if handler.unary_stream:
            inner_stream = handler.unary_stream

            def unary_stream(request, context):
                start = time.perf_counter()
                span = _span()
                trace_id = span.trace_id
                status = "OK"
                abort_exc = None
                try:
                    yield from inner_stream(request, context)
                except Exception as exc:
                    if _is_abort(exc, context):
                        status = "ABORTED"
                        abort_exc = exc
                    else:
                        logger.error(
                            {"error": "rpc panic recovered", "method": method,
                             "stack": traceback.format_exc(), "trace_id": trace_id}
                        )
                        status = "INTERNAL"
                finally:
                    span.__exit__(None, None, None)  # end + reset current-span
                    elapsed = int((time.perf_counter() - start) * 1e6)
                    logger.info(RPCLog(trace_id, method, status, elapsed))
                if abort_exc is not None:
                    raise abort_exc
                if status != "OK":
                    context.abort(grpc.StatusCode.INTERNAL, "some unexpected error has occurred")

            return grpc.unary_stream_rpc_method_handler(
                unary_stream,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )

        return handler  # other streaming shapes pass through un-instrumented


class GRPCRequest:
    """Request façade over a JSON unary RPC body (transport abstraction
    parity: pkg/gofr/request.go:10-16)."""

    def __init__(self, method: str, payload: Any, metadata: dict[str, str]):
        self.method = method
        self.payload = payload if isinstance(payload, dict) else {"body": payload}
        self._raw = payload
        self.metadata = metadata

    def param(self, key: str) -> str:
        value = self.payload.get(key, "")
        return "" if value is None else str(value)

    def params(self, key: str) -> list[str]:
        value = self.payload.get(key)
        if value is None:
            return []
        return [str(v) for v in value] if isinstance(value, list) else [str(value)]

    def path_param(self, key: str) -> str:
        return self.param(key)

    def bind(self, into: Any = None) -> Any:
        if into is None:
            return self._raw
        obj = into() if isinstance(into, type) else into
        if isinstance(self._raw, dict):
            for k, v in self._raw.items():
                setattr(obj, k, v)
        return obj

    def header(self, name: str) -> str:
        return self.metadata.get(name.lower(), "")

    def host_name(self) -> str:
        return self.metadata.get(":authority", "grpc")


class GRPCServer:
    """Parity: grpc.go:16-47."""

    def __init__(
        self,
        port: int,
        container: Any,
        registrations: Optional[list[tuple[Callable, Any]]] = None,
        json_services: Optional[dict[str, dict[str, Callable]]] = None,
        json_stream_services: Optional[dict[str, dict[str, Callable]]] = None,
        max_workers: int = 32,
    ):
        self.port = port
        self.container = container
        self.logger = container.logger
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=[_RecoveryLoggingInterceptor(self.logger)],
        )
        for add_to_server, servicer in registrations or []:
            add_to_server(servicer, self.server)
        stream_services = json_stream_services or {}
        for service_name in set(json_services or {}) | set(stream_services):
            self._register_json_service(
                service_name,
                (json_services or {}).get(service_name, {}),
                stream_services.get(service_name, {}),
            )

    def _register_json_service(
        self,
        service_name: str,
        methods: dict[str, Callable],
        stream_methods: Optional[dict[str, Callable]] = None,
    ) -> None:
        overlap = set(methods) & set(stream_methods or {})
        if overlap:
            raise ValueError(
                f"service '{service_name}' registers {sorted(overlap)} as both "
                "unary and streaming — a method must be one or the other"
            )
        handlers: dict[str, grpc.RpcMethodHandler] = {}
        for method_name, handler in methods.items():
            handlers[method_name] = grpc.unary_unary_rpc_method_handler(
                self._wrap_json_handler(f"/{service_name}/{method_name}", handler),
                request_deserializer=None,  # raw bytes
                response_serializer=None,
            )
        for method_name, handler in (stream_methods or {}).items():
            handlers[method_name] = grpc.unary_stream_rpc_method_handler(
                self._wrap_json_stream_handler(f"/{service_name}/{method_name}", handler),
                request_deserializer=None,
                response_serializer=None,
            )
        generic = grpc.method_handlers_generic_handler(service_name, handlers)
        self.server.add_generic_rpc_handlers((generic,))

    def _build_json_context(
        self, request_bytes: bytes, context: grpc.ServicerContext, method: str
    ) -> Context:
        """Shared request preamble for unary and streaming JSON RPCs:
        metadata normalization, JSON decode (malformed → INVALID_ARGUMENT
        abort), and handler Context construction."""
        metadata = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
        try:
            payload = json.loads(request_bytes.decode("utf-8")) if request_bytes else None
        except (json.JSONDecodeError, UnicodeDecodeError):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "invalid JSON payload")
        return Context(GRPCRequest(method, payload, metadata), self.container)

    def _wrap_json_handler(self, method: str, handler: Callable) -> Callable:
        container = self.container

        def unary(request_bytes: bytes, context: grpc.ServicerContext) -> bytes:
            ctx = self._build_json_context(request_bytes, context, method)
            try:
                result = handler(ctx)
            except Exception as exc:
                _abort_for_error(container, context, method, exc)
                return b""
            from gofr_tpu.http.responder import _jsonable

            return json.dumps({"data": result}, default=_jsonable).encode("utf-8")

        return unary

    def _wrap_json_stream_handler(self, method: str, handler: Callable) -> Callable:
        """Server-streaming JSON RPC: the handler returns an iterator (or a
        ``Stream``); each yielded item is one JSON message on the stream —
        the token-decode transport for the bidi/streaming serving configs
        (BASELINE.md config 4)."""
        container = self.container

        def unary_stream(request_bytes: bytes, context: grpc.ServicerContext):
            ctx = self._build_json_context(request_bytes, context, method)
            from gofr_tpu.http.responder import _jsonable
            from gofr_tpu.http.response import Stream

            try:
                result = handler(ctx)
                events = result.events if isinstance(result, Stream) else result
                for item in events:
                    yield json.dumps(item, default=_jsonable).encode("utf-8")
            except Exception as exc:
                _abort_for_error(container, context, method, exc)

        return unary_stream

    # -- lifecycle (parity: grpc.go:32-46) -----------------------------------
    def start(self) -> None:
        addr = f"[::]:{self.port}"
        self.server.add_insecure_port(addr)
        self.server.start()
        self.logger.infof("starting gRPC server on port %s", self.port)

    def wait(self) -> None:
        self.server.wait_for_termination()

    def stop(self, grace: float = 2.0) -> None:
        self.server.stop(grace)


def _abort_for_error(
    container: Any, context: grpc.ServicerContext, method: str, exc: Exception
) -> None:
    """Shared error→status policy for unary and streaming JSON handlers:
    typed errors surface their message on the mapped status; unexpected
    errors are logged server-side and masked as INTERNAL."""
    status = status_from_error(exc)
    code = _status_to_grpc(status)
    if status == 500 and not hasattr(exc, "status_code"):
        container.logger.errorf("grpc handler error on %s: %r", method, exc)
        context.abort(code, "some unexpected error has occurred")
    else:
        context.abort(code, str(exc))


def _status_to_grpc(status: int) -> grpc.StatusCode:
    return {
        400: grpc.StatusCode.INVALID_ARGUMENT,
        401: grpc.StatusCode.UNAUTHENTICATED,
        403: grpc.StatusCode.PERMISSION_DENIED,
        404: grpc.StatusCode.NOT_FOUND,
        408: grpc.StatusCode.DEADLINE_EXCEEDED,
        429: grpc.StatusCode.RESOURCE_EXHAUSTED,
        502: grpc.StatusCode.UNAVAILABLE,
        503: grpc.StatusCode.UNAVAILABLE,
    }.get(status, grpc.StatusCode.INTERNAL)
