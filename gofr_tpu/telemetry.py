"""Request flight recorder: per-request end-to-end inference telemetry.

The metrics registry answers "how is the fleet doing"; it cannot answer
"what happened to THIS request". The flight recorder keeps one
``FlightRecord`` per inference request — enqueue/dispatch/first-token/
last-token marks, queue wait, TTFT, TPOT, token counts, batch cohort
size — in a bounded ring buffer, plus an always-keep side buffer for
slow and errored requests (the interesting ones must survive ring
eviction under traffic). At completion each record is emitted as ONE
canonical wide-event log line (every field, one dict) through the
container logger, so log search and the admin API see the same truth.

Admin surface (app.py): ``GET /admin/requests`` returns recent records
(``?slow=``/``?errored=`` filters), ``GET /admin/slo`` computes
rolling-window per-model p50/p95/p99 TTFT and TPOT from the records
themselves — exact sample percentiles, not histogram bucket upper
bounds.

The record travels with the request the same way spans do: a
contextvar. Handlers ``start()`` it, the batcher stamps queue timing
and cohort size on the queue item's captured record, the decode pool
stamps pool occupancy, the device stamps token timing. Thread
boundaries (handler pool, batcher dispatch, stream generation thread)
propagate it via ``contextvars.copy_context()``.

This module also hosts the **durable generation journal**
(:class:`GenerationJournal`): a bounded per-request record of prompt
hash, sampling parameters (including the seed), and the emitted token
ids. The flight recorder answers "what happened"; the journal answers
"where exactly was this generation when the engine wedged" — after the
recovery supervisor (tpu/recovery.py) rebuilds the stack, an
interrupted request is re-admitted and RESUMED: the journaled tokens
replay instantly, the continuation teacher-forces a prefill over
prompt+emitted through the paged-KV path (block aliasing makes the
re-prefill nearly copy-free), and the resumed stream is bit-identical
to an uninterrupted run for deterministic (greedy/seeded) requests.
The journal entry rides its own contextvar (``current_journal_entry``)
so the decode pool can stamp interruption causes without a new
plumbing layer.
"""

from __future__ import annotations

import contextvars
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Optional

# process identity, regenerated on every interpreter start: the fleet
# prober compares it across probes to tell "the same process recovered"
# from "a NEW process answers at this address" — the supervisor-restart
# signature a reborn replica walks probation under (fleet/replica.py).
# Served on the ready 200 body and /admin/engine.
BOOT_ID = uuid.uuid4().hex[:16]

_current_record: contextvars.ContextVar[Optional["FlightRecord"]] = (
    contextvars.ContextVar("gofr_flight_record", default=None)
)

_current_journal_entry: contextvars.ContextVar[Optional["JournalEntry"]] = (
    contextvars.ContextVar("gofr_journal_entry", default=None)
)


def current_journal_entry() -> Optional["JournalEntry"]:
    """The in-flight generation's journal entry, if journaling is on."""
    return _current_journal_entry.get()


def activate_journal_entry(entry: Optional["JournalEntry"]) -> Any:
    """Bind ``entry`` as the current one (None clears); returns the
    reset token. The device binds it around each generation so the
    decode pool / batcher layers can stamp interruption causes."""
    return _current_journal_entry.set(entry)


def current_record() -> Optional["FlightRecord"]:
    """The in-flight request's FlightRecord, if one is active."""
    return _current_record.get()


# -- fleet-wide request origin (cross-process hop correlation) ---------------
#
# The fleet router stamps every forward with ``X-Gofr-Request-Id`` (the
# fleet-wide correlation id, minted once — or honored from a sanitized
# client ``X-Request-ID``) and ``X-Gofr-Hop`` (which router, which
# failover attempt, which resume continuation). Replicas parse both at
# admission into a contextvar — the same pattern the deadline and the
# KV-donor hint ride — and every FlightRecord born under it carries an
# ``origin`` block, so ``GET /admin/fleet/trace/<id>`` can join the
# router's route record with the replica-side flight records it caused.

# request ids are operator-facing correlation keys that end up in log
# lines, URLs and admin queries: bound length, restrict charset, and
# treat anything else as absent (garbage degrades to a minted id, never
# to a 4xx — same discipline as parse_kv_hint)
REQUEST_ID_MAX_LEN = 64
_REQUEST_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)

_current_origin: contextvars.ContextVar[Optional[dict]] = (
    contextvars.ContextVar("gofr_request_origin", default=None)
)


def sanitize_request_id(raw: Any) -> Optional[str]:
    """Validate a request id off the wire: non-empty, at most
    ``REQUEST_ID_MAX_LEN`` chars, charset ``[A-Za-z0-9._-]``. Returns
    the id or None — callers mint their own on None, never reject."""
    if not raw or not isinstance(raw, str):
        return None
    value = raw.strip()
    if not value or len(value) > REQUEST_ID_MAX_LEN:
        return None
    if not all(c in _REQUEST_ID_CHARS for c in value):
        return None
    return value


def format_hop(router_id: str, attempt: int, resume_from: int = 0) -> str:
    """The ``X-Gofr-Hop`` wire value the router stamps per forward."""
    return f"router={router_id};attempt={int(attempt)};resume={int(resume_from)}"


def parse_hop(raw: Any) -> Optional[dict]:
    """Parse an ``X-Gofr-Hop`` header (``router=<id>;attempt=<n>;
    resume=<n>``) into ``{"router", "attempt", "resume_from"}``.
    Malformed input returns None — hop metadata is telemetry, never a
    reason to fail a request."""
    if not raw or not isinstance(raw, str) or len(raw) > 256:
        return None
    fields: dict[str, str] = {}
    for part in raw.strip().split(";"):
        key, sep, value = part.partition("=")
        if sep:
            fields[key.strip()] = value.strip()
    router = sanitize_request_id(fields.get("router", ""))
    if router is None:
        return None
    try:
        attempt = int(fields.get("attempt", ""))
        resume_from = int(fields.get("resume", "0"))
    except ValueError:
        return None
    if attempt < 0 or resume_from < 0:
        return None
    return {"router": router, "attempt": attempt, "resume_from": resume_from}


def activate_origin(origin: Optional[dict]) -> Any:
    """Bind the request's fleet origin (``{"request_id", "router",
    "attempt", "resume_from"}``; None clears) so the FlightRecord born
    downstream stamps it. Returns the contextvar reset token."""
    return _current_origin.set(origin)


def current_origin() -> Optional[dict]:
    """The in-flight request's fleet origin block, if the router
    stamped one (None on direct, router-less requests)."""
    return _current_origin.get()


def origin_from_headers(request_id_raw: Any, hop_raw: Any) -> Optional[dict]:
    """Build the origin block from the two router-stamped headers.
    Either header alone still yields a (partial) origin; both absent or
    garbage yields None."""
    request_id = sanitize_request_id(request_id_raw)
    hop = parse_hop(hop_raw)
    if request_id is None and hop is None:
        return None
    origin: dict[str, Any] = {"request_id": request_id or ""}
    if hop is not None:
        origin.update(hop)
    return origin


# -- tenant identity (bounded-cardinality usage metering) --------------------
#
# The admission gate resolves the request's HASHED tenant id
# (fleet/admission.tenant_of: sha256 of the Authorization value — never
# raw key material) and binds it here, the same contextvar ride the
# deadline, the KV-donor hint, and the fleet origin take. The
# FlightRecord born downstream stamps it, the recorder's TenantLedger
# meters it, and /admin/requests?tenant= joins a support ticket to the
# flight records that carried it.

_current_tenant: contextvars.ContextVar[Optional[str]] = (
    contextvars.ContextVar("gofr_request_tenant", default=None)
)


def activate_tenant(tenant: Optional[str]) -> Any:
    """Bind the request's hashed tenant id (None/"" clears); returns the
    contextvar reset token."""
    return _current_tenant.set(tenant or None)


def current_tenant() -> Optional[str]:
    """The in-flight request's hashed tenant id, if admission bound one
    (None on paths that never ran the admission gate)."""
    return _current_tenant.get()


def exemplar_provider() -> Optional[dict]:
    """Default metrics exemplar provider (metrics.py Histogram): the
    correlating ids of the CURRENT observation — the active request's
    trace_id (flight record first, else the live span) and, below the
    dispatch layer, the executing dispatch_id. Contextvar reads only:
    O(1), no locks, safe on the hot path. Returns None outside any
    request/dispatch context (boot-time observations stay exemplar-free)."""
    labels: dict[str, str] = {}
    record = _current_record.get()
    trace_id = record.trace_id if record is not None else ""
    if not trace_id:
        from gofr_tpu.tracing import current_trace_id

        trace_id = current_trace_id() or ""
    if trace_id:
        labels["trace_id"] = trace_id
    # sys.modules, not an import: gofr_tpu.tpu's package init pulls in
    # jax, and an app serving no TPU must never pay that import because
    # a latency histogram fired
    import sys

    introspect = sys.modules.get("gofr_tpu.tpu.introspect")
    if introspect is not None:
        dispatch = introspect.current_dispatch()
        if dispatch is not None:
            labels["dispatch_id"] = str(dispatch.dispatch_id)
    return labels or None


def activate_record(record: Optional["FlightRecord"]) -> Any:
    """Bind ``record`` as the current one; returns the reset token.
    Handlers run inside a per-request copied context (handler.py), so
    not resetting leaks nothing past the request."""
    return _current_record.set(record)


class FlightRecord:
    """One request's flight data. Marks are ``time.perf_counter`` values
    anchored to ``wall_start`` (``time.time`` at creation) for display.
    Single-shot marks are set-once attribute assignments (atomic under
    the GIL); the accumulating fields (``tokens_out``, ``pool_cohort``)
    take the record's lock — an n>1 fan-out runs candidates concurrently
    against ONE record, and ``+=`` is a read-modify-write."""

    __slots__ = (
        "trace_id", "request_id", "origin",
        "model", "endpoint", "status", "error", "stream",
        "tokens_in", "tokens_out", "batch_size", "pool_cohort",
        "prefill_chunks", "prefill_bucket", "sched_defer_s",
        "pool_reject_reason", "dispatch_ids", "anomalous_dispatches",
        "spec_drafted", "spec_accepted", "spec_dispatches", "spec_emitted",
        "kv_blocks", "kv_aliased_blocks", "mesh_axes",
        "tenant", "deadline_s", "priority", "shed_stage",
        "wall_start", "t_start", "t_enqueue", "t_dispatch",
        "t_first_token", "t_last_token", "t_done", "wall_done", "_lock",
        # the recorder's in-flight index holds records WEAKLY (an
        # abandoned record must vanish with its request, not leak)
        "__weakref__",
    )

    # device dispatches linked per record: enough to cover a prefill, its
    # chunks, and the first pooled decode chunks without letting a
    # 10k-token generation grow the record unboundedly
    MAX_DISPATCH_IDS = 32

    def __init__(
        self,
        model: str,
        endpoint: str,
        trace_id: str = "",
        tokens_in: int = 0,
        stream: bool = False,
    ):
        self.trace_id = trace_id
        # fleet origin: the router-stamped request id + hop block, read
        # off the origin contextvar exactly like the deadline below —
        # this is what lets /admin/fleet/trace/<id> find the replica
        # flight records one routed request caused
        origin = current_origin()
        self.request_id = origin.get("request_id", "") if origin else ""
        self.origin = None
        if origin and "router" in origin:
            self.origin = {
                "router": origin.get("router"),
                "attempt": origin.get("attempt"),
                "resume_from": origin.get("resume_from"),
            }
        self.model = model
        self.endpoint = endpoint
        self.status = "in_flight"
        self.error = ""
        self.stream = stream
        self.tokens_in = tokens_in
        self.tokens_out = 0
        self.batch_size = 0  # prefill batch cohort (batcher dispatch)
        self.pool_cohort = 0  # active decode-pool slots when this joined
        self.prefill_chunks = 0  # bounded-compute prefill dispatches
        self.prefill_bucket = 0  # widest compiled bucket the prefill rode
        self.sched_defer_s = 0.0  # total interference-scheduler defer
        self.pool_reject_reason = ""  # why the decode pool refused (solo'd)
        self.dispatch_ids: list[int] = []  # device dispatches this rode
        # of those, the ones the cost model flagged anomalous
        # (tpu/costmodel.py): a slow request's wide event names the
        # exact dispatch that blew its prediction
        self.anomalous_dispatches: list[int] = []
        # pooled speculative decoding (tpu/spec_pool.py): draft tokens
        # proposed/accepted and the verify dispatches + tokens they
        # emitted — tokens_per_dispatch is THE number speculation exists
        # to raise (1.0 = plain decode), percentiled on /admin/slo
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_dispatches = 0
        self.spec_emitted = 0
        self.kv_blocks = 0  # paged-KV blocks reserved for this request
        self.kv_aliased_blocks = 0  # of those, admitted copy-free (prefix share)
        # serving-mesh axes this request ran on ({"tp": 2, ...}; None =
        # single chip) — latency is only comparable within one topology
        self.mesh_axes: Optional[dict] = None
        # hashed tenant id (admission gate via the tenant contextvar —
        # same ride as the origin above); None on paths that never ran
        # admission (bare test containers, internal probes)
        self.tenant = current_tenant()
        # deadline-aware serving (gofr_tpu/deadline.py): the request's
        # total budget + priority tier, read off the request contextvars
        # at record start (priority rides its own var so a deadline-less
        # X-Priority request still records the tier brownout sheds by);
        # shed_stage records WHERE an exceeded deadline shed it
        # (queue | admission | decode), "" = never shed
        from gofr_tpu.deadline import current_deadline, current_priority

        deadline = current_deadline()
        self.deadline_s = deadline.budget_s if deadline is not None else None
        self.priority = (
            deadline.priority if deadline is not None else current_priority()
        )
        self.shed_stage = ""
        # gofrlint: wall-clock — /admin/requests display ts (durations use t_*)
        self.wall_start = time.time()
        self.t_start = time.perf_counter()
        self.t_enqueue: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self.wall_done: Optional[float] = None
        self._lock = threading.Lock()

    # -- marks (called from batcher / pool / device) -------------------------
    def mark_enqueue(self) -> None:
        if self.t_enqueue is None:
            self.t_enqueue = time.perf_counter()

    def mark_dispatch(self, cohort: int) -> None:
        """First prefill dispatch: stamps the batch cohort this request
        rode with (later dispatches — chunked prefill — keep the first)."""
        if self.t_dispatch is None:
            self.t_dispatch = time.perf_counter()
            self.batch_size = cohort

    def mark_first_token(self) -> None:
        if self.t_first_token is None:
            self.t_first_token = time.perf_counter()

    def mark_pooled(self, cohort: int) -> None:
        """Decode joined the continuous-batching pool with ``cohort``
        active slots (keeps the max seen across fan-out candidates)."""
        with self._lock:
            if cohort > self.pool_cohort:
                self.pool_cohort = cohort

    def note_prefill_chunk(self, n: int = 1, bucket: int = 0) -> None:
        """Prefill dispatch accounting: ``n`` bounded-compute chunks
        landed, each through a ``bucket``-wide compiled shape (the widest
        seen is kept — bucket vs. ``tokens_in`` shows the padding a
        request paid)."""
        with self._lock:
            self.prefill_chunks += n
            if bucket > self.prefill_bucket:
                self.prefill_bucket = bucket

    def note_sched_defer(self, seconds: float) -> None:
        """Interference-scheduler defer: time this request's prefill
        chunks waited for their decode-interleave turn (accumulates
        across chunks)."""
        if seconds and seconds > 0:
            with self._lock:
                self.sched_defer_s += seconds

    def note_dispatch_id(self, dispatch_id: int) -> None:
        """Link a device dispatch (tpu/introspect.py DispatchTimeline)
        this request rode — `/admin/requests` entries then resolve
        directly to the `/admin/dispatches` records that carried them.
        Bounded at MAX_DISPATCH_IDS (the decode pool stamps every chunk a
        pooled stream shares)."""
        with self._lock:
            if len(self.dispatch_ids) < self.MAX_DISPATCH_IDS:
                self.dispatch_ids.append(dispatch_id)

    def note_anomaly(self, dispatch_id: int) -> None:
        """The cost model flagged a dispatch this request rode as
        anomalous (observed blew past predicted, tpu/costmodel.py) —
        the wide event then pins the slow request to the exact
        `/admin/anomalies` entry. Same bound as the id list."""
        with self._lock:
            if (
                dispatch_id not in self.anomalous_dispatches
                and len(self.anomalous_dispatches) < self.MAX_DISPATCH_IDS
            ):
                self.anomalous_dispatches.append(dispatch_id)

    def note_pool_reject(self, reason: str) -> None:
        """The decode pool refused this request (it decoded solo); the
        FIRST rejection reason is kept — later fan-out candidates may
        see a different pool state."""
        if not self.pool_reject_reason:
            self.pool_reject_reason = reason

    def note_spec(self, drafted: int, accepted: int, emitted: int,
                  dispatches: int = 1) -> None:
        """One pooled-spec delivery this request rode: ``drafted``
        draft tokens proposed, ``accepted`` of them matched the target,
        ``emitted`` tokens delivered. ``dispatches`` is the
        weight-stream count of the delivery — 1 for a verify cycle
        (ONE forward whatever the width: the spec win), the pool's
        chunk size for a plain chunk a spec-armed row rode (one stream
        per scan step) — so tokens_per_dispatch reads 1.0 for plain
        decode on every producer and >1.0 only for real speculation."""
        with self._lock:
            self.spec_drafted += drafted
            self.spec_accepted += accepted
            self.spec_dispatches += dispatches
            self.spec_emitted += emitted

    def note_kv(self, blocks: int, aliased: int = 0) -> None:
        """Paged-KV admission accounting: ``blocks`` reserved for this
        request, ``aliased`` of them shared copy-free with the prefix
        cache. Keeps the max seen (fan-out candidates admit separately)."""
        with self._lock:
            if blocks > self.kv_blocks:
                self.kv_blocks = blocks
            if aliased > self.kv_aliased_blocks:
                self.kv_aliased_blocks = aliased

    def note_mesh(self, axes: dict) -> None:
        """Stamp the serving-mesh shape (set-once; the device stamps it
        when a request enters its generate path under TPU_MESH)."""
        if self.mesh_axes is None:
            self.mesh_axes = dict(axes)

    def note_tokens(self, n: int = 1) -> None:
        with self._lock:
            self.tokens_out += n
        self.t_last_token = time.perf_counter()

    def note_shed(self, stage: str) -> None:
        """Deadline shed accounting: the FIRST stage that gave up on
        this request wins (a queue shed's DeadlineExceeded also unwinds
        through the handler's error path)."""
        if not self.shed_stage:
            self.shed_stage = stage

    def note_error(self, exc: BaseException) -> None:
        """Device-layer failure: remembered even if the transport still
        manages a response (a stream that already committed its 200).
        A deadline shed keeps its own terminal status — "the budget ran
        out" and "the device broke" must stay distinguishable on
        /admin/requests and in the SLO error rate."""
        from gofr_tpu.errors import DeadlineExceeded

        if isinstance(exc, DeadlineExceeded):
            self.status = "deadline_exceeded"
            if getattr(exc, "stage", ""):
                self.note_shed(exc.stage)
        else:
            self.status = "error"
        self.error = f"{type(exc).__name__}: {exc}"

    # -- derived -------------------------------------------------------------
    @property
    def queue_wait(self) -> Optional[float]:
        if self.t_enqueue is None or self.t_dispatch is None:
            return None
        return self.t_dispatch - self.t_enqueue

    @property
    def ttft(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_start

    @property
    def tpot(self) -> Optional[float]:
        """Mean time per output token AFTER the first (decode cadence)."""
        if (
            self.t_first_token is None or self.t_last_token is None
            or self.tokens_out < 2
        ):
            return None
        return (self.t_last_token - self.t_first_token) / (self.tokens_out - 1)

    @property
    def duration(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_start

    @property
    def tokens_per_dispatch(self) -> Optional[float]:
        """Tokens emitted per target weight-stream while spec-armed
        (1.0 = plain decode; None = never rode the spec path)."""
        if self.spec_dispatches < 1:
            return None
        return self.spec_emitted / self.spec_dispatches

    def to_dict(self) -> dict[str, Any]:
        """The wide-event shape: every field, one flat dict. Durations in
        seconds (floats); wall timestamps in unix seconds."""

        def _offset(mark: Optional[float]) -> Optional[float]:
            if mark is None:
                return None
            return self.wall_start + (mark - self.t_start)

        return {
            "event": "request_flight",
            "trace_id": self.trace_id,
            "request_id": self.request_id or None,
            "origin": self.origin,
            "model": self.model,
            "endpoint": self.endpoint,
            "status": self.status,
            "error": self.error or None,
            "stream": self.stream,
            "tokens_in": self.tokens_in,
            "tokens_out": self.tokens_out,
            "batch_size": self.batch_size,
            "pool_cohort": self.pool_cohort,
            "prefill_chunks": self.prefill_chunks,
            "prefill_bucket": self.prefill_bucket or None,
            "sched_defer_s": self.sched_defer_s or None,
            "pool_reject_reason": self.pool_reject_reason or None,
            "dispatch_ids": list(self.dispatch_ids),
            "anomalous_dispatches": list(self.anomalous_dispatches) or None,
            "spec_drafted": self.spec_drafted or None,
            "spec_accepted": self.spec_accepted or None,
            "tokens_per_dispatch": self.tokens_per_dispatch,
            "kv_blocks": self.kv_blocks or None,
            "kv_aliased_blocks": self.kv_aliased_blocks or None,
            "mesh_axes": self.mesh_axes,
            "tenant": self.tenant,
            "deadline_s": self.deadline_s,
            "priority": self.priority,
            "shed_stage": self.shed_stage or None,
            "start_ts": self.wall_start,
            "enqueue_ts": _offset(self.t_enqueue),
            "dispatch_ts": _offset(self.t_dispatch),
            "first_token_ts": _offset(self.t_first_token),
            "done_ts": self.wall_done,
            "queue_wait_s": self.queue_wait,
            "ttft_s": self.ttft,
            "tpot_s": self.tpot,
            "duration_s": self.duration,
        }


def request_key(model: str, prompt_ids: Any, max_new_tokens: int,
                sampler: Any = None, stop_tokens: Any = None) -> str:
    """Deterministic identity of one generation request: the journal
    key interrupted entries are claimed back by at resume time. Hashes
    the prompt (never stores it raw — prompts are user data, the
    journal serves on no endpoint but its key could leak into logs),
    the sampling knobs INCLUDING the seed, the budget, and the stop
    set: two requests that could produce different streams must never
    share a key."""
    import hashlib

    parts = [model, str(int(max_new_tokens))]
    if sampler is not None:
        parts.append(
            f"t={getattr(sampler, 'temperature', 0)}"
            f"|k={getattr(sampler, 'top_k', 0)}"
            f"|p={getattr(sampler, 'top_p', 1.0)}"
            f"|m={getattr(sampler, 'min_p', 0.0)}"
            f"|r={getattr(sampler, 'repetition_penalty', 1.0)}"
            f"|pp={getattr(sampler, 'presence_penalty', 0.0)}"
            f"|fp={getattr(sampler, 'frequency_penalty', 0.0)}"
            f"|s={getattr(sampler, 'seed', None)}"
        )
    if stop_tokens:
        parts.append(",".join(str(t) for t in sorted(stop_tokens)))
    digest = hashlib.sha256()
    for part in parts:
        digest.update(part.encode("utf-8"))
        digest.update(b"\x00")
    digest.update(
        ",".join(str(int(t)) for t in (prompt_ids or ())).encode("ascii")
    )
    return digest.hexdigest()[:32]


class JournalEntry:
    """One generation's durable record. Single-writer append (the
    emitting thread); ``tokens`` reads take a snapshot copy under the
    GIL (list slicing is atomic). Status walks
    active → done | interrupted → resumed."""

    __slots__ = (
        "key", "model", "max_new_tokens", "seeded", "deterministic",
        "tokens", "status", "reason", "t_start", "t_interrupted",
        "prior", "truncated", "max_tokens", "wal_id", "_wal",
    )

    def __init__(self, key: str, model: str, max_new_tokens: int,
                 seeded: bool, deterministic: bool, max_tokens: int,
                 prior: Optional[list] = None):
        self.key = key
        self.model = model
        self.max_new_tokens = max_new_tokens
        self.seeded = seeded
        # greedy or seeded: replaying the request reproduces the stream
        # bit-identically — the precondition for resume
        self.deterministic = deterministic
        self.max_tokens = max_tokens
        # a RESUMED request's entry pre-seeds the tokens the interrupted
        # incarnation already produced, so a second wedge resumes from
        # the union, not from the resume point
        self.tokens: list[int] = list(prior or ())
        self.truncated = False
        self.status = "active"
        self.reason = ""
        self.t_start = time.perf_counter()
        self.t_interrupted: Optional[float] = None
        # write-ahead log attachment (journal_wal.py): when the journal
        # runs durable, every append streams through to disk so a
        # SIGKILLed process rehydrates this entry at next boot
        self.wal_id = 0
        self._wal: Any = None

    def append(self, token: int) -> None:
        if len(self.tokens) >= self.max_tokens:
            # a bounded record can no longer prove bit-identity past its
            # cap — the entry stays for forensics but refuses resume
            if not self.truncated and self._wal is not None:
                # retire the on-disk record too: a rehydrated truncated
                # entry could not prove the tokens past its cap either
                self._wal.retire(self.wal_id)
            self.truncated = True
            return
        self.tokens.append(int(token))
        if self._wal is not None:
            self._wal.append_tokens(self.wal_id, (token,))

    def note_interrupted(self, reason: str) -> None:
        """Stamp WHY (pool failure, batcher close, recovery teardown);
        the first cause wins — later layers see consequences."""
        if not self.reason:
            self.reason = reason

    def snapshot(self) -> dict[str, Any]:
        return {
            "key": self.key,
            "model": self.model,
            "status": self.status,
            "tokens": len(self.tokens),
            "max_new_tokens": self.max_new_tokens,
            "deterministic": self.deterministic,
            "reason": self.reason or None,
        }


class GenerationJournal:
    """Bounded store of :class:`JournalEntry` records keyed by
    :func:`request_key`.

    Completed entries retire immediately (their tokens already reached
    the client); INTERRUPTED entries are the valuable ones — they wait,
    bounded by ``capacity`` (oldest evicted first), for a resume to
    :meth:`claim` them. The journal never initiates anything: the
    device consults it on a resume request (``X-Resume-From`` /
    ``generate_stream(resume_from=...)``) and the fleet router decides
    WHEN to resume."""

    def __init__(self, capacity: int = 256, max_tokens: int = 8192,
                 metrics: Any = None, wal: Any = None):
        self.capacity = max(1, capacity)
        self.max_tokens = max(1, max_tokens)
        self._lock = threading.Lock()
        # key -> list of entries (concurrent identical seeded requests
        # are legal; each gets its own entry, claims pop one)
        self._interrupted: "dict[str, list[JournalEntry]]" = {}
        self._interrupted_order: "deque[JournalEntry]" = deque()
        self._active = 0
        self.interruptions = 0
        self.completions = 0
        # optional write-ahead log (journal_wal.JournalWAL): every
        # lifecycle transition and emitted token streams to disk, and
        # rehydrate() reinstates a SIGKILLed process's resumable entries
        self.wal = wal
        self.rehydrated = 0
        self._resumes = (
            metrics.counter(
                "gofr_tpu_journal_resumes_total",
                "interrupted generations resumed from the journal by "
                "mode: teacher_forced (prefill over prompt+emitted, "
                "paged-KV aliased) or replayed (full deterministic "
                "regeneration, first tokens suppressed)",
                labels=("mode",),
            )
            if metrics is not None else None
        )

    # -- lifecycle (device-side) ----------------------------------------------
    def start(self, key: str, model: str, max_new_tokens: int,
              seeded: bool, deterministic: bool,
              prior: Optional[list] = None) -> JournalEntry:
        entry = JournalEntry(
            key, model, max_new_tokens, seeded, deterministic,
            max_tokens=self.max_tokens, prior=prior,
        )
        if self.wal is not None:
            entry._wal = self.wal
            entry.wal_id = self.wal.open_entry(
                key, model, max_new_tokens, seeded, deterministic,
                prior=prior,
            )
        with self._lock:
            self._active += 1
        return entry

    def rehydrate(self) -> int:
        """Reinstate the WAL's recovered entries as interrupted,
        resumable ones — called once at boot, before serving. Returns
        the count (also on :attr:`rehydrated` and ``stats()``). The
        restarted process then serves ``X-Resume-From`` for its own
        pre-crash streams exactly as if the engine had merely wedged."""
        if self.wal is None:
            return 0
        count = 0
        for state in self.wal.recover():
            entry = JournalEntry(
                state["key"], state["model"], int(state["mnt"]),
                seeded=bool(state["seeded"]),
                deterministic=bool(state["det"]),
                max_tokens=self.max_tokens,
                prior=state.get("tokens") or (),
            )
            entry.wal_id = int(state["id"])
            entry._wal = self.wal
            self.wal.adopt(entry.wal_id, state)
            self.interrupt(entry, state.get("reason") or "process death")
            count += 1
        # interrupt() counted these as live interruptions; recovery
        # evidence must stay distinguishable from in-process failures
        with self._lock:
            self.interruptions -= count
        self.rehydrated = count
        return count

    def finish(self, entry: JournalEntry) -> None:
        """Clean completion: the entry retires (its stream reached the
        client; nothing to resume)."""
        if entry.status != "active":
            return
        entry.status = "done"
        if entry._wal is not None and not entry.truncated:
            entry._wal.finish(entry.wal_id)
        with self._lock:
            self._active = max(0, self._active - 1)
            self.completions += 1

    def interrupt(self, entry: JournalEntry, reason: str) -> None:
        """The generation died mid-flight: retain the entry for resume
        (idempotent — the first interruption wins)."""
        if entry.status != "active":
            return
        entry.status = "interrupted"
        entry.note_interrupted(reason)
        entry.t_interrupted = time.perf_counter()
        if entry._wal is not None and not entry.truncated:
            entry._wal.interrupt(entry.wal_id, entry.reason)
        evictions: list[JournalEntry] = []
        with self._lock:
            self._active = max(0, self._active - 1)
            self.interruptions += 1
            self._interrupted.setdefault(entry.key, []).append(entry)
            self._interrupted_order.append(entry)
            while len(self._interrupted_order) > self.capacity:
                evicted = self._interrupted_order.popleft()
                bucket = self._interrupted.get(evicted.key)
                if bucket is not None:
                    try:
                        bucket.remove(evicted)
                    except ValueError:
                        pass  # already claimed
                    if not bucket:
                        self._interrupted.pop(evicted.key, None)
                evictions.append(evicted)
        for evicted in evictions:
            if evicted._wal is not None and evicted.status == "interrupted":
                # capacity eviction: the on-disk record retires too, or
                # recovery would resurrect an entry the live journal
                # already refused to keep. OUTSIDE the journal lock: a
                # WAL write is disk I/O (fsync on rotation), and the
                # lock sits on the per-token serving path
                evicted._wal.retire(evicted.wal_id)

    # -- resume (device-side, driven by the router/client) ---------------------
    def claim(self, key: str, min_tokens: int = 0) -> Optional[JournalEntry]:
        """Pop one interrupted entry for ``key`` holding at least
        ``min_tokens`` journaled tokens (the client already received
        that many — a shorter record cannot prove them). Returns None
        when nothing matches; the caller then falls back to full
        deterministic replay."""
        claimed: Optional[JournalEntry] = None
        with self._lock:
            bucket = self._interrupted.get(key)
            if not bucket:
                return None
            for i, entry in enumerate(bucket):
                if entry.truncated or len(entry.tokens) < min_tokens:
                    continue
                del bucket[i]
                if not bucket:
                    self._interrupted.pop(key, None)
                try:
                    self._interrupted_order.remove(entry)
                except ValueError:
                    pass
                entry.status = "resumed"
                claimed = entry
                break
        if claimed is not None and claimed._wal is not None:
            # the resumed CONTINUATION opens its own entry (the resume
            # generate passes journal_key/journal_prior), so this record
            # retires — a second crash resumes from the continuation's
            # entry, which holds the union of tokens. OUTSIDE the
            # journal lock: the WAL write is disk I/O
            claimed._wal.claim(claimed.wal_id)
        return claimed

    def note_resume(self, mode: str) -> None:
        """Count one resume by mode (teacher_forced | replayed)."""
        if self._resumes is not None:
            self._resumes.inc(mode=mode)

    # -- read side -------------------------------------------------------------
    def interrupted(self) -> list[dict[str, Any]]:
        with self._lock:
            return [e.snapshot() for e in self._interrupted_order]

    def stats(self) -> dict[str, Any]:
        with self._lock:
            out = {
                "active": self._active,
                "interrupted": len(self._interrupted_order),
                "capacity": self.capacity,
                "max_tokens_per_entry": self.max_tokens,
                "interruptions": self.interruptions,
                "completions": self.completions,
                "rehydrated": self.rehydrated,
            }
        out["wal"] = self.wal.stats() if self.wal is not None else None
        return out


def _percentiles(samples: list[float]) -> dict[str, float]:
    """Exact nearest-rank p50/p95/p99 from raw samples."""
    import math

    ordered = sorted(samples)
    n = len(ordered)

    def rank(q: float) -> float:
        # nearest-rank: smallest value with cumulative fraction >= q
        return ordered[max(0, min(n - 1, math.ceil(q * n) - 1))]

    return {"p50": rank(0.50), "p95": rank(0.95), "p99": rank(0.99)}


class Flight:
    """Handler-side record lifecycle, shared by every endpoint (the
    chat/completions copies drifted once in review). Use as a context
    manager around the generation: a clean exit finishes the record ok;
    an exception finishes it as errored — UNLESS it is a pre-inference
    parameter rejection (a 4xx raised before any device work touched the
    record), which is dropped: records describe actual inference
    attempts, and a client retrying a malformed request must not inflate
    the model's SLO error rate. Streaming handlers call ``defer(result)``
    to hand completion to the stream's end instead."""

    def __init__(self, recorder: Optional["FlightRecorder"],
                 record: Optional[FlightRecord]):
        self.recorder = recorder
        self.record = record
        self._deferred = False

    def defer(self, result: Any) -> Any:
        """Wrap a Stream result: the record completes when the stream
        ends (or the client disconnects), not when the handler returns."""
        self._deferred = True
        if self.recorder is None:
            return result
        return self.recorder.finish_stream(result, self.record)

    def __enter__(self) -> "Flight":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        if self.recorder is None or self.record is None or self._deferred:
            return False
        if exc is None:
            self.recorder.finish(self.record)
            return False
        status_code = getattr(exc, "status_code", None)
        if (
            self.record.status != "error"  # the device never noted a failure
            and isinstance(status_code, int) and status_code < 500
        ):
            return False  # parameter rejection before inference: no record
        self.recorder.finish(self.record, error=exc)
        return False


def flight(
    recorder: Optional["FlightRecorder"],
    model: str,
    endpoint: str,
    trace_id: str = "",
    tokens_in: int = 0,
    stream: bool = False,
) -> Flight:
    """Start (and contextvar-activate) a FlightRecord under a ``Flight``
    lifecycle guard; recorder None (bare test containers) yields an
    inert guard whose ``defer`` passes results through untouched."""
    record = None
    if recorder is not None:
        record = recorder.start(
            model=model, endpoint=endpoint, trace_id=trace_id,
            tokens_in=tokens_in, stream=stream,
        )
    return Flight(recorder, record)


class TenantLedger:
    """Bounded per-tenant usage metering: a space-saving heavy-hitter
    sketch over hashed tenant ids.

    Exactly ``size`` tenants are tracked at a time (``TENANT_LEDGER_SIZE``,
    default 256). Per tracked tenant the ledger keeps exact counters —
    requests, tokens in/out, sheds, deadline misses, errors — from the
    moment the tenant entered the table. When a new tenant arrives at a
    full table, the minimum-weight slot (weight = requests + sheds) is
    evicted: its counters roll into the ``~other`` aggregate (sum
    conservation — fleet totals never lose a request), and the newcomer
    starts fresh carrying ``err`` = the evicted weight, the classic
    space-saving undercount bound ("this tenant may have had up to err
    earlier requests attributed to ~other"). Heavy hitters therefore
    stay exact: once a tenant's weight exceeds the churn floor it is
    never the minimum, so 10k distinct scanners can never evict a real
    workload — and, critically, NO per-tenant Prometheus series is ever
    minted (bounded cardinality is the point; the only /metrics surface
    is the tracked-entries gauge and the overflow counter).

    Lock-guarded dict arithmetic only — the feed point is
    ``FlightRecorder.finish`` plus the shed paths, i.e. the request hot
    path (bench.py's slo_microbench keeps the cost honest)."""

    OTHER = "~other"
    FIELDS = (
        "requests", "tokens_in", "tokens_out", "sheds",
        "deadline_misses", "errors",
    )

    def __init__(self, size: int = 256, metrics: Any = None):
        if size < 1:
            raise ValueError("TENANT_LEDGER_SIZE must be >= 1")
        self.size = int(size)
        self._slots: dict[str, dict[str, int]] = {}
        self._other: dict[str, int] = {f: 0 for f in self.FIELDS}
        self._evictions = 0
        self._lock = threading.Lock()
        self._tracked_gauge = (
            metrics.gauge(
                "gofr_tpu_tenants_tracked_entries",
                "tenants currently tracked exactly by the ledger "
                "(bounded by TENANT_LEDGER_SIZE; the rest aggregate "
                "into ~other)",
            )
            if metrics is not None else None
        )
        self._overflow_counter = (
            metrics.counter(
                "gofr_tpu_tenant_overflow_total",
                "tenant slots evicted into the ~other aggregate "
                "(space-saving overflow)",
            )
            if metrics is not None else None
        )

    @staticmethod
    def _weight(slot: dict[str, int]) -> int:
        return slot["requests"] + slot["sheds"]

    def observe(
        self,
        tenant: str,
        requests: int = 0,
        tokens_in: int = 0,
        tokens_out: int = 0,
        sheds: int = 0,
        deadline_misses: int = 0,
        errors: int = 0,
    ) -> None:
        """Add one observation to ``tenant``'s slot (admitting it into
        the table, evicting the minimum-weight slot if full)."""
        if not tenant:
            return
        evicted = False
        with self._lock:
            slot = self._slots.get(tenant)
            if slot is None:
                err = 0
                if len(self._slots) >= self.size:
                    victim = min(self._slots, key=lambda t: self._weight(self._slots[t]))
                    old = self._slots.pop(victim)
                    for field in self.FIELDS:
                        self._other[field] += old[field]
                    err = self._weight(old)
                    self._evictions += 1
                    evicted = True
                slot = {f: 0 for f in self.FIELDS}
                slot["err"] = err
                self._slots[tenant] = slot
            slot["requests"] += requests
            slot["tokens_in"] += tokens_in
            slot["tokens_out"] += tokens_out
            slot["sheds"] += sheds
            slot["deadline_misses"] += deadline_misses
            slot["errors"] += errors
            tracked = len(self._slots)
        # metric writes OUTSIDE the ledger lock (registry has its own)
        if evicted and self._overflow_counter is not None:
            self._overflow_counter.inc()
        if self._tracked_gauge is not None:
            self._tracked_gauge.set(float(tracked))

    def shed(self, tenant: str) -> None:
        """Meter one shed (brownout / quota / router 429-503): sheds
        never create a FlightRecord, so the shed sites feed directly."""
        self.observe(tenant, sheds=1)

    # -- read side (admin API / postmortem / fleetsim) -----------------------
    def get(self, tenant: str) -> Optional[dict[str, Any]]:
        """One tenant's exact counters (None = not currently tracked —
        it may still have history inside ``~other``)."""
        with self._lock:
            slot = self._slots.get(tenant)
            if slot is None:
                return None
            return dict(slot, tenant=tenant)

    def top(self, k: int = 50) -> list[dict[str, Any]]:
        """Top-``k`` tracked tenants by total tokens (in + out), ties
        broken by weight — the '/admin/tenants' default page."""
        with self._lock:
            rows = [dict(slot, tenant=t) for t, slot in self._slots.items()]
        rows.sort(
            key=lambda r: (
                r["tokens_in"] + r["tokens_out"],
                r["requests"] + r["sheds"],
                r["tenant"],
            ),
            reverse=True,
        )
        return rows[: max(0, k)]

    def totals(self) -> dict[str, int]:
        """Exact fleet-wide counters: tracked slots + ~other summed (sum
        conservation — eviction moves counts, never drops them)."""
        with self._lock:
            out = dict(self._other)
            for slot in self._slots.values():
                for field in self.FIELDS:
                    out[field] += slot[field]
        return out

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "tracked": len(self._slots),
                "size": self.size,
                "evictions": self._evictions,
                "other": dict(self._other),
            }

    def snapshot(self, k: int = 50) -> dict[str, Any]:
        """The ``/admin/tenants`` (and postmortem ``tenants`` block)
        shape: stats + totals + the top-``k`` page."""
        return dict(self.stats(), totals=self.totals(), tenants=self.top(k))

    def overview(self, k: int = 3) -> dict[str, Any]:
        """Compact headline for /admin/overview and the /admin/engine
        scrape: tracked count, eviction pressure, the top-``k`` heavy
        hitters by tokens."""
        stats = self.stats()
        return {
            "tracked": stats["tracked"],
            "size": stats["size"],
            "evictions": stats["evictions"],
            "top": [
                {
                    "tenant": r["tenant"],
                    "requests": r["requests"],
                    "tokens": r["tokens_in"] + r["tokens_out"],
                    "sheds": r["sheds"],
                }
                for r in self.top(k)
            ],
        }


class FlightRecorder:
    """Thread-safe bounded store of completed FlightRecords.

    ``capacity`` bounds the main ring (most recent completions);
    ``keep`` bounds the side buffer that always retains slow/errored
    requests even after the ring evicts them. ``slow_threshold_s``
    classifies slow: total duration or TTFT past it. ``tenants`` is the
    optional :class:`TenantLedger` every finished record meters into."""

    def __init__(
        self,
        capacity: int = 512,
        keep: int = 128,
        slow_threshold_s: float = 2.0,
        logger: Any = None,
        tenants: Optional["TenantLedger"] = None,
    ):
        self.capacity = capacity
        self.slow_threshold_s = slow_threshold_s
        self.logger = logger
        self.tenants = tenants
        self._ring: "deque[FlightRecord]" = deque(maxlen=max(1, capacity))
        self._notable: "deque[FlightRecord]" = deque(maxlen=max(1, keep))
        # records started but not yet finished — the postmortem bundle
        # needs the requests riding a WEDGED dispatch, and those never
        # reach the ring. Weak values: a record abandoned without finish
        # (pre-inference parameter rejection) vanishes with its request
        # instead of leaking here forever.
        self._active: "weakref.WeakValueDictionary[int, FlightRecord]" = (
            weakref.WeakValueDictionary()
        )
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def start(
        self,
        model: str,
        endpoint: str,
        trace_id: str = "",
        tokens_in: int = 0,
        stream: bool = False,
        activate: bool = True,
    ) -> FlightRecord:
        record = FlightRecord(
            model=model, endpoint=endpoint, trace_id=trace_id,
            tokens_in=tokens_in, stream=stream,
        )
        with self._lock:
            self._active[id(record)] = record
        if activate:
            activate_record(record)
        return record

    def finish(
        self,
        record: Optional[FlightRecord],
        status: str = "ok",
        error: Optional[BaseException] = None,
    ) -> None:
        """Complete a record: stamps done, lands it in the buffers, and
        emits the wide-event log line. Idempotent — the first finish
        wins (a stream wrapper and an error path may both reach it)."""
        if record is None or record.t_done is not None:
            return
        record.t_done = time.perf_counter()
        record.wall_done = time.time()  # gofrlint: wall-clock — /admin/requests display timestamp
        if error is not None:
            record.note_error(error)
        elif record.status == "in_flight":
            record.status = status
        with self._lock:
            self._active.pop(id(record), None)
            self._ring.append(record)
            if self.is_slow(record) or record.status != "ok":
                self._notable.append(record)
        # per-tenant usage metering: every completed flight lands in the
        # bounded ledger (sheds never reach here — the shed sites feed
        # the ledger directly). Cancelled still counts as a request: the
        # tenant consumed admission + tokens up to the abort.
        if self.tenants is not None and record.tenant:
            self.tenants.observe(
                record.tenant,
                requests=1,
                tokens_in=record.tokens_in,
                tokens_out=record.tokens_out,
                deadline_misses=(
                    1 if record.status == "deadline_exceeded" else 0
                ),
                errors=1 if record.status == "error" else 0,
            )
        if self.logger is not None:
            try:
                self.logger.info(record.to_dict())
            except Exception:
                # gofrlint: disable=GFL006 — wide-event log emission:
                # telemetry must never take a request down
                pass

    def is_slow(self, record: FlightRecord) -> bool:
        duration = record.duration or 0.0
        ttft = record.ttft or 0.0
        return max(duration, ttft) >= self.slow_threshold_s

    def finish_stream(self, result: Any, record: Optional[FlightRecord]) -> Any:
        """Wrap a handler's Stream result so ``record`` completes when
        the stream ends — normal exhaustion, an error, or the client
        disconnecting (generator close). Non-Stream results pass
        through untouched (the caller finishes synchronously)."""
        from gofr_tpu.http.response import Stream

        if record is None or not isinstance(result, Stream):
            return result
        events = result.events

        def guarded() -> Any:
            try:
                yield from events
            except GeneratorExit:
                self.finish(record, status="cancelled")
                raise
            except BaseException as exc:
                self.finish(record, error=exc)
                raise
            else:
                self.finish(record)

        result.events = guarded()
        return result

    # -- read side (admin API / postmortem) ----------------------------------
    def active_count(self) -> int:
        """In-flight request count — the cheap read for rollups that
        only need the number, not the serialized records."""
        with self._lock:
            return len(self._active)

    def active_records(self) -> list[dict[str, Any]]:
        """Records started but not finished — the requests in flight RIGHT
        NOW, oldest first. This is what a postmortem bundle needs most:
        the requests riding a wedged dispatch never reach the ring."""
        with self._lock:
            active = sorted(self._active.values(), key=lambda r: r.t_start)
        return [r.to_dict() for r in active]

    def records(
        self,
        slow: Optional[bool] = None,
        errored: Optional[bool] = None,
        limit: int = 100,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> list[dict[str, Any]]:
        """Most-recent-first record dicts. ``slow=True``/``errored=True``
        filter; ``request_id``/``trace_id``/``tenant`` match exactly
        (the jump from an id in a log line — or a hashed tenant id off a
        429 body — to the records that carried it); the side buffer is
        merged in so flagged requests stay visible after ring
        eviction."""
        with self._lock:
            merged: list[FlightRecord] = list(self._ring)
            seen = {id(r) for r in merged}
            merged.extend(r for r in self._notable if id(r) not in seen)
        merged.sort(key=lambda r: r.t_done or r.t_start)
        out = []
        for record in reversed(merged):
            if slow is not None and self.is_slow(record) != slow:
                continue
            if errored is not None and (record.status != "ok") != errored:
                continue
            if request_id is not None and record.request_id != request_id:
                continue
            if trace_id is not None and record.trace_id != trace_id:
                continue
            if tenant is not None and record.tenant != tenant:
                continue
            out.append(record.to_dict())
            if len(out) >= limit:
                break
        return out

    def finished_since(self, horizon: float) -> list[FlightRecord]:
        """Completed records with ``t_done >= horizon`` (a
        ``time.perf_counter`` mark, the records' own timebase) — the SLO
        engine's windowed scan. Returns the live record objects (marks
        are set-once, completed records no longer mutate): treat as
        read-only."""
        with self._lock:
            return [
                r for r in self._ring
                if r.t_done is not None and r.t_done >= horizon
            ]

    def slo(self, window_s: float = 300.0) -> dict[str, Any]:
        """Rolling-window per-model SLO view: exact p50/p95/p99 of TTFT
        and TPOT over requests completed in the last ``window_s``
        seconds, computed from the raw records (a cumulative histogram
        cannot express a rolling window and only knows bucket bounds)."""
        # monotonic window: wall-clock steps (NTP, suspend) must never
        # grow or shrink the SLO window
        horizon = time.perf_counter() - window_s
        with self._lock:
            recent = [
                r for r in self._ring
                if r.t_done is not None and r.t_done >= horizon
            ]
        models: dict[str, Any] = {}
        for model in sorted({r.model for r in recent}):
            rows = [r for r in recent if r.model == model]
            ttfts = [r.ttft for r in rows if r.ttft is not None]
            tpots = [r.tpot for r in rows if r.tpot is not None]
            entry: dict[str, Any] = {
                "count": len(rows),
                "errors": sum(1 for r in rows if r.status != "ok"),
            }
            if ttfts:
                entry["ttft_s"] = _percentiles(ttfts)
            if tpots:
                entry["tpot_s"] = _percentiles(tpots)
            # interference-scheduler visibility: how often prefills were
            # chunked and how much their chunks waited for decode turns
            defers = [r.sched_defer_s for r in rows if r.sched_defer_s]
            if defers:
                entry["sched_defer_s"] = _percentiles(defers)
            chunked = sum(1 for r in rows if r.prefill_chunks > 1)
            if chunked:
                entry["chunked_prefills"] = chunked
            # pooled speculative decoding: emitted tokens per verify
            # dispatch across the window's spec-riding requests (1.0 =
            # plain decode; the fleet SLO the spec bench gates on)
            tpds = [
                r.tokens_per_dispatch for r in rows
                if r.tokens_per_dispatch is not None
            ]
            if tpds:
                entry["tokens_per_dispatch"] = _percentiles(tpds)
            models[model] = entry
        return {"window_s": window_s, "models": models}
