"""Device profiling: JAX/XLA trace capture behind admin endpoints.

SURVEY.md §5: the reference has no continuous profiler (no pprof
endpoints); the TPU build adds device profiling via the runtime's profiler
hooks. ``jax.profiler.start_trace`` captures XLA device traces (HLO
timelines, memory viewer data) into a TensorBoard-compatible directory;
the admin endpoints (handler.py: POST /admin/profiler/start|stop, GET
/admin/profiler) drive it on a live serving process, so a production TTFT
regression can be traced without redeploying.

Per-batch device time is additionally recorded as a span tag on every
dispatched batch (tpu/device.py ``tpu-batch`` spans) — the always-on,
cheap signal; full traces are the on-demand deep dive.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Optional


class Profiler:
    """Thread-safe wrapper around one active jax.profiler trace session."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._started_at: Optional[float] = None

    def start(self, log_dir: Optional[str] = None) -> dict[str, Any]:
        import jax

        from gofr_tpu.config import get_env

        with self._lock:
            if self._dir is not None:
                raise RuntimeError(f"profiler already tracing into {self._dir}")
            log_dir = log_dir or get_env("PROFILE_DIR") or tempfile.mkdtemp(
                prefix="gofr-profile-"
            )
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
            self._dir = log_dir
            self._started_at = time.monotonic()
            return {"state": "tracing", "dir": log_dir}

    def stop(self) -> dict[str, Any]:
        import jax

        with self._lock:
            if self._dir is None:
                raise RuntimeError("profiler is not tracing")
            # clear state BEFORE stop_trace: if collection fails the
            # profiler must not wedge in "tracing" forever (the endpoint
            # exists to debug live processes; restarting defeats it)
            log_dir, self._dir = self._dir, None
            elapsed = time.monotonic() - (self._started_at or time.monotonic())
            self._started_at = None
            jax.profiler.stop_trace()
        files = []
        for root, _, names in os.walk(log_dir):
            files.extend(os.path.relpath(os.path.join(root, n), log_dir) for n in names)
        return {
            "state": "stopped", "dir": log_dir,
            "seconds": round(elapsed, 2), "artifacts": sorted(files),
        }

    def status(self) -> dict[str, Any]:
        with self._lock:
            if self._dir is None:
                return {"state": "idle"}
            return {
                "state": "tracing", "dir": self._dir,
                "seconds": round(time.monotonic() - (self._started_at or 0), 2),
            }


_PROFILER = Profiler()


def profiler() -> Profiler:
    """Process-wide profiler (the device runtime is process-wide too)."""
    return _PROFILER
