"""Loss primitives shared by the plain, ring (sp), and pipeline (pp)
training paths — one definition so the parallel losses can never silently
diverge from the baseline the tests compare against."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def next_token_nll(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Per-position negative log-likelihood.

    logits [..., S, V] (any float dtype; softmax accumulates in f32),
    targets [..., S] int -> nll [..., S] float32.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
