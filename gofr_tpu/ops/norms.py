"""Normalization ops. Accumulation in float32 regardless of input dtype
(bfloat16 activations keep MXU-friendly layouts; the variance reduction is
the numerically sensitive part)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm (Llama-family). Keeps input dtype on output."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return ((xf / rms) * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
