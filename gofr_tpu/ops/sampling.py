"""On-device token sampling: temperature, top-k, nucleus (top-p).

Decode-time sampling runs on the accelerator (one fused kernel over the
[B, V] logits — no host round-trip of the full vocab distribution), keyed
by ``jax.random`` so a request seed makes generation reproducible.

Semantics (the standard composition): logits are temperature-scaled, then
top-k filtered, then nucleus-filtered (smallest prefix of the sorted
distribution whose mass reaches ``top_p``; always at least one token),
then min-p filtered (drop tokens whose probability is below ``min_p``
times the top token's), then sampled categorically. ``temperature=0``
short-circuits to argmax.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)


def apply_repetition_penalty(
    logits: jnp.ndarray, presence: jnp.ndarray, penalty: jnp.ndarray | float
) -> jnp.ndarray:
    """CTRL-style repetition penalty: tokens already in the context
    (``presence`` [B, V] bool — prompt plus generated) have positive
    logits divided by ``penalty`` and negative logits multiplied by it.
    ``penalty`` is a scalar or a per-row [B, 1] array (1 = off — the
    penalized pool executable carries one knob per slot); applies BEFORE
    the greedy/sampled split so greedy decode is penalized too (the HF
    semantics)."""
    logits = logits.astype(jnp.float32)
    penalty = jnp.asarray(penalty, jnp.float32)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(presence, penalized, logits)


def apply_penalties(
    logits: jnp.ndarray,
    presence: jnp.ndarray,
    repetition_penalty: jnp.ndarray | float,
    counts: jnp.ndarray,
    presence_penalty: jnp.ndarray | float = 0.0,
    frequency_penalty: jnp.ndarray | float = 0.0,
    bias: jnp.ndarray | float = 0.0,
) -> jnp.ndarray:
    """All sampling penalties in one place. ``presence`` [B, V] bool
    covers the whole context (prompt + generated) and drives the CTRL
    repetition penalty; ``counts`` [B, V] f32 counts GENERATED tokens only
    and drives the additive OpenAI penalties: ``presence_penalty`` is
    subtracted once for any token already generated, ``frequency_penalty``
    once per occurrence. ``bias`` [B, V] f32 is the OpenAI ``logit_bias``
    row (added last — ±100 bans/forces a token regardless of the other
    penalties). All knob operands are dynamic — one compiled penalized
    executable serves every combination."""
    logits = apply_repetition_penalty(logits, presence, repetition_penalty)
    counts = counts.astype(jnp.float32)
    presence_penalty = jnp.asarray(presence_penalty, jnp.float32)
    frequency_penalty = jnp.asarray(frequency_penalty, jnp.float32)
    return (
        logits
        - presence_penalty * (counts > 0).astype(jnp.float32)
        - frequency_penalty * counts
        + bias
    )


def check_bias_ids(logit_bias: dict, vocab_size: int) -> None:
    """Raise ValueError if any ``logit_bias`` token id falls outside the
    vocab (map to a 400 — a silently dropped ban is worse than a
    refusal). The ONE home for this rule: the row builder below and the
    streaming path's eager pre-commit check both call it, so the
    streaming and non-streaming 400s cannot drift."""
    for tok in logit_bias:
        if not 0 <= tok < vocab_size:
            raise ValueError(
                f'"logit_bias" token id {tok} outside vocab [0, {vocab_size})'
            )


def bias_row_from_map(logit_bias: dict, vocab_size: int) -> jnp.ndarray:
    """[1, V] f32 additive-bias row from a validated ``{token_id: bias}``
    map (host-side build, one upload per biased request). Raises
    ValueError on out-of-vocab ids via ``check_bias_ids``."""
    import numpy as np

    check_bias_ids(logit_bias, vocab_size)
    row = np.zeros((1, vocab_size), np.float32)
    for tok, bias in logit_bias.items():
        row[0, tok] = bias
    return jnp.asarray(row)


def update_counts(counts: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Count freshly sampled ``tokens`` [B] into ``counts`` [B, V] f32
    (inside the decode scan — one scatter-add per step)."""
    b = counts.shape[0]
    return counts.at[jnp.arange(b), tokens].add(1.0)


def presence_from_tokens(ids: Any, vocab_size: int) -> jnp.ndarray:
    """[1, V] bool presence row for a prompt (host-side build, one upload
    per penalized request)."""
    import numpy as np

    row = np.zeros((1, vocab_size), bool)
    row[0, np.asarray(ids, np.int32)] = True
    return jnp.asarray(row)


def update_presence(presence: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Mark freshly sampled ``tokens`` [B] in ``presence`` [B, V] (inside
    the decode scan — one scatter per step)."""
    b = presence.shape[0]
    return presence.at[jnp.arange(b), tokens].set(True)


def _filter_top_k_top_p(
    scaled: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray | float = 0.0,
) -> jnp.ndarray:
    """Apply top-k, nucleus (top-p), and min-p filtering to
    temperature-scaled logits. ``scaled`` [B, V]; ``top_k`` [B] int32
    (0 = off); ``top_p`` [B, 1] f32 (1 = off); ``min_p`` [B, 1] f32
    (0 = off; drop tokens whose probability is below min_p times the top
    token's — scale-aware tail truncation).

    ONE full-vocab sort serves all three filters (a [B, V] sort is the
    expensive op here — V is 128K for llama3): top-k thresholds at the
    k-th largest value, and the nucleus and min-p cutoffs are computed in
    the same sorted space (masking below the top-k threshold there is
    order-preserving, so no second sort of the filtered array). Nucleus
    uses sequential-warper semantics: drop tokens whose EXCLUSIVE
    cumulative probability (descending order) has already reached top_p;
    the argmax token always survives (its exclusive cumsum is 0, and its
    probability trivially clears its own min-p bar)."""
    b, v = scaled.shape
    min_p = jnp.asarray(min_p, jnp.float32)
    if min_p.ndim == 0:
        min_p = jnp.full((b, 1), min_p)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)  # [B]
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    sorted_k = jnp.where(sorted_desc < kth, _NEG_INF, sorted_desc)

    probs = jax.nn.softmax(sorted_k, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive
    cutoff_logit = jnp.min(
        jnp.where(cum < top_p, sorted_k, jnp.inf), axis=-1, keepdims=True
    )
    # min-p: keep tokens with prob >= min_p * top prob (probs[:, :1] is
    # the max — descending order)
    keep_mp = probs >= min_p * probs[:, :1]
    cutoff_mp = jnp.min(
        jnp.where(keep_mp, sorted_k, jnp.inf), axis=-1, keepdims=True
    )
    cutoff = jnp.maximum(kth, jnp.maximum(cutoff_logit, cutoff_mp))
    return jnp.where(scaled < cutoff, _NEG_INF, scaled)


def warped_probs(
    logits: jnp.ndarray,
    temperature: jnp.ndarray | float,
    top_k: jnp.ndarray | int = 0,
    top_p: jnp.ndarray | float = 1.0,
    min_p: jnp.ndarray | float = 0.0,
) -> jnp.ndarray:
    """[N, V] logits -> the exact warped DISTRIBUTION ``sample_logits``
    samples from (temperature scale, then top-k/top-p/min-p filters,
    then softmax). Speculative sampling needs the full rows: the draft
    samples from its warped q and returns it, and the target's accept
    test and residual max(p - q, 0) both compare whole distributions.
    Call only with temperature > 0 (greedy spec takes the argmax path)."""
    logits = logits.astype(jnp.float32)
    n = logits.shape[0]
    temperature = jnp.asarray(temperature, jnp.float32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    filtered = _filter_top_k_top_p(
        scaled,
        jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (n,)),
        jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (n, 1)),
        jnp.broadcast_to(jnp.asarray(min_p, jnp.float32), (n, 1)),
    )
    return jax.nn.softmax(filtered, axis=-1)


@jax.jit
def sample_logits(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: float | jnp.ndarray = 1.0,
    top_k: int | jnp.ndarray = 0,
    top_p: float | jnp.ndarray = 1.0,
    min_p: float | jnp.ndarray = 0.0,
) -> jnp.ndarray:
    """[B, V] float logits -> [B] int32 sampled token ids.

    temperature, top_k, top_p, and min_p are ALL dynamic operands: one
    compiled sampler serves every request — request-supplied knobs must
    never recompile on the serving path."""
    logits = logits.astype(jnp.float32)
    b = logits.shape[0]
    temperature = jnp.asarray(temperature, jnp.float32)
    top_p = jnp.asarray(top_p, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    min_p = jnp.asarray(min_p, jnp.float32)

    def _greedy() -> jnp.ndarray:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled() -> jnp.ndarray:
        scaled = logits / jnp.maximum(temperature, 1e-6)
        filtered = _filter_top_k_top_p(
            scaled,
            jnp.broadcast_to(top_k, (b,)),
            jnp.broadcast_to(top_p, (b, 1)),
            jnp.broadcast_to(min_p, (b, 1)),
        )
        return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)

    # cond, not where: the greedy default (every /generate without a
    # temperature) must not pay the full-vocab sort per step
    return jax.lax.cond(temperature <= 0.0, _greedy, _sampled)


@jax.jit
def sample_logits_rows(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray | float = 0.0,
) -> jnp.ndarray:
    """Per-ROW sampling params: logits [B, V], temperature/top_k/top_p/
    min_p each [B] -> [B] int32 ids. The continuous-batching decode pool
    mixes requests with different sampling settings in one dispatch, so
    each row carries its own knobs (rows with temperature 0 take their
    argmax)."""
    logits = logits.astype(jnp.float32)
    b = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.asarray(temperature, jnp.float32).reshape(b, 1)
    top_p = jnp.asarray(top_p, jnp.float32).reshape(b, 1)
    top_k = jnp.asarray(top_k, jnp.int32).reshape(b)
    min_p = jnp.broadcast_to(jnp.asarray(min_p, jnp.float32), (b,)).reshape(b, 1)

    def _mixed() -> jnp.ndarray:
        scaled = logits / jnp.maximum(temperature, 1e-6)
        filtered = _filter_top_k_top_p(scaled, top_k, top_p, min_p)
        sampled = jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)
        return jnp.where(temperature[:, 0] <= 0.0, greedy, sampled)

    # cond, not where: an all-greedy batch (the common pool state — every
    # /generate without a temperature) must not pay a full-vocab sort per
    # decode step; the pool dispatches this inside every chunk
    return jax.lax.cond(jnp.all(temperature <= 0.0), lambda: greedy, _mixed)


def stop_tokens_from_body(body: dict) -> Optional[list[int]]:
    """Parse "stop_tokens" from a request body: a list of token ids that
    end generation (the stop token itself is not emitted). Shared by the
    HTTP/gRPC handlers, next to Sampler.from_body. Raises ValueError on a
    malformed value (map to a 400)."""
    stop_tokens = body.get("stop_tokens")
    if stop_tokens is None:
        return None
    if not isinstance(stop_tokens, list) or not all(
        isinstance(t, int) and not isinstance(t, bool) for t in stop_tokens
    ):
        raise ValueError('"stop_tokens" must be a list of token ids')
    return stop_tokens


class Sampler:
    """Per-request sampling state: seeded key split per step. A plain
    Python object driven by the host decode loop (the [B, V] math above is
    the on-device part)."""

    def __init__(
        self,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        min_p: float = 0.0,
        repetition_penalty: float = 1.0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        logit_bias: Optional[dict] = None,
        seed: Optional[int] = None,
    ):
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if not 0.0 <= min_p < 1.0:
            raise ValueError("min_p must be in [0, 1)")
        if repetition_penalty <= 0.0:
            raise ValueError("repetition_penalty must be > 0")
        # the OpenAI documented range for both additive penalties
        if not -2.0 <= presence_penalty <= 2.0:
            raise ValueError("presence_penalty must be in [-2, 2]")
        if not -2.0 <= frequency_penalty <= 2.0:
            raise ValueError("frequency_penalty must be in [-2, 2]")
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.min_p = float(min_p)
        self.repetition_penalty = float(repetition_penalty)
        self.presence_penalty = float(presence_penalty)
        self.frequency_penalty = float(frequency_penalty)
        self.logit_bias: Optional[dict] = None
        if logit_bias:
            if not isinstance(logit_bias, dict):
                raise ValueError('"logit_bias" must be a map of token id to bias')
            parsed: dict = {}
            for k, v in logit_bias.items():
                try:
                    tok = int(k)  # OpenAI clients send string keys (JSON)
                    val = float(v)
                except (TypeError, ValueError):
                    raise ValueError(
                        '"logit_bias" must map token ids to numbers'
                    ) from None
                if not -100.0 <= val <= 100.0:
                    raise ValueError(
                        '"logit_bias" values must be in [-100, 100]'
                    )
                parsed[tok] = val
            self.logit_bias = parsed
        self.seeded = seed is not None
        # the REQUEST's seed (None when unseeded): the generation
        # journal keys on it — two requests that sample from different
        # key streams must never share a resume identity
        self.seed = int(seed) if seed is not None else None
        if seed is None:
            # unseeded requests must be genuinely random, not key(0)
            import secrets

            seed = secrets.randbits(63)
        self._key = jax.random.key(int(seed))

    @classmethod
    def from_body(cls, body: dict) -> "Sampler":
        """Build from a request body's sampling keys (temperature, top_k,
        top_p, min_p, repetition_penalty, presence_penalty,
        frequency_penalty, seed) — the shared parse for HTTP/gRPC
        handlers. An explicit JSON null means "use the default" (the
        OpenAI fields are nullable), never a 400.
        Raises ValueError/TypeError on malformed values (map to a 400)."""

        def get(key: str, default):
            value = body.get(key)
            return default if value is None else value

        return cls(
            temperature=float(get("temperature", 0.0)),
            top_k=int(get("top_k", 0)),
            top_p=float(get("top_p", 1.0)),
            min_p=float(get("min_p", 0.0)),
            repetition_penalty=float(get("repetition_penalty", 1.0)),
            presence_penalty=float(get("presence_penalty", 0.0)),
            frequency_penalty=float(get("frequency_penalty", 0.0)),
            logit_bias=get("logit_bias", None),
            seed=body.get("seed"),
        )

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    @property
    def penalized(self) -> bool:
        """True when any penalty or logit bias is active: such requests
        thread presence/counts/bias state through decode — pooled via
        per-slot penalty rows (``DECODE_POOL_PENALTIES``), or the solo
        chunk variant when also seeded/logprobs/adapter-bound."""
        return (
            self.repetition_penalty != 1.0
            or self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or bool(self.logit_bias)
        )

    def take_key(self) -> jax.Array:
        """Split off a fresh subkey (device-side sampling in decode_chunk)."""
        self._key, sub = jax.random.split(self._key)
        return sub

    def pick(self, logits) -> int:
        """[V] or [1, V] logits -> one token id."""
        logits = jnp.asarray(logits)
        if logits.ndim == 1:
            logits = logits[None, :]
        if self.greedy:
            return int(jnp.argmax(logits[0]))
        self._key, sub = jax.random.split(self._key)
        return int(
            sample_logits(
                logits, sub, temperature=self.temperature,
                top_k=self.top_k, top_p=self.top_p, min_p=self.min_p,
            )[0]
        )
