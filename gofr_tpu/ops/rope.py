"""Rotary position embeddings (split-half convention, Llama-style).

Frequencies are precomputed once per model config and closed over by the
jitted forward — no per-step trig on the hot path beyond the gather.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0) -> jnp.ndarray:
    """Returns [max_seq, head_dim//2] complex-free (cos, sin) stacked as
    [max_seq, head_dim//2, 2] float32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_seq, head_dim//2]
    return jnp.stack([jnp.cos(freqs), jnp.sin(freqs)], axis=-1)


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, n_heads, head_dim] by position.

    ``positions``: [seq] or [batch, seq] absolute positions (decode passes
    the cache offset). Split-half convention: (x1, x2) -> (x1*cos - x2*sin,
    x2*cos + x1*sin).
    """
    dtype = x.dtype
    cos_sin = freqs[positions]  # [..., seq, head_dim//2, 2]
    cos = cos_sin[..., 0][..., None, :]  # broadcast over heads: [..., seq, 1, hd/2]
    sin = cos_sin[..., 1][..., None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)
