"""TPU-first compute ops: norms, rotary embeddings, attention.

The reference framework has no compute layer (SURVEY.md §0: zero ML
components); these ops exist for the TPU-native capability — models compiled
with jit/pjit and served through the TPU datasource. Each op has a pure-XLA
reference implementation; the attention hot op additionally has a Pallas
flash kernel used automatically on TPU (``gofr_tpu.ops.flash_attention``).
"""

from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import layer_norm, rms_norm
from gofr_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = ["attention", "rms_norm", "layer_norm", "apply_rope", "rope_frequencies"]
