"""Multi-head / grouped-query attention with selectable implementation.

- ``impl="xla"``: pure-jnp reference (softmax in f32, grouped einsum so GQA
  never materializes repeated KV heads).
- ``impl="pallas"``: Pallas TPU flash-attention kernel (gofr_tpu.ops.flash);
  runs in interpret mode on non-TPU backends so tests cover the kernel.
- ``impl="auto"``: pallas on TPU when shapes are tile-friendly, else XLA.

Layouts: q [B, Sq, Hq, D]; k, v [B, Skv, Hkv, D]; Hq % Hkv == 0.
``q_offset`` positions the query block absolutely (decode: cache length).
``kv_lens`` [B] bounds the valid key prefix (padded/unwritten cache tail)
— the structured form of a padding mask, supported by both paths.
``mask`` is an arbitrary boolean mask ([B, Skv] or [B, Sq, Skv]); only the
XLA path supports it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    mask: Optional[jnp.ndarray] = None,
    kv_lens: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    if k.dtype != q.dtype:
        # low-precision KV cache (float8_e4m3fn via cfg.kv_dtype): upcast
        # at the attention boundary — capacity is the win (2x tokens per
        # HBM byte); a fused low-precision cache read in the kernel is the
        # follow-on traffic optimization
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    if impl == "auto":
        # arbitrary masks stay on the XLA path (kv_lens is fine: the flash
        # kernel bounds its KV loop with it)
        impl = "pallas" if (mask is None and _pallas_ok(q, k)) else "xla"
    if impl == "pallas":
        if mask is not None:
            raise NotImplementedError(
                "pallas flash attention supports kv_lens=, not arbitrary mask="
            )
        from gofr_tpu.ops.flash import flash_attention

        return flash_attention(
            q, k, v, causal=causal, q_offset=q_offset, kv_lens=kv_lens, scale=scale
        )
    if kv_lens is not None:
        len_mask = jnp.arange(k.shape[1])[None, :] < kv_lens[:, None]  # [B, Skv]
        if mask is None:
            mask = len_mask
        elif mask.ndim == 2:
            mask = jnp.logical_and(mask, len_mask)
        else:
            mask = jnp.logical_and(mask, len_mask[:, None, :])
    return _xla_attention(q, k, v, causal, q_offset, mask, scale)


def _pallas_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    if jax.default_backend() not in ("tpu",):
        return False
    b, sq, hq, d = q.shape
    skv = k.shape[1]
    if sq == 1 and skv < 2048:
        # short-cache decode: per-layer kernel launch overhead outweighs
        # the bounded-KV-loop win (measured on llama3-8b int8, 512-slot
        # cache, v5e: 18.0ms/step XLA vs 22.7ms/step pallas). The ragged
        # kernel pays off once the cache is long enough that XLA's
        # O(max_seq) masked softmax dominates.
        return False
    return d % 128 == 0 and skv % 128 == 0


def _xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool,
    q_offset: int | jnp.ndarray,
    mask: Optional[jnp.ndarray],
    scale: Optional[float],
) -> jnp.ndarray:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    # [b, hkv, groups, sq, skv]; accumulate in f32 for softmax stability
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale

    if causal:
        k_pos = jnp.arange(skv)
        offset = jnp.asarray(q_offset)
        if offset.ndim == 0:
            q_pos = offset + jnp.arange(sq)  # [sq]
            causal_mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None]
        else:
            # per-batch offsets [b]: ragged decode positions
            q_pos = offset.reshape(-1, 1) + jnp.arange(sq)[None, :]  # [b, sq]
            causal_mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, None]
        logits = jnp.where(causal_mask, logits, _NEG_INF)
    if mask is not None:
        # mask: [b, skv] key-validity (padding) or [b, sq, skv]
        if mask.ndim == 2:
            m = mask[:, None, None, None, :]
        else:
            m = mask[:, None, None, :, :]
        logits = jnp.where(m, logits, _NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if causal or mask is not None:
        # fully-masked rows (e.g. kv_lens == 0 padding slots) emit zeros —
        # same convention as the flash kernel's l == 0 guard — instead of
        # the uniform-softmax mean(v) that finite -inf masking would give
        all_masked = jnp.all(logits <= _NEG_INF / 2, axis=-1, keepdims=True)
        probs = jnp.where(all_masked, 0.0, probs.astype(jnp.float32)).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(b, sq, hq, d)
