"""Multi-head / grouped-query attention with selectable implementation.

- ``impl="xla"``: pure-jnp reference (softmax in f32, grouped einsum so GQA
  never materializes repeated KV heads).
- ``impl="pallas"``: Pallas TPU flash-attention kernel (gofr_tpu.ops.flash).
- ``impl="auto"``: pallas on TPU when shapes are tile-friendly, else XLA.

Layouts: q [B, Sq, Hq, D]; k, v [B, Skv, Hkv, D]; Hq % Hkv == 0.
``q_offset`` positions the query block absolutely (decode: cache length).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    if impl == "auto":
        # the flash kernel has no padding-mask support yet; masked calls
        # must stay on the XLA path rather than silently dropping the mask
        impl = "pallas" if (mask is None and _pallas_ok(q, k)) else "xla"
    if impl == "pallas":
        if mask is not None:
            raise NotImplementedError("pallas flash attention does not support mask=")
        from gofr_tpu.ops.flash import flash_attention

        return flash_attention(q, k, v, causal=causal, q_offset=q_offset, scale=scale)
    return _xla_attention(q, k, v, causal, q_offset, mask, scale)


def _pallas_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    if jax.default_backend() not in ("tpu",):
        return False
    b, sq, hq, d = q.shape
    skv = k.shape[1]
    # flash kernel wants lane-aligned head_dim and enough rows to tile
    return d % 128 == 0 and sq >= 8 and skv % 128 == 0


def _xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool,
    q_offset: int | jnp.ndarray,
    mask: Optional[jnp.ndarray],
    scale: Optional[float],
) -> jnp.ndarray:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    # [b, hkv, groups, sq, skv]; accumulate in f32 for softmax stability
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale

    if causal:
        k_pos = jnp.arange(skv)
        offset = jnp.asarray(q_offset)
        if offset.ndim == 0:
            q_pos = offset + jnp.arange(sq)  # [sq]
            causal_mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None]
        else:
            # per-batch offsets [b]: ragged decode positions
            q_pos = offset.reshape(-1, 1) + jnp.arange(sq)[None, :]  # [b, sq]
            causal_mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None, None]
        logits = jnp.where(causal_mask, logits, _NEG_INF)
    if mask is not None:
        # mask: [b, skv] key-validity (padding) or [b, sq, skv]
        if mask.ndim == 2:
            m = mask[:, None, None, None, :]
        else:
            m = mask[:, None, None, :, :]
        logits = jnp.where(m, logits, _NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
    return out.reshape(b, sq, hq, d)
