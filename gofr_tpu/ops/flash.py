"""Pallas TPU flash attention (forward kernel + recompute backward).

TPU-first design (pallas_guide: grid/block specs, scalar prefetch, online
softmax in VMEM):

- grid = (batch, q_heads, q_blocks); the KV loop runs *inside* the kernel
  as a ``lax.fori_loop`` with a **dynamic trip count** — causal blocks past
  the diagonal and blocks past the written KV length are never visited, so
  prefill does half the work and ragged decode touches only the live cache
  prefix.
- K/V for one (batch, kv-head) live whole in VMEM (max_seq 8192 × 128 in
  bf16 = 2 MiB each, well under the ~16 MiB budget); Q is tiled ``block_q``
  rows at a time. GQA maps query head → kv head in the BlockSpec index map,
  so repeated KV heads are never materialized.
- per-batch scalars (``q_offset`` for ragged decode positions, ``kv_lens``
  bounding the valid cache prefix) ride scalar prefetch
  (``PrefetchScalarGridSpec``) — available before the body for the
  dynamic loop bound.
- online softmax: running (m, l, acc) in f32; probabilities cast back to
  the value dtype so the p·V matmul hits the MXU in bf16 with f32
  accumulation.
- backward: **fused Pallas kernels** (FlashAttention-2 style). The forward
  additionally emits per-row logsumexp; ``_dq_kernel`` recomputes P from it
  and accumulates dQ over the same bounded KV loop as the forward, and
  ``_dkv_kernel`` accumulates dK/dV per KV block over the (causally
  bounded) query blocks, summing GQA groups by revisiting the output block
  on the innermost grid axis. The O(S²) score matrix never materializes in
  either direction. A checkpointed q-blockwise XLA recompute
  (``_blockwise_reference``) remains as the numeric oracle and the
  ``FUSED_BWD = False`` escape hatch.

Layouts match gofr_tpu.ops.attention: q [B, Sq, Hq, D]; k, v [B, Skv,
Hkv, D]; Hq % Hkv == 0. On non-TPU backends the kernel runs in pallas
interpret mode (tests exercise the real kernel logic on the CPU mesh, the
way the reference tests run against in-process fakes, SURVEY.md §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(-1e30)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_KV = 128


def _kernel(
    offs_ref,  # [B] int32 scalar-prefetch: absolute position of q row 0
    lens_ref,  # [B] int32 scalar-prefetch: valid KV prefix length
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, Skv_pad, D]
    v_ref,  # [1, 1, Skv_pad, D]
    out_ref,  # [1, 1, block_q, D]
    lse_ref,  # [1, 1, block_q, 1] f32: per-row logsumexp (backward
    # residual). The trailing singleton is a TPU tiling requirement: the
    # block's last two dims must be (divisible by 8, divisible by 128) or
    # equal the array dims — a [1, 1, block_q] block puts a size-1 head
    # axis second-to-last, which real-TPU lowering rejects (interpret
    # mode does not check; the r04 hardware sweep caught it)
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    num_kv_blocks: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)

    offset = offs_ref[b]
    kv_len = lens_ref[b]

    qb = q_ref[0, 0, :, :]  # [block_q, D]
    d = qb.shape[-1]

    # absolute positions of this query block's rows (2D iota: TPU rule)
    q_pos = (
        offset
        + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )  # [block_q, 1]
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (1, block_kv), 1)  # [1, block_kv]

    # dynamic trip count: stop at the KV length bound, and (causal) at the
    # block containing this q block's last row
    hi = pl.cdiv(kv_len, block_kv)
    if causal:
        last_q = offset + (qi + 1) * block_q  # exclusive
        hi = jnp.minimum(hi, pl.cdiv(last_q, block_kv))
    hi = jnp.minimum(hi, num_kv_blocks)

    def body(j, carry):
        m_prev, l_prev, acc_prev = carry
        kb = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]  # [block_kv, D]
        vb = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]

        s = jax.lax.dot_general(
            qb,
            kb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_kv]

        k_pos = j * block_kv + k_ids  # [1, block_kv]
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        p = jnp.exp(s - m_new)  # [block_q, block_kv] f32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype),
            vb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc_prev * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))

    # fully-masked rows (padding) have l == 0 → emit zeros, not NaN
    out = acc / jnp.where(l == 0.0, 1.0, l)
    out_ref[0, 0, :, :] = out.astype(out_ref.dtype)
    # logsumexp residual for the fused backward; +inf on fully-masked rows
    # makes their recomputed probabilities exp(-1e30 - inf) = 0 there
    lse = jnp.where(l > 0.0, m + jnp.log(l), jnp.inf)
    lse_ref[0, 0, :, :] = lse


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_kv", "interpret")
)
def _flash_fwd_impl(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    offsets: jnp.ndarray,
    kv_lens: jnp.ndarray,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    interpret: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv

    # [B, H, S, D] layout: the kernel tiles (sublane=seq, lane=head_dim)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    # sublane floor 16 covers the bf16 min tile (f32 needs only 8); sq=1
    # decode pads its q block rather than falling back to XLA
    block_q = min(block_q, max(sq, 16))
    block_kv = min(block_kv, skv)
    sq_pad = pl.cdiv(sq, block_q) * block_q
    skv_pad = pl.cdiv(skv, block_kv) * block_kv
    qt = _pad_axis(qt, 2, sq_pad)
    kt = _pad_axis(kt, 2, skv_pad)
    vt = _pad_axis(vt, 2, skv_pad)
    num_q_blocks = sq_pad // block_q
    num_kv_blocks = skv_pad // block_kv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, num_q_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g=groups: (bi, h // g, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g=groups: (bi, h // g, 0, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda bi, h, qi, *_: (bi, h, qi, 0)
            ),
        ],
    )

    kernel = functools.partial(
        _kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq_pad, 1), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hq * sq * skv * d,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * hq * sq * skv,
        ),
    )(offsets, kv_lens, qt, kt, vt)
    return jnp.swapaxes(out[:, :, :sq, :], 1, 2), lse[:, :, :sq, 0]


def _dq_kernel(
    offs_ref,  # [B] int32 scalar-prefetch
    lens_ref,  # [B] int32 scalar-prefetch
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, Skv_pad, D]
    v_ref,  # [1, 1, Skv_pad, D]
    do_ref,  # [1, 1, block_q, D]
    lse_ref,  # [1, 1, block_q, 1] f32 (trailing 1: TPU tiling, see _kernel)
    dvec_ref,  # [1, 1, block_q, 1] f32: D = rowsum(dO ⊙ O)
    dq_ref,  # [1, 1, block_q, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    num_kv_blocks: int,
):
    """dQ = scale · Σ_j dS_j K_j with dS = P ⊙ (dP − D), P recomputed from
    the forward's logsumexp — same KV loop bounds as the forward, so the
    O(S²) score matrix never materializes."""
    b = pl.program_id(0)
    qi = pl.program_id(2)
    offset = offs_ref[b]
    kv_len = lens_ref[b]

    qb = q_ref[0, 0, :, :]
    dob = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, :]  # [block_q, 1]
    dvec = dvec_ref[0, 0, :, :]  # [block_q, 1]

    q_pos = (
        offset + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (1, block_kv), 1)

    hi = pl.cdiv(kv_len, block_kv)
    if causal:
        hi = jnp.minimum(hi, pl.cdiv(offset + (qi + 1) * block_q, block_kv))
    hi = jnp.minimum(hi, num_kv_blocks)

    def body(j, acc):
        kb = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        vb = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = j * block_kv + k_ids
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_kv]; masked/padded → 0
        dp = jax.lax.dot_general(
            dob, vb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dvec)  # [block_q, block_kv]
        return acc + jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc0 = jnp.zeros((block_q, qb.shape[-1]), jnp.float32)
    acc = jax.lax.fori_loop(0, hi, body, acc0)
    dq_ref[0, 0, :, :] = acc * scale


def _dkv_kernel(
    offs_ref,  # [B] int32 scalar-prefetch
    lens_ref,  # [B] int32 scalar-prefetch
    q_ref,  # [1, 1, Sq_pad, D] — one query head's full (padded) sequence
    k_ref,  # [1, 1, block_kv, D]
    v_ref,  # [1, 1, block_kv, D]
    do_ref,  # [1, 1, Sq_pad, D]
    lse_ref,  # [1, 1, Sq_pad, 1] f32 (trailing 1: TPU tiling, see _kernel)
    dvec_ref,  # [1, 1, Sq_pad, 1] f32
    dk_ref,  # [1, 1, block_kv, D] f32 — revisited across the g grid axis
    dv_ref,  # [1, 1, block_kv, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    num_q_blocks: int,
):
    """dK/dV for one KV block, accumulated over the query blocks that can
    see it (dynamic causal lower bound) and, via grid revisiting, over the
    ``groups`` query heads sharing this KV head (GQA). The g axis is the
    innermost grid dimension, so the output block stays resident while the
    group accumulates."""
    b = pl.program_id(0)
    ki = pl.program_id(2)
    g = pl.program_id(3)
    offset = offs_ref[b]
    kv_len = lens_ref[b]

    kb = k_ref[0, 0, :, :]
    vb = v_ref[0, 0, :, :]
    d = kb.shape[-1]

    k_pos = ki * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_kv), 1
    )  # [1, block_kv]
    kv_mask = k_pos < kv_len

    # causal: only query blocks whose last row reaches this KV block's
    # first position contribute (same arithmetic as the forward's hi bound,
    # seen from the KV side)
    if causal:
        lo = jnp.maximum(0, (ki * block_kv - offset) // block_q)
    else:
        lo = 0

    def body(qi, carry):
        dk_acc, dv_acc = carry
        qb = q_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        dob = do_ref[0, 0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        dvec = dvec_ref[0, 0, pl.ds(qi * block_q, block_q), :]

        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_kv]
        q_pos = (
            offset + qi * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        )
        mask = kv_mask
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)
        # zero at masked positions (s = -inf). Zero-PADDED query rows have
        # lse = 0 and s = 0, so p = exp(0) = 1 there — those rows still
        # contribute nothing, but only because dO = 0 and D (dvec) = 0
        # make dv/ds vanish; preserve that invariant when editing.
        p = jnp.exp(s - lse)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, vb.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dvec)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    zeros = jnp.zeros((block_kv, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, num_q_blocks, body, (zeros, zeros))

    @pl.when(g == 0)
    def _init():
        dk_ref[0, 0, :, :] = dk * scale
        dv_ref[0, 0, :, :] = dv

    @pl.when(g > 0)
    def _accum():
        dk_ref[0, 0, :, :] += dk * scale
        dv_ref[0, 0, :, :] += dv


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_kv", "interpret")
)
def _flash_bwd_impl(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    offsets: jnp.ndarray,
    kv_lens: jnp.ndarray,
    out: jnp.ndarray,
    lse: jnp.ndarray,
    g: jnp.ndarray,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    interpret: bool,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    dot = jnp.swapaxes(g, 1, 2)

    block_q = min(block_q, max(sq, 16))
    block_kv = min(block_kv, skv)
    sq_pad = pl.cdiv(sq, block_q) * block_q
    skv_pad = pl.cdiv(skv, block_kv) * block_kv
    qt = _pad_axis(qt, 2, sq_pad)
    kt = _pad_axis(kt, 2, skv_pad)
    vt = _pad_axis(vt, 2, skv_pad)
    dot = _pad_axis(dot, 2, sq_pad)  # zero-padded rows contribute nothing
    num_q_blocks = sq_pad // block_q
    num_kv_blocks = skv_pad // block_kv

    # D = rowsum(dO ⊙ O): one cheap fused elementwise+reduce, shared by
    # both kernels (padded rows: dO = 0 → D = 0)
    dvec = jnp.sum(
        dot.astype(jnp.float32)
        * _pad_axis(jnp.swapaxes(out, 1, 2), 2, sq_pad).astype(jnp.float32),
        axis=-1,
    )[..., None]  # [B, Hq, Sq_pad, 1] — trailing 1: TPU tiling (see _kernel)
    lse_pad = _pad_axis(lse, 2, sq_pad)[..., None]

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g_=groups: (bi, h // g_, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g_=groups: (bi, h // g_, 0, 0),
            ),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, h, qi, *_: (bi, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, h, qi, *_: (bi, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)
        ),
    )
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, causal=causal, scale=scale, block_q=block_q,
            block_kv=block_kv, num_kv_blocks=num_kv_blocks,
        ),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_pad, d), jnp.float32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * hq * sq * skv * d,
            bytes_accessed=(q.size + k.size + v.size + g.size) * q.dtype.itemsize,
            transcendentals=b * hq * sq * skv,
        ),
    )(offsets, kv_lens, qt, kt, vt, dot, lse_pad, dvec)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        # g innermost: consecutive iterations revisit the same dk/dv block
        grid=(b, hkv, num_kv_blocks, groups),
        in_specs=[
            pl.BlockSpec(
                (1, 1, sq_pad, d),
                lambda bi, h, ki, gi, *_, g_=groups: (bi, h * g_ + gi, 0, 0),
            ),
            pl.BlockSpec((1, 1, block_kv, d), lambda bi, h, ki, gi, *_: (bi, h, ki, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda bi, h, ki, gi, *_: (bi, h, ki, 0)),
            pl.BlockSpec(
                (1, 1, sq_pad, d),
                lambda bi, h, ki, gi, *_, g_=groups: (bi, h * g_ + gi, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, sq_pad, 1),
                lambda bi, h, ki, gi, *_, g_=groups: (bi, h * g_ + gi, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, sq_pad, 1),
                lambda bi, h, ki, gi, *_, g_=groups: (bi, h * g_ + gi, 0, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d), lambda bi, h, ki, gi, *_: (bi, h, ki, 0)),
            pl.BlockSpec((1, 1, block_kv, d), lambda bi, h, ki, gi, *_: (bi, h, ki, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, causal=causal, scale=scale, block_q=block_q,
            block_kv=block_kv, num_q_blocks=num_q_blocks,
        ),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, skv_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, skv_pad, d), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=5 * b * hq * sq * skv * d,
            bytes_accessed=(q.size + k.size + v.size + g.size) * q.dtype.itemsize,
            transcendentals=b * hq * sq * skv,
        ),
    )(offsets, kv_lens, qt, kt, vt, dot, lse_pad, dvec)

    dq = jnp.swapaxes(dq[:, :, :sq, :], 1, 2).astype(q.dtype)
    dk = jnp.swapaxes(dk[:, :, :skv, :], 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv[:, :, :skv, :], 1, 2).astype(v.dtype)
    return dq, dk, dv


def _normalize_scalars(
    q: jnp.ndarray,
    k: jnp.ndarray,
    q_offset: int | jnp.ndarray,
    kv_lens: Optional[jnp.ndarray],
) -> tuple[jnp.ndarray, jnp.ndarray]:
    b, skv = q.shape[0], k.shape[1]
    offsets = jnp.asarray(q_offset, jnp.int32)
    if offsets.ndim == 0:
        offsets = jnp.full((b,), offsets, jnp.int32)
    if kv_lens is None:
        lens = jnp.full((b,), skv, jnp.int32)
    else:
        lens = jnp.minimum(jnp.asarray(kv_lens, jnp.int32), skv)
    return offsets, lens


def _reference(q, k, v, offsets, kv_lens, causal, scale):
    """XLA reference with identical semantics (backward recompute path)."""
    from gofr_tpu.ops.attention import attention

    return attention(
        q, k, v, causal=causal, q_offset=offsets, kv_lens=kv_lens, scale=scale,
        impl="xla",
    )


BWD_BLOCK_Q = 512  # q rows per checkpointed backward block


def _blockwise_reference(q, k, v, offsets, kv_lens, causal, scale,
                         block_q: Optional[int] = None):
    """Semantically identical to ``_reference`` but computed q-block by
    q-block under ``jax.checkpoint``: differentiating THIS never holds more
    than one block's [block_q, Skv] score matrix — O(block_q·S) backward
    memory instead of the O(S²) of a full-sequence recompute. Serves as
    the numeric oracle for the fused Pallas backward kernels and as the
    ``FUSED_BWD = False`` fallback. dk/dv accumulate through the scan's
    carry."""
    if block_q is None:
        block_q = BWD_BLOCK_Q  # module-level lookup: tests can patch it
    b, sq, hq, d = q.shape
    if sq <= block_q:
        return _reference(q, k, v, offsets, kv_lens, causal, scale)
    n_blocks = -(-sq // block_q)
    pad = n_blocks * block_q - sq
    q_padded = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    q_blocks = q_padded.reshape(b, n_blocks, block_q, hq, d).transpose(1, 0, 2, 3, 4)
    starts = jnp.arange(n_blocks, dtype=jnp.int32) * block_q

    @jax.checkpoint
    def block(qb, start):
        # q rows [start, start+block_q) attend the full KV under the same
        # causal/ragged semantics (offsets shift per block)
        return _reference(qb, k, v, offsets + start, kv_lens, causal, scale)

    def body(_, inputs):
        qb, start = inputs
        return None, block(qb, start)

    _, outs = jax.lax.scan(body, None, (q_blocks, starts))
    out = outs.transpose(1, 0, 2, 3, 4).reshape(b, n_blocks * block_q, hq, d)
    return out[:, :sq]


# Backward implementation switch: True (default) uses the fused Pallas
# kernels; False selects the checkpointed q-blockwise XLA recompute (the
# numeric oracle the fused kernels are tested against, and the escape
# hatch if a backend miscompiles the backward kernels). Read at TRACE
# time: set it before building jitted train steps — already-compiled
# functions keep the backward they were traced with until their jit
# caches are cleared (jax.clear_caches()).
FUSED_BWD = True


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret):
    return _flash_fwd_impl(
        q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret
    )[0]


def _flash_fwd(q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret):
    out, lse = _flash_fwd_impl(
        q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret
    )
    return out, (q, k, v, offsets, kv_lens, out, lse)


def _flash_bwd(causal, scale, block_q, block_kv, interpret, residuals, g):
    q, k, v, offsets, kv_lens, out, lse = residuals
    if FUSED_BWD:
        dq, dk, dv = _flash_bwd_impl(
            q, k, v, offsets, kv_lens, out, lse, g,
            causal, scale, block_q, block_kv, interpret,
        )
    else:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _blockwise_reference(
                q_, k_, v_, offsets, kv_lens, causal, scale
            ),
            q,
            k,
            v,
        )
        dq, dk, dv = vjp(g)
    return (
        dq,
        dk,
        dv,
        np.zeros(offsets.shape, jax.dtypes.float0),
        np.zeros(kv_lens.shape, jax.dtypes.float0),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    kv_lens: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_kv: int = DEFAULT_BLOCK_KV,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention. q [B, Sq, Hq, D]; k, v [B, Skv, Hkv, D].

    ``q_offset``: scalar or [B] absolute position of q row 0 (ragged
    decode). ``kv_lens``: optional [B] count of valid KV positions
    (padded/unwritten cache tail is masked). Differentiable via the fused
    backward kernels (gradients flow to q, k, v; not to the position
    scalars).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offsets, lens = _normalize_scalars(q, k, q_offset, kv_lens)
    return _flash(
        q, k, v, offsets, lens, causal, float(scale), block_q, block_kv, interpret
    )
