"""Pallas TPU flash attention. Placeholder dispatching to the XLA reference
until the kernel lands (task: pallas flash kernel); the public signature is
stable so callers never change."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    from gofr_tpu.ops.attention import _xla_attention

    return _xla_attention(q, k, v, causal, q_offset, None, scale)
