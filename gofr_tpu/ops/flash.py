"""Pallas TPU flash attention (forward kernel + recompute backward).

TPU-first design (pallas_guide: grid/block specs, scalar prefetch, online
softmax in VMEM):

- grid = (batch, q_heads, q_blocks); the KV loop runs *inside* the kernel
  as a ``lax.fori_loop`` with a **dynamic trip count** — causal blocks past
  the diagonal and blocks past the written KV length are never visited, so
  prefill does half the work and ragged decode touches only the live cache
  prefix.
- K/V for one (batch, kv-head) live whole in VMEM (max_seq 8192 × 128 in
  bf16 = 2 MiB each, well under the ~16 MiB budget); Q is tiled ``block_q``
  rows at a time. GQA maps query head → kv head in the BlockSpec index map,
  so repeated KV heads are never materialized.
- per-batch scalars (``q_offset`` for ragged decode positions, ``kv_lens``
  bounding the valid cache prefix) ride scalar prefetch
  (``PrefetchScalarGridSpec``) — available before the body for the
  dynamic loop bound.
- online softmax: running (m, l, acc) in f32; probabilities cast back to
  the value dtype so the p·V matmul hits the MXU in bf16 with f32
  accumulation.
- backward: ``jax.custom_vjp`` that recomputes attention **q-block by
  q-block under jax.checkpoint** and differentiates that — flash speed
  forward, correct gradients under ``jax.grad``, and backward memory
  bounded at O(block_q·S) per block instead of materializing the full
  O(S²) score matrix (a fused Pallas backward kernel can replace this
  without an API change).

Layouts match gofr_tpu.ops.attention: q [B, Sq, Hq, D]; k, v [B, Skv,
Hkv, D]; Hq % Hkv == 0. On non-TPU backends the kernel runs in pallas
interpret mode (tests exercise the real kernel logic on the CPU mesh, the
way the reference tests run against in-process fakes, SURVEY.md §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(-1e30)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_KV = 128


def _kernel(
    offs_ref,  # [B] int32 scalar-prefetch: absolute position of q row 0
    lens_ref,  # [B] int32 scalar-prefetch: valid KV prefix length
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, Skv_pad, D]
    v_ref,  # [1, 1, Skv_pad, D]
    out_ref,  # [1, 1, block_q, D]
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    num_kv_blocks: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)

    offset = offs_ref[b]
    kv_len = lens_ref[b]

    qb = q_ref[0, 0, :, :]  # [block_q, D]
    d = qb.shape[-1]

    # absolute positions of this query block's rows (2D iota: TPU rule)
    q_pos = (
        offset
        + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )  # [block_q, 1]
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (1, block_kv), 1)  # [1, block_kv]

    # dynamic trip count: stop at the KV length bound, and (causal) at the
    # block containing this q block's last row
    hi = pl.cdiv(kv_len, block_kv)
    if causal:
        last_q = offset + (qi + 1) * block_q  # exclusive
        hi = jnp.minimum(hi, pl.cdiv(last_q, block_kv))
    hi = jnp.minimum(hi, num_kv_blocks)

    def body(j, carry):
        m_prev, l_prev, acc_prev = carry
        kb = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]  # [block_kv, D]
        vb = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]

        s = jax.lax.dot_general(
            qb,
            kb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_kv]

        k_pos = j * block_kv + k_ids  # [1, block_kv]
        mask = k_pos < kv_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        p = jnp.exp(s - m_new)  # [block_q, block_kv] f32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype),
            vb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_new = acc_prev * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))

    # fully-masked rows (padding) have l == 0 → emit zeros, not NaN
    out = acc / jnp.where(l == 0.0, 1.0, l)
    out_ref[0, 0, :, :] = out.astype(out_ref.dtype)


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    pad = to - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_kv", "interpret")
)
def _flash_fwd_impl(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    offsets: jnp.ndarray,
    kv_lens: jnp.ndarray,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    interpret: bool,
) -> jnp.ndarray:
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv

    # [B, H, S, D] layout: the kernel tiles (sublane=seq, lane=head_dim)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    # sublane floor 16 covers the bf16 min tile (f32 needs only 8); sq=1
    # decode pads its q block rather than falling back to XLA
    block_q = min(block_q, max(sq, 16))
    block_kv = min(block_kv, skv)
    sq_pad = pl.cdiv(sq, block_q) * block_q
    skv_pad = pl.cdiv(skv, block_kv) * block_kv
    qt = _pad_axis(qt, 2, sq_pad)
    kt = _pad_axis(kt, 2, skv_pad)
    vt = _pad_axis(vt, 2, skv_pad)
    num_q_blocks = sq_pad // block_q
    num_kv_blocks = skv_pad // block_kv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, num_q_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g=groups: (bi, h // g, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, skv_pad, d),
                lambda bi, h, qi, *_, g=groups: (bi, h // g, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, h, qi, *_: (bi, h, qi, 0)
        ),
    )

    kernel = functools.partial(
        _kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_pad, d), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hq * sq * skv * d,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=b * hq * sq * skv,
        ),
    )(offsets, kv_lens, qt, kt, vt)
    return jnp.swapaxes(out[:, :, :sq, :], 1, 2)


def _normalize_scalars(
    q: jnp.ndarray,
    k: jnp.ndarray,
    q_offset: int | jnp.ndarray,
    kv_lens: Optional[jnp.ndarray],
) -> tuple[jnp.ndarray, jnp.ndarray]:
    b, skv = q.shape[0], k.shape[1]
    offsets = jnp.asarray(q_offset, jnp.int32)
    if offsets.ndim == 0:
        offsets = jnp.full((b,), offsets, jnp.int32)
    if kv_lens is None:
        lens = jnp.full((b,), skv, jnp.int32)
    else:
        lens = jnp.minimum(jnp.asarray(kv_lens, jnp.int32), skv)
    return offsets, lens


def _reference(q, k, v, offsets, kv_lens, causal, scale):
    """XLA reference with identical semantics (backward recompute path)."""
    from gofr_tpu.ops.attention import attention

    return attention(
        q, k, v, causal=causal, q_offset=offsets, kv_lens=kv_lens, scale=scale,
        impl="xla",
    )


BWD_BLOCK_Q = 512  # q rows per checkpointed backward block


def _blockwise_reference(q, k, v, offsets, kv_lens, causal, scale,
                         block_q: Optional[int] = None):
    """Semantically identical to ``_reference`` but computed q-block by
    q-block under ``jax.checkpoint``: differentiating THIS never holds more
    than one block's [block_q, Skv] score matrix — O(block_q·S) backward
    memory instead of the O(S²) that a full-sequence recompute
    materializes (exactly the regime ring attention exists for;
    round-2 verdict weak #7). dk/dv accumulate through the scan's carry.
    """
    if block_q is None:
        block_q = BWD_BLOCK_Q  # module-level lookup: tests can patch it
    b, sq, hq, d = q.shape
    if sq <= block_q:
        return _reference(q, k, v, offsets, kv_lens, causal, scale)
    n_blocks = -(-sq // block_q)
    pad = n_blocks * block_q - sq
    q_padded = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    q_blocks = q_padded.reshape(b, n_blocks, block_q, hq, d).transpose(1, 0, 2, 3, 4)
    starts = jnp.arange(n_blocks, dtype=jnp.int32) * block_q

    @jax.checkpoint
    def block(qb, start):
        # q rows [start, start+block_q) attend the full KV under the same
        # causal/ragged semantics (offsets shift per block)
        return _reference(qb, k, v, offsets + start, kv_lens, causal, scale)

    def body(_, inputs):
        qb, start = inputs
        return None, block(qb, start)

    _, outs = jax.lax.scan(body, None, (q_blocks, starts))
    out = outs.transpose(1, 0, 2, 3, 4).reshape(b, n_blocks * block_q, hq, d)
    return out[:, :sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret):
    return _flash_fwd_impl(
        q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret
    )


def _flash_fwd(q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret):
    out = _flash_fwd_impl(
        q, k, v, offsets, kv_lens, causal, scale, block_q, block_kv, interpret
    )
    return out, (q, k, v, offsets, kv_lens)


def _flash_bwd(causal, scale, block_q, block_kv, interpret, residuals, g):
    q, k, v, offsets, kv_lens = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_reference(
            q_, k_, v_, offsets, kv_lens, causal, scale
        ),
        q,
        k,
        v,
    )
    dq, dk, dv = vjp(g)
    return (
        dq,
        dk,
        dv,
        np.zeros(offsets.shape, jax.dtypes.float0),
        np.zeros(kv_lens.shape, jax.dtypes.float0),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int | jnp.ndarray = 0,
    kv_lens: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_kv: int = DEFAULT_BLOCK_KV,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention. q [B, Sq, Hq, D]; k, v [B, Skv, Hkv, D].

    ``q_offset``: scalar or [B] absolute position of q row 0 (ragged
    decode). ``kv_lens``: optional [B] count of valid KV positions
    (padded/unwritten cache tail is masked). Differentiable via recompute.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offsets, lens = _normalize_scalars(q, k, q_offset, kv_lens)
    return _flash(
        q, k, v, offsets, lens, causal, float(scale), block_q, block_kv, interpret
    )
