"""12-factor configuration: ``./configs/.env`` file loaded into the process
environment, reads always backed by live env vars.

Parity: /root/reference/pkg/gofr/config/config.go:3-6 (the two-method Config
interface) and config/godotenv.go:9-33 (.env load then ``os.Getenv``).
Semantics preserved: the .env file never overrides variables already present
in the environment, and lookups hit the live environment so tests can inject
values with ``monkeypatch.setenv``.

TPU-native keys added on top of the reference set (SURVEY.md §2 #22):
``TPU_ENABLED``, ``TPU_MESH`` (serving mesh, e.g. "tp=4,dp=4"),
``MODEL_NAME``, ``MODEL_PATH``, ``MODEL_QUANT``, ``BATCH_MAX_SIZE``,
``BATCH_TIMEOUT_MS``. (An early ``METRICS_ENABLED`` toggle was never
wired — metrics are always on; the knob was dropped rather than left
inert. gofrlint GFL008 now guards this class of drift.)

Paged-KV keys (tpu/kv_blocks.py, see docs/advanced-guide/performance):
``KV_PAGED`` (default on) switches KV storage/admission to
block-granular paged mode; ``KV_BLOCK_TOKENS`` (default 64) is the
block size; ``KV_BLOCKS`` / ``KV_HBM_BUDGET_MB`` size the shared
block budget (0 = auto, non-binding).

Serving-mesh key (tpu/device.py + parallel/): ``TPU_MESH`` (e.g.
"tp=2" or "tp=4,dp=4") shards serving executables over a named mesh.
Paged KV, chunked prefill, the prefix cache, and the penalized pool
compose with tp-only meshes (the paged block arena shards its kv-head
axis over tp); dp/fsdp meshes degrade paged KV/chunked prefill and any
mesh degrades pooled multi-LoRA — each degrade is logged and counted
on ``gofr_tpu_mesh_degrade_total{feature}``. ``KV_BLOCK_TOKENS`` must
be divisible by tp for the echo runner's host-mesh arena, and the
model's ``n_kv_heads`` by tp for device arenas — violations fail the
boot with the axis named.

Observability keys (timebase + postmortem layer, see
docs/advanced-guide/observability.md for semantics):
``TIMEBASE_INTERVAL_S`` (default 5) / ``TIMEBASE_WINDOW_S`` (default
900) / ``TIMEBASE_ENABLED`` size and arm the metric-snapshot ring;
``POSTMORTEM_DIR`` (default ./postmortems — setting it EXPLICITLY also
arms the crash/fatal-signal hooks), ``POSTMORTEM_KEEP``,
``POSTMORTEM_MIN_INTERVAL_S``, ``POSTMORTEM_SNAPSHOTS`` govern the
black-box bundles; ``METRICS_MAX_SERIES`` (default 1000) caps
per-metric label cardinality; ``METRICS_EXEMPLARS=off`` disables
OpenMetrics histogram exemplars.

Fleet-router keys (gofr_tpu/fleet, see docs/advanced-guide/fleet.md):
``FLEET_REPLICAS`` (comma list of replica base URLs, optionally
``name=url``) turns a process into the fleet front door via
``tools/router.py``; routing: ``FLEET_RETRIES`` (2),
``FLEET_DEADLINE_S`` (30), ``FLEET_CONNECT_TIMEOUT_S`` (2),
``FLEET_READ_TIMEOUT_S`` (30), ``FLEET_AFFINITY`` (on),
``FLEET_AFFINITY_MAX_SKEW`` (4), ``FLEET_ROUTES``; health:
``FLEET_PROBE_INTERVAL_S`` (1),
``FLEET_PROBE_TIMEOUT_S`` (1), ``FLEET_PROBE_HEDGE_MS`` (0 = off),
``FLEET_OUT_AFTER`` (2), ``FLEET_PROBATION_PROBES`` (3); breaker:
``FLEET_BREAKER_THRESHOLD`` (5), ``FLEET_BREAKER_COOLDOWN_S`` (5);
admission: ``FLEET_QUOTA_RPS`` (0 = off), ``FLEET_QUOTA_BURST``,
``FLEET_TRUST_TENANT_HEADER`` (off — only behind a gateway that stamps
``X-Tenant``), ``FLEET_MAX_INFLIGHT`` (256),
``FLEET_SATURATION_QUEUE`` (64), ``FLEET_RETRY_AFTER_S`` (1); drain:
``FLEET_DRAIN_TIMEOUT_S`` (10); resumable streams: ``FLEET_RESUME``
(on — mid-stream failover for deterministic SSE), ``FLEET_MAX_RESUMES``
(4 continuation attempts per stream); HA: ``FLEET_ROUTER_ID`` (defaults
to a per-process id) labels one of N side-by-side router instances —
the router tier has no single point of failure: quota is redis-backed
(shared), affinity/KV-locality is stateless rendezvous hashing, and
the in-flight cap, route records, breaker and prober verdicts are
explicitly PER-INSTANCE (N routers = N x ``FLEET_MAX_INFLIGHT``);
tracing: ``FLEET_TRACE_SCRAPE_TIMEOUT_S`` (1 — per-replica evidence
scrape budget for ``GET /admin/fleet/trace/<id>``; replicas that miss
it show as ``evidence_gaps`` on a partial trace).

Self-healing keys (tpu/recovery.py + telemetry.py, see
docs/advanced-guide/fleet.md "Wedge-recovery runbook"):
``RECOVERY_ENABLED`` (on — a wedged engine quarantines the stuck
dispatch and rebuilds back to serving; off restores terminal wedged),
``RECOVERY_MAX_ATTEMPTS`` (3), ``RECOVERY_BACKOFF_S`` (1, doubling) /
``RECOVERY_BACKOFF_MAX_S`` (30), ``RECOVERY_ATTEMPT_TIMEOUT_S`` (300 —
a rebuild hanging past it is terminal ``failed``); ``JOURNAL`` (on —
durable generation journal: prompt hash + sampling params + emitted
token ids per request, the substrate of bit-identical stream resume),
``JOURNAL_CAPACITY`` (256 interrupted entries retained),
``JOURNAL_MAX_TOKENS`` (8192 tokens recorded per entry).

Crash-durability keys (journal_wal.py + tools/supervisor.py, see
docs/advanced-guide/fleet.md "Process-death recovery"):
``JOURNAL_DIR`` (unset = in-memory journal only) arms the disk-backed
segmented WAL behind the generation journal — a SIGKILLed replica
rehydrates its resumable entries at next boot and serves
``X-Resume-From`` for its own pre-crash streams bit-identically;
``JOURNAL_FSYNC`` (``interrupt`` — flush every record to the OS, which
survives process death, and fsync on interruption/rotation/close;
``always`` fsyncs per record for the power-loss threat model at a
measured per-token cost — see the bench's journal_wal_microbench;
``off`` never fsyncs); ``JOURNAL_SEGMENT_BYTES`` (1 MiB) rotates
segments — live entries carry across via rotation checkpoints — and
``JOURNAL_SEGMENTS`` (4) bounds retention. Recovery refuses torn and
corrupt tail records (CRC-framed, kvwire discipline) rather than
installing them. Run the replica under ``tools/supervisor.py`` (or an
equivalent init) so a crashed process respawns; the fleet prober
detects the reborn process by its changed ready ``boot_id`` and walks
it back through probation as ``restarting`` (visible on
``/admin/fleet`` and ``gofr_tpu_router_replica_restarts_total``).

Deadline-aware-serving keys (gofr_tpu/deadline.py, see
docs/advanced-guide/fleet.md "Deadlines & brownout"):
``REQUEST_DEADLINE_S`` (0 = off — the default end-to-end budget for
requests without an ``X-Request-Deadline-Ms`` header; the header
always wins, and a header of 0 opts a single request out), every
serving stage honors it: the batcher sheds expired items at dequeue
(stage ``queue``), pool/paged-KV admission rejects budgets that
cannot cover one decode chunk at the observed cadence (stage
``admission``), and the decode loop expires rows per chunk (stage
``decode``) — all 504-mapped and counted on
``gofr_tpu_deadline_exceeded_total{stage}``. ``PRIORITY_DEFAULT``
(5) is the tier requests without an ``X-Priority`` header (0
sheddable .. 9 protected, router-forwarded) serve at. Brownout:
``BROWNOUT_QUEUE_DEPTH`` (0 = off; queue depth arming level 1 at the
threshold, level 2 at 2x) and ``BROWNOUT_KV_UTIL`` (0 = off; a 0..1
KV-ledger-utilization fraction, hard level at the midpoint to full)
arm the graded controller; at level >= 1 priorities below
``BROWNOUT_SHED_PRIORITY`` (5) 429 with Retry-After, at level 2
priorities at-or-below it shed and ``BROWNOUT_CLAMP_TOKENS`` (0 =
off) clamps ``max_tokens``. The live level serves on
``/admin/engine`` and ``gofr_tpu_brownout_level``.

Disaggregated prefill/decode keys (fleet/kvwire.py + tpu/device.py,
see docs/advanced-guide/fleet.md "Disaggregated prefill/decode"):
``FLEET_ROLE`` (``mixed`` — what a replica advertises on
``/admin/engine``: ``prefill`` replicas take prefill-heavy work and
act as KV donors, ``decode`` replicas take token generation, ``mixed``
takes anything) and ``FLEET_ROLE_ROUTING`` (on, router-side — off
ignores advertised roles and stamps no donor hints) steer the tiers;
an empty or breaker-vetoed tier always degrades to mixed routing, so
role config can never shrink what the fleet serves.
``KV_TRANSFER`` (on — a replica serves its cached paged-KV block
tables on ``GET /admin/kv/<prompt_hash>`` and pulls a router-stamped
``X-KV-Donor``'s warm prefix before admission; off disarms both
directions), ``KV_TRANSFER_TIMEOUT_S`` (2 — one pull's overall budget,
also the export side's default deadline; a pull additionally never
spends more than half the request's remaining deadline),
``KV_TRANSFER_PIN_TTL_S`` (60 — the bounded lifetime of the block pins
an export holds, released by a named timer even if the serving thread
dies mid-send), ``KV_TRANSFER_TRUST_HINT`` (off — ``X-KV-Donor`` names
a URL the replica will FETCH into its shared prefix cache, so the
header is an SSRF/cache-poisoning primitive if client-minted; set
``on`` ONLY on replicas whose front door is the fleet router, exactly
the ``FLEET_TRUST_TENANT_HEADER`` contract). ``/admin/kv`` is on the
``ADMIN_TOKEN``-gated admin plane; a pull forwards the replica's own
token, so a tokened fleet (one shared token) keeps transferring.
Every pull outcome counts
on
``gofr_tpu_kv_transfer_total{outcome}``; any failure falls back to
local chunked prefill — a transfer can make a request faster, never
break it.

Fleet-scale hardening keys (fleet/replica.py + fleet/admission.py,
see docs/advanced-guide/fleet.md "Fleet simulation"):
``FLEET_PROBE_JITTER`` (0.2 — decorrelated per-replica probe jitter
as a fraction of ``FLEET_PROBE_INTERVAL_S``; 0 restores the
synchronized sweep, which at N=16 fires every probe of a round in one
burst window) and ``FLEET_QUOTA_CACHE_TTL_S`` (0.05 — short-TTL local
token-lease cache over the redis quota bucket; 0 = one redis sync
(two pipelined round trips) per request per tenant, the Zipf hot-key
tax the fleetsim measures).

Pooled-speculative-decoding keys (tpu/spec_pool.py + tpu/decode_pool.py,
see docs/advanced-guide/performance "Speculative decoding"):
``SPEC_POOLED`` (off — ``on`` routes speculation THROUGH the
continuous-batching pool: each greedy pooled request drafts k tokens
per cycle and one batched ``[slots, width]`` verify dispatch commits
the accepted prefixes, rejected tokens rolling back by length /
paged-KV refcount; the solo ``DRAFT_MODEL_NAME`` latency mode stands
down for pool-eligible requests), ``SPEC_NGRAM`` (on — zero-weight
n-gram/prompt-lookup drafting from the request's own prompt+emitted
context, no draft checkpoint), ``SPEC_K_MAX`` (4 — draft-width bound;
the per-request adaptive-k EMA degrades toward 0 = plain decode on
poor acceptance and is clamped under brownout level >= 1 and by the
remaining deadline budget), ``SPEC_FAKE_ACCEPT`` (echo runner only: a
cyclic schedule of per-cycle accept counts, e.g. "3,1,0", making
every accept/reject/rollback branch deterministic in tier-1).

Dispatch-cost-model keys (tpu/costmodel.py, see
docs/advanced-guide/observability.md "Cost model & anomalies"):
``COSTMODEL`` (on — per-dispatch roofline prediction + residual
accounting + the anomaly surface; off removes the whole layer),
``COSTMODEL_PROFILE`` (path to a cost-profile JSON; default the
committed ``gofr_tpu/tpu/cost_profile.json`` — ``tools/costcal.py``
owns the fit), ``COSTMODEL_HLO`` (``auto`` — harvest
``cost_analysis()`` sheets by recompiling prefill buckets at warmup on
TPU only; ``on`` forces it, ``off`` skips it — tier-1/CPU never pays
the recompiles), ``COSTMODEL_ANOMALY_FACTOR`` (4 — observed past this
multiple of predicted flags ``slow_dispatch``),
``COSTMODEL_MIN_ANOMALY_MS`` (50 — absolute excess floor both anomaly
causes must ALSO clear; the no-false-positive guarantee for
microsecond dispatches), ``COSTMODEL_EMA_ALPHA`` (0.2) /
``COSTMODEL_EMA_BAND`` (2.5) govern the per-family residual EMA and
its ``ema_drift`` verdict (latched per excursion), and
``ANOMALY_RING_SIZE`` (256) bounds the typed-event ring behind
``GET /admin/anomalies``.

SLO & tenant-metering keys (slo.py + telemetry.py TenantLedger, see
docs/advanced-guide/observability.md "SLOs, budgets & tenants"):
``SLO_TARGETS`` (default
``availability=0.999;shed_rate=0.05;tier=9:availability=0.9995``) —
semicolon-separated ``[scope:]metric=target`` objectives; metrics:
``availability`` (good fraction), ``shed_rate`` (allowed shed
fraction, global-only), ``ttft_p95_ms`` / ``ttft_p99_ms`` /
``tpot_p95_ms`` / ``tpot_p99_ms`` (millisecond percentile bounds);
scopes ``model=<name>:``, ``tier=<n>:``, ``tier>=<n>:``. Burn-rate
alerting is multi-window: the fast page fires past
``SLO_BURN_FAST_RATE`` (14.4) on BOTH ``SLO_BURN_FAST_S`` (300) and
``SLO_BURN_FAST_LONG_S`` (3600); the slow ticket past
``SLO_BURN_SLOW_RATE`` (6) on both ``SLO_BURN_SLOW_S`` (21600) and
``SLO_BURN_SLOW_LONG_S`` (259200, also the budget-ledger window);
``SLO_EVAL_INTERVAL_S`` (15) paces the evaluator thread and ``SLO``
(on) removes the layer entirely. Windows clip silently to what the
flight-record ring and ``TIMEBASE_WINDOW_S`` retain. Verdicts land in
the anomaly ring (``slo_fast_burn``/``slo_slow_burn``), on
``gofr_tpu_slo_burn_rate{objective,window}`` /
``gofr_tpu_slo_budget_remaining{objective}`` /
``gofr_tpu_slo_burn_alerts_total``, and on ``GET /admin/slo/budget``.
``TENANT_LEDGER_SIZE`` (256) bounds the space-saving top-K sketch
behind ``GET /admin/tenants`` — per-tenant usage (requests, tokens,
sheds, deadline misses) is EXACT for the top-K heavy hitters and
aggregated into ``~other`` beyond, so 5k distinct API keys add zero
Prometheus series (only ``gofr_tpu_tenants_tracked_entries`` and
``gofr_tpu_tenant_overflow_total`` exist).

Correctness-tooling keys (devtools/sanitizer.py + tests/conftest.py,
see docs/advanced-guide/static-analysis.md): ``GOFR_SANITIZE=1`` arms
the runtime concurrency sanitizer under tests;
``GOFR_SANITIZE_HOLD_MS`` (default 150) is the lock hold-time warning
threshold; ``GOFR_SANITIZE_ALL=1`` widens lock-order tracking beyond
project-created locks; ``GOFR_SANITIZE_REPORT`` names the findings
file.

Module-level accessors :func:`get_env`, :func:`env_flag`, and
:func:`environ_snapshot` are the ONLY sanctioned raw environment reads
in package code (gofrlint rule GFL001).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol

# The config-surface provenance registry (gofrlint GFL008): every env
# key package code reads must have a row here, and every row must be
# read somewhere in the tree (package, tools, bench or tests) — an
# unreadable row is an inert knob and fails lint. Harness-only knobs
# (BENCH_*, FLEETSIM_GATE_*, WATCH_*) belong to their scripts, not to
# the package surface, and are deliberately NOT declared. The prose
# sections of the module docstring above stay the operator-facing
# documentation; this dict is the machine-checked index of it.
DECLARED_KEYS: dict[str, str] = {
    # core serving / reference-parity surface
    "APP_NAME": "service name stamped on traces",
    "LOG_LEVEL": "root logger level",
    "HTTP_PORT": "HTTP listen port",
    "GRPC_PORT": "gRPC listen port",
    "HANDLER_THREADS": "HTTP handler thread-pool size",
    "ADMIN_TOKEN": "bearer token gating the /admin plane",
    # datasources (reference parity: sql + redis)
    "DB_DIALECT": "sql dialect (mysql/postgres/sqlite)",
    "DB_HOST": "sql host (presence arms the datasource)",
    "DB_PORT": "sql port",
    "DB_NAME": "sql database name",
    "DB_USER": "sql user",
    "DB_PASSWORD": "sql password",
    "REDIS_HOST": "redis host (presence arms the client)",
    "REDIS_PORT": "redis port",
    # TPU / model boot
    "TPU_ENABLED": "arm the TPU serving engine",
    "TPU_BOOT": "boot-mode override (echo/real)",
    "TPU_MESH": "serving mesh spec, e.g. tp=4,dp=4",
    "TPU_TOPOLOGY": "expected device topology assertion",
    "TPU_COORDINATOR": "multihost coordinator address",
    "TPU_NUM_PROCESSES": "multihost process count",
    "TPU_PROCESS_ID": "this host's multihost process id",
    "MODEL_NAME": "served model name",
    "MODEL_PATH": "checkpoint path",
    "MODEL_QUANT": "weight quantization mode",
    "MODEL_BUCKETS": "prefill padding bucket list",
    "MODEL_MAX_SEQ": "max sequence length",
    "MODEL_ATTN_IMPL": "attention implementation override",
    "MODEL_KV_DTYPE": "KV-cache dtype (e.g. f8)",
    "TOKENIZER": "tokenizer implementation override",
    "TOKENIZER_PATH": "tokenizer asset path",
    "GEN_STOP_EOS": "stop generation on EOS token",
    "GEN_STOP_TOKENS": "extra stop-token ids",
    "ECHO_STEP_MS": "echo runner per-step latency",
    "LORA_ADAPTERS": "pooled multi-LoRA adapter table",
    # batching / scheduling / decode pool
    "BATCH_MAX_SIZE": "max continuous-batch size",
    "BATCH_TIMEOUT_MS": "batch formation window",
    "BATCH_COHORT": "cohort grouping policy",
    "SCHED_POLICY": "scheduler policy (fcfs/interference)",
    "SCHED_MAX_DEFER_MS": "interference-scheduler defer bound",
    "PREFILL_CHUNK_TOKENS": "chunked-prefill chunk size",
    "DECODE_CHUNK": "decode loop chunk size",
    "DECODE_SLOTS": "decode pool slot count",
    "DECODE_POOL": "enable the continuous-batching pool",
    "DECODE_PIPELINE": "overlap host/device decode stages",
    "DECODE_POOL_PENALTIES": "penalized-pool admission weights",
    "PREFIX_CACHE": "shared prefix cache toggle",
    "PREFIX_LCP_MIN": "min longest-common-prefix to reuse",
    # paged KV + cross-replica transfer
    "KV_PAGED": "block-granular paged KV mode",
    "KV_BLOCK_TOKENS": "tokens per KV block",
    "KV_BLOCKS": "fixed shared block budget (0 = auto)",
    "KV_HBM_BUDGET_MB": "HBM budget for the block arena",
    "KV_TRANSFER": "serve/pull warm KV across replicas",
    "KV_TRANSFER_TIMEOUT_S": "one pull's overall budget",
    "KV_TRANSFER_PIN_TTL_S": "bounded export block-pin lifetime",
    "KV_TRANSFER_TRUST_HINT": "trust client X-KV-Donor (SSRF gate)",
    # speculative decoding
    "SPEC_POOLED": "route speculation through the pool",
    "SPEC_NGRAM": "n-gram/prompt-lookup drafting",
    "SPEC_K_MAX": "draft-width bound",
    "SPEC_FAKE_ACCEPT": "echo-runner deterministic accepts",
    "DRAFT_MODEL_NAME": "solo-mode draft model name",
    "DRAFT_MODEL_PATH": "solo-mode draft checkpoint",
    "DRAFT_TOKENS": "solo-mode draft depth",
    # deadlines / brownout
    "REQUEST_DEADLINE_S": "default end-to-end request budget",
    "PRIORITY_DEFAULT": "tier for requests without X-Priority",
    "BROWNOUT_QUEUE_DEPTH": "queue depth arming brownout",
    "BROWNOUT_KV_UTIL": "KV utilization arming brownout",
    "BROWNOUT_SHED_PRIORITY": "priority floor shed under brownout",
    "BROWNOUT_CLAMP_TOKENS": "max_tokens clamp at level 2",
    # observability: metrics / timebase / postmortem / profiling
    "METRICS_MAX_SERIES": "per-metric label-cardinality cap",
    "METRICS_EXEMPLARS": "OpenMetrics histogram exemplars",
    "TIMEBASE_ENABLED": "metric-snapshot ring toggle",
    "TIMEBASE_INTERVAL_S": "snapshot cadence",
    "TIMEBASE_WINDOW_S": "snapshot retention window",
    "POSTMORTEM_DIR": "black-box bundle dir (arms crash hooks)",
    "POSTMORTEM_KEEP": "bundles retained",
    "POSTMORTEM_MIN_INTERVAL_S": "bundle rate limit",
    "POSTMORTEM_SNAPSHOTS": "timebase snapshots per bundle",
    "FLIGHT_RECORDER_SIZE": "flight-record ring capacity",
    "FLIGHT_RECORDER_KEEP": "completed records retained",
    "FLIGHT_SLOW_MS": "slow-request capture threshold",
    "PROFILE_DIR": "jax profiler trace output dir",
    "DISPATCH_TIMELINE_SIZE": "dispatch timeline ring capacity",
    # tracing
    "TRACER_HOST": "zipkin exporter host",
    "TRACER_PORT": "zipkin exporter port",
    "FLEET_TRACE_SCRAPE_TIMEOUT_S": "per-replica trace-evidence budget",
    # dispatch cost model
    "COSTMODEL": "roofline prediction + anomaly surface",
    "COSTMODEL_PROFILE": "cost-profile JSON path",
    "COSTMODEL_HLO": "HLO cost-sheet harvest mode",
    "COSTMODEL_ANOMALY_FACTOR": "slow-dispatch multiple",
    "COSTMODEL_MIN_ANOMALY_MS": "absolute anomaly excess floor",
    "COSTMODEL_EMA_ALPHA": "residual EMA smoothing",
    "COSTMODEL_EMA_BAND": "residual EMA drift band",
    "ANOMALY_RING_SIZE": "typed anomaly-event ring capacity",
    # SLO engine + tenant metering
    "SLO": "SLO evaluation layer toggle",
    "SLO_TARGETS": "objective spec (scope:metric=target;...)",
    "SLO_BURN_FAST_S": "fast-burn short window",
    "SLO_BURN_FAST_LONG_S": "fast-burn long window",
    "SLO_BURN_FAST_RATE": "fast-burn page threshold",
    "SLO_BURN_SLOW_S": "slow-burn short window",
    "SLO_BURN_SLOW_LONG_S": "slow-burn long window / budget ledger",
    "SLO_BURN_SLOW_RATE": "slow-burn ticket threshold",
    "SLO_EVAL_INTERVAL_S": "evaluator thread cadence",
    "TENANT_LEDGER_SIZE": "top-K tenant sketch capacity",
    # self-healing / journal / WAL
    "RECOVERY_ENABLED": "wedge-recovery state machine",
    "RECOVERY_MAX_ATTEMPTS": "rebuild attempts before failed",
    "RECOVERY_BACKOFF_S": "first rebuild backoff",
    "RECOVERY_BACKOFF_MAX_S": "backoff ceiling",
    "RECOVERY_ATTEMPT_TIMEOUT_S": "hung-rebuild terminal timeout",
    "WATCHDOG_DISPATCH_TIMEOUT_S": "dispatch watchdog threshold",
    "JOURNAL": "durable generation journal",
    "JOURNAL_CAPACITY": "interrupted entries retained",
    "JOURNAL_MAX_TOKENS": "tokens recorded per entry",
    "JOURNAL_DIR": "disk-backed WAL dir (unset = memory)",
    "JOURNAL_FSYNC": "WAL durability mode",
    "JOURNAL_SEGMENT_BYTES": "WAL segment rotation size",
    "JOURNAL_SEGMENTS": "WAL segments retained",
    # fleet router / replicas
    "FLEET_REPLICAS": "replica URL list (arms the router)",
    "FLEET_ROUTES": "extra route table entries",
    "FLEET_ROUTER_ID": "HA router instance label",
    "FLEET_RETRIES": "per-request retry budget",
    "FLEET_DEADLINE_S": "router end-to-end deadline",
    "FLEET_CONNECT_TIMEOUT_S": "upstream connect timeout",
    "FLEET_READ_TIMEOUT_S": "upstream read timeout",
    "FLEET_AFFINITY": "prefix-affinity routing",
    "FLEET_AFFINITY_MAX_SKEW": "affinity load-skew bound",
    "FLEET_PROBE_INTERVAL_S": "health probe cadence",
    "FLEET_PROBE_TIMEOUT_S": "health probe timeout",
    "FLEET_PROBE_HEDGE_MS": "hedged second probe delay",
    "FLEET_PROBE_JITTER": "decorrelated probe jitter fraction",
    "FLEET_OUT_AFTER": "failed probes before out",
    "FLEET_PROBATION_PROBES": "probes to re-admit a replica",
    "FLEET_BREAKER_THRESHOLD": "breaker error threshold",
    "FLEET_BREAKER_COOLDOWN_S": "breaker half-open cooldown",
    "FLEET_QUOTA_RPS": "per-tenant quota (redis-backed)",
    "FLEET_QUOTA_BURST": "quota bucket burst",
    "FLEET_QUOTA_CACHE_TTL_S": "local token-lease cache TTL",
    "FLEET_TRUST_TENANT_HEADER": "trust client X-Tenant",
    "FLEET_MAX_INFLIGHT": "per-instance in-flight cap",
    "FLEET_SATURATION_QUEUE": "admission queue depth",
    "FLEET_RETRY_AFTER_S": "Retry-After on shed",
    "FLEET_DRAIN_TIMEOUT_S": "graceful drain budget",
    "FLEET_RESUME": "mid-stream failover for SSE",
    "FLEET_MAX_RESUMES": "continuation attempts per stream",
    "FLEET_ROLE": "advertised replica role",
    "FLEET_ROLE_ROUTING": "router honors advertised roles",
    # openai-compat layer
    "OPENAI_ACCEPT_UNKNOWN_MODEL": "serve unknown model names",
    "OPENAI_FANOUT_WORKERS": "n>1 sampling fanout pool size",
    "CHAT_TEMPLATE": "chat template style",
    "CHAT_TEMPLATE_JINJA": "jinja template path override",
    "CHAT_TEMPLATE_OPENER": "assistant-turn opener override",
    # native extension loader
    "GOFR_NATIVE_LIB": "prebuilt native library path",
    "GOFR_NATIVE_CACHE": "native build cache dir",
    "GOFR_NATIVE_DISABLE": "force the pure-python fallback",
    # correctness tooling (devtools/sanitizer.py + tests/conftest.py)
    "GOFR_POOL_DEBUG": "decode-pool debug logging",
    "GOFR_SANITIZE": "runtime concurrency sanitizer",
    "GOFR_SANITIZE_ALL": "track non-project locks too",
    "GOFR_SANITIZE_HOLD_MS": "lock hold-time warning threshold",
    "GOFR_SANITIZE_REPORT": "sanitizer findings file",
    "GOFR_SANITIZE_GRAPH": "observed lock-order graph JSON file",
}


class Config(Protocol):
    """Two-method config surface every component depends on."""

    def get(self, key: str) -> Optional[str]: ...

    def get_or_default(self, key: str, default: str) -> str: ...


def parse_env_file(path: str) -> dict[str, str]:
    """Parse a dotenv file: KEY=VALUE lines, ``#`` comments, optional
    single/double quotes, ``export`` prefix tolerated."""
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return out
    for raw in lines:
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("export "):
            line = line[len("export "):].lstrip()
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip()
        value = value.strip()
        if not key:
            continue
        if value[:1] in ("'", '"'):
            quote = value[0]
            closing = value.find(quote, 1)
            if closing != -1:
                value = value[1:closing]  # anything after the close quote is comment/junk
            else:
                value = value[1:]
        elif " #" in value:
            # strip trailing inline comment on unquoted values
            value = value.split(" #", 1)[0].rstrip()
        out[key] = value
    return out


def get_env(key: str, default: Optional[str] = None) -> Optional[str]:
    """THE sanctioned raw environment read (gofrlint GFL001): package
    code routes every env lookup through here (or a Config instance) so
    the config surface stays auditable in one module. Entry-point
    scripts may read the environment directly."""
    return os.environ.get(key, default)


def env_flag(key: str) -> bool:
    """True when ``key`` is set to ``1`` — the framework's debug-toggle
    idiom (``GOFR_POOL_DEBUG``, ``GOFR_SANITIZE``, ...)."""
    return os.environ.get(key, "") == "1"


def environ_snapshot() -> dict[str, str]:
    """A point-in-time copy of the whole environment — for consumers
    that must iterate it (postmortem config fingerprints, test
    save/restore scaffolding) without scattering raw reads."""
    return dict(os.environ)


class EnvConfig:
    """Config backed directly by the process environment."""

    def get(self, key: str) -> Optional[str]:
        return os.environ.get(key)

    def get_or_default(self, key: str, default: str) -> str:
        value = os.environ.get(key)
        return value if value not in (None, "") else default


class EnvFileConfig(EnvConfig):
    """Loads ``<configs_dir>/.env`` into the environment (non-overriding),
    then behaves like :class:`EnvConfig`.

    Parity: config/godotenv.go:18-33 — missing file is not an error; the app
    simply runs on ambient environment variables.
    """

    def __init__(self, configs_dir: str = "./configs") -> None:
        self.configs_dir = configs_dir
        env_path = os.path.join(configs_dir, ".env")
        for key, value in parse_env_file(env_path).items():
            os.environ.setdefault(key, value)


def new_env_file(configs_dir: str = "./configs") -> EnvFileConfig:
    return EnvFileConfig(configs_dir)
