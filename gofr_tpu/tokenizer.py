"""Byte-level BPE tokenizer: native (C++) fast path, pure-Python fallback.

Serving endpoints speak text; models speak ids. This module is the bridge:
a greedy rank-based byte-level BPE (the GPT-2 family's merge loop,
implemented from the published algorithm) with

- a C++ implementation (native/tokenizer.cpp, loaded via gofr_tpu.native)
  for the per-request hot path,
- an identical pure-Python implementation used when no toolchain exists
  (and as the equivalence oracle in tests),
- a count-based trainer (``train_bpe``) so users can fit merges to their
  corpus, and a one-line model file format: ``left right`` id pairs.

Config wiring (container): ``TOKENIZER_PATH`` points at a merges file;
``TOKENIZER=byte`` gives the mergeless 256-id byte tokenizer. Special ids
(pad/bos/eos) occupy the TOP of the id space so raw byte ids stay stable.
"""

from __future__ import annotations

import ctypes
from collections import Counter
from typing import Optional

SPECIAL_TOKENS = ("<pad>", "<bos>", "<eos>")


class Tokenizer:
    def __init__(self, merges: list[tuple[int, int]], n_special: int = len(SPECIAL_TOKENS)):
        # drop duplicates and pairs referencing not-yet-defined symbols —
        # ranks and pieces must stay in lockstep (mirrors gofr_tok_new)
        self.merges = []
        self._ranks: dict[tuple[int, int], int] = {}
        self._pieces = [bytes([i]) for i in range(256)]  # id -> byte string
        for left, right in merges:
            if (left, right) in self._ranks:
                continue
            if not (0 <= left < len(self._pieces) and 0 <= right < len(self._pieces)):
                continue
            self._ranks[(left, right)] = len(self.merges)
            self.merges.append((left, right))
            self._pieces.append(self._pieces[left] + self._pieces[right])
        self.n_special = n_special
        self._native = None
        self._handle = None
        from gofr_tpu import native

        lib = native.load()
        if lib is not None:
            blob = "\n".join(f"{l} {r}" for l, r in self.merges).encode()
            handle = lib.gofr_tok_new(blob, len(blob), n_special)
            if handle:
                self._native = lib
                self._handle = handle

    # -- constructors --------------------------------------------------------
    @classmethod
    def byte_level(cls, n_special: int = len(SPECIAL_TOKENS)) -> "Tokenizer":
        """No merges: one id per byte (ids 0..255) + specials."""
        return cls([], n_special)

    @classmethod
    def from_file(cls, path: str, n_special: int = len(SPECIAL_TOKENS)) -> "Tokenizer":
        merges: list[tuple[int, int]] = []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 2:
                    try:
                        merges.append((int(parts[0]), int(parts[1])))
                    except ValueError:
                        continue  # header/comment lines are skipped
        return cls(merges, n_special)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for left, right in self.merges:
                f.write(f"{left} {right}\n")

    # -- properties ----------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges) + self.n_special

    @property
    def backend(self) -> str:
        return "native" if self._native is not None else "python"

    def special_id(self, name: str) -> int:
        """pad/bos/eos ids sit at the top of the id space."""
        idx = SPECIAL_TOKENS.index(f"<{name}>")
        if idx >= self.n_special:
            raise ValueError(f"tokenizer has no <{name}> (n_special={self.n_special})")
        return 256 + len(self.merges) + idx

    # -- encode / decode -----------------------------------------------------
    def encode(self, text: str | bytes) -> list[int]:
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        if self._native is not None:
            return self._encode_native(data)
        return self._encode_python(data)

    def decode(self, ids: list[int]) -> str:
        if self._native is not None:
            data = self._decode_native(ids)
        else:
            top = 256 + len(self.merges)
            data = b"".join(self._pieces[i] for i in ids if 0 <= i < top)
        return data.decode("utf-8", errors="replace")

    def _encode_native(self, data: bytes) -> list[int]:
        lib = self._native
        cap = max(len(data), 1)
        buf = (ctypes.c_int32 * cap)()
        n = lib.gofr_tok_encode(self._handle, data, len(data), buf, cap)
        return list(buf[: min(n, cap)])  # n <= len(data) always: merges only shrink

    def _decode_native(self, ids: list[int]) -> bytes:
        lib = self._native
        arr = (ctypes.c_int32 * max(len(ids), 1))(*ids)
        # every id decodes to >=1 byte; longest piece bounds the need
        cap = max(1, sum(len(self._pieces[i]) if 0 <= i < len(self._pieces) else 0 for i in ids))
        buf = (ctypes.c_uint8 * cap)()
        n = lib.gofr_tok_decode(self._handle, arr, len(ids), buf, cap)
        return bytes(buf[: min(n, cap)])

    def _encode_python(self, data: bytes) -> list[int]:
        """O(n log n) greedy merge: linked list + lazy min-heap, identical
        candidate ordering (rank, then leftmost) to the native encode."""
        import heapq

        n = len(data)
        if n == 0:
            return []
        ids = list(data)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        dead = [False] * n
        ranks = self._ranks
        heap: list[tuple[int, int, int, int]] = []
        for i in range(n - 1):
            rank = ranks.get((ids[i], ids[i + 1]))
            if rank is not None:
                heap.append((rank, i, ids[i], ids[i + 1]))
        heapq.heapify(heap)
        while heap:
            rank, i, left, right = heapq.heappop(heap)
            j = -1 if dead[i] else nxt[i]
            if j < 0 or dead[i] or dead[j] or ids[i] != left or ids[j] != right:
                continue  # stale candidate
            ids[i] = 256 + rank
            dead[j] = True
            nxt[i] = nxt[j]
            if nxt[j] >= 0:
                prv[nxt[j]] = i
            for a in (prv[i], i):
                b = nxt[a] if a >= 0 else -1
                if a >= 0 and b >= 0:
                    r = ranks.get((ids[a], ids[b]))
                    if r is not None:
                        heapq.heappush(heap, (r, a, ids[a], ids[b]))
        out = []
        i = 0
        while i >= 0:
            out.append(ids[i])
            i = nxt[i]
        return out

    def stream_decoder(self) -> "StreamDecoder":
        """Incremental decoder for token streams: buffers partial UTF-8
        sequences across token boundaries so multi-byte characters split
        over tokens decode correctly (SSE/gRPC streaming)."""
        return StreamDecoder(self)

    def __del__(self):  # noqa: D105
        lib, handle = getattr(self, "_native", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            try:
                lib.gofr_tok_free(handle)
            except Exception:
                pass


class StreamDecoder:
    """Feeds token ids one at a time, emitting text as soon as complete
    UTF-8 sequences are available; trailing partial bytes stay buffered."""

    def __init__(self, tokenizer: Tokenizer):
        import codecs

        self._tok = tokenizer
        self._dec = codecs.getincrementaldecoder("utf-8")(errors="replace")

    def feed(self, token_id: int) -> str:
        pieces = self._tok._pieces
        if not 0 <= token_id < len(pieces):
            return ""  # special/oob ids carry no bytes
        return self._dec.decode(pieces[token_id])

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)


def train_bpe(
    corpus: str | bytes,
    vocab_size: int,
    n_special: int = len(SPECIAL_TOKENS),
) -> Tokenizer:
    """Count-based BPE training: repeatedly merge the most frequent adjacent
    pair until the vocabulary reaches ``vocab_size`` (or no pair repeats).
    Simple full-recount per merge — training is offline, serving is not."""
    data = corpus.encode("utf-8") if isinstance(corpus, str) else bytes(corpus)
    n_merges = vocab_size - 256 - n_special
    if n_merges < 0:
        raise ValueError(f"vocab_size must be >= {256 + n_special}")
    ids = list(data)
    merges: list[tuple[int, int]] = []
    for _ in range(n_merges):
        counts = Counter(zip(ids, ids[1:]))
        if not counts:
            break
        pair, freq = counts.most_common(1)[0]
        if freq < 2:
            break
        new_id = 256 + len(merges)
        merges.append(pair)
        out = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        ids = out
    return Tokenizer(merges, n_special)


def load_tokenizer(config) -> Optional[Tokenizer]:
    """Container wiring: TOKENIZER_PATH (merges file) > TOKENIZER=byte >
    None (id-only endpoints)."""
    path = config.get("TOKENIZER_PATH")
    if path:
        return Tokenizer.from_file(path)
    if config.get_or_default("TOKENIZER", "") == "byte":
        return Tokenizer.byte_level()
    return None
