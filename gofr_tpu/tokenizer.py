"""Byte-level BPE tokenizer: native (C++) fast path, pure-Python fallback.

Serving endpoints speak text; models speak ids. This module is the bridge:
a greedy rank-based byte-level BPE (the GPT-2 family's merge loop,
implemented from the published algorithm) with

- a C++ implementation (native/tokenizer.cpp, loaded via gofr_tpu.native)
  for the per-request hot path,
- an identical pure-Python implementation used when no toolchain exists
  (and as the equivalence oracle in tests),
- a count-based trainer (``train_bpe``) so users can fit merges to their
  corpus, and a one-line model file format: ``left right`` id pairs.

Config wiring (container): ``TOKENIZER_PATH`` points at a merges file;
``TOKENIZER=byte`` gives the mergeless 256-id byte tokenizer. Special ids
(pad/bos/eos) occupy the TOP of the id space so raw byte ids stay stable.
"""

from __future__ import annotations

import ctypes
from collections import Counter
from functools import lru_cache
from typing import Optional

SPECIAL_TOKENS = ("<pad>", "<bos>", "<eos>")

# the GPT-2 byte-level BPE regex (public algorithm) — used when an HF
# tokenizer.json requests ByteLevel pre-tokenization without its own pattern
_GPT2_SPLIT = (
    r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
)


@lru_cache(maxsize=1)
def _byte_unicode_tables() -> tuple[dict[int, str], dict[str, int]]:
    """GPT-2 byte<->unicode mapping (public algorithm): printable bytes map
    to themselves, the rest shift into U+0100.. so every byte has a visible
    single-character representation inside HF vocab strings."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    byte_to_uni = {b: chr(c) for b, c in zip(bs, cs)}
    uni_to_byte = {u: b for b, u in byte_to_uni.items()}
    return byte_to_uni, uni_to_byte


def _hf_token_bytes(token: str) -> Optional[bytes]:
    """HF vocab string -> raw bytes; None when the string contains
    characters outside the byte-level alphabet (added/special tokens)."""
    _, uni_to_byte = _byte_unicode_tables()
    try:
        return bytes(uni_to_byte[ch] for ch in token)
    except KeyError:
        return None


class Tokenizer:
    def __init__(self, merges: list[tuple[int, int]], n_special: int = len(SPECIAL_TOKENS)):
        # drop duplicates and pairs referencing not-yet-defined symbols —
        # ranks and pieces must stay in lockstep (mirrors gofr_tok_new)
        self.merges = []
        self._ranks: dict[tuple[int, int], int] = {}
        self._pieces = [bytes([i]) for i in range(256)]  # id -> byte string
        for left, right in merges:
            if (left, right) in self._ranks:
                continue
            if not (0 <= left < len(self._pieces) and 0 <= right < len(self._pieces)):
                continue
            self._ranks[(left, right)] = len(self.merges)
            self.merges.append((left, right))
            self._pieces.append(self._pieces[left] + self._pieces[right])
        self.n_special = n_special
        # HF interop (from_hf_json): internal ids (byte ids + dense merge
        # ranks) translate to the checkpoint's external ids at the API edge
        self._ext_of: Optional[list[int]] = None  # internal id -> external
        self._int_of: Optional[dict[int, int]] = None  # external -> internal
        self._ext_vocab: Optional[int] = None
        self._special_ids: dict[str, int] = {}  # "bos"/"eos"/"pad" -> ext id
        self._token_ids: dict[str, int] = {}  # special content -> ext id
        self._pretok = None  # compiled split regex (HF pre-tokenizer)
        self._native = None
        self._handle = None
        from gofr_tpu import native

        lib = native.load()
        if lib is not None:
            blob = "\n".join(f"{l} {r}" for l, r in self.merges).encode()
            handle = lib.gofr_tok_new(blob, len(blob), n_special)
            if handle:
                self._native = lib
                self._handle = handle

    # -- constructors --------------------------------------------------------
    @classmethod
    def byte_level(cls, n_special: int = len(SPECIAL_TOKENS)) -> "Tokenizer":
        """No merges: one id per byte (ids 0..255) + specials."""
        return cls([], n_special)

    @classmethod
    def from_file(cls, path: str, n_special: int = len(SPECIAL_TOKENS)) -> "Tokenizer":
        merges: list[tuple[int, int]] = []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 2:
                    try:
                        merges.append((int(parts[0]), int(parts[1])))
                    except ValueError:
                        continue  # header/comment lines are skipped
        return cls(merges, n_special)

    @classmethod
    def from_hf_json(cls, path: str) -> "Tokenizer":
        """Load an HF ``tokenizer.json`` (byte-level BPE: GPT-2/Llama-3
        family). The merge list translates rank-for-rank onto this BPE; the
        vocab supplies the external-id mapping so encode/decode speak the
        checkpoint's ids. The file's own Split pre-tokenizer regex is
        honored (merges never cross pre-token boundaries, matching HF
        exactly); ByteLevel-only tokenizers get the published GPT-2
        pattern."""
        import json

        with open(path) as f:
            spec = json.load(f)
        model = spec.get("model", {})
        if model.get("type") != "BPE":
            raise ValueError(
                f"{path}: model.type={model.get('type')!r} — only byte-level "
                "BPE tokenizer.json files are supported"
            )
        vocab: dict[str, int] = model["vocab"]
        byte_to_uni, _ = _byte_unicode_tables()

        # internal piece table: byte ids 0..255, then one id per merge
        piece_ids: dict[bytes, int] = {bytes([b]): b for b in range(256)}
        merges: list[tuple[int, int]] = []
        raw_merges = model.get("merges", [])
        for entry in raw_merges:
            if isinstance(entry, str):
                left_s, _, right_s = entry.partition(" ")
            else:
                left_s, right_s = entry
            left_b = _hf_token_bytes(left_s)
            right_b = _hf_token_bytes(right_s)
            if left_b is None or right_b is None:
                continue
            left = piece_ids.get(left_b)
            right = piece_ids.get(right_b)
            if left is None or right is None:
                continue  # references a piece never built (filtered merge)
            piece_ids[left_b + right_b] = 256 + len(merges)
            merges.append((left, right))

        tok = cls(merges, n_special=0)

        # internal -> external ids via the vocab strings
        ext_of = [-1] * (256 + len(tok.merges))
        for token_str, ext_id in vocab.items():
            raw = _hf_token_bytes(token_str)
            if raw is None:
                continue
            internal = piece_ids.get(raw)
            if internal is not None and internal < len(ext_of):
                ext_of[internal] = ext_id
        tok._ext_of = ext_of
        tok._int_of = {e: i for i, e in enumerate(ext_of) if e >= 0}
        max_ext = max((e for e in ext_of if e >= 0), default=-1)

        # added/special tokens (bos/eos/pad by conventional content)
        for added in spec.get("added_tokens", []):
            content, ext_id = added.get("content"), added.get("id")
            if content is None or ext_id is None:
                continue
            tok._token_ids[content] = ext_id
            max_ext = max(max_ext, ext_id)
        for name, candidates in (
            ("bos", ("<|begin_of_text|>", "<s>", "<bos>", "<|startoftext|>")),
            ("eos", ("<|end_of_text|>", "</s>", "<eos>", "<|endoftext|>")),
            ("pad", ("<pad>", "<|pad|>", "<|finetune_right_pad_id|>")),
        ):
            for cand in candidates:
                if cand in tok._token_ids:
                    tok._special_ids[name] = tok._token_ids[cand]
                    break
        tok._ext_vocab = max_ext + 1
        tok._pretok = _compile_pretokenizer(spec.get("pre_tokenizer"))
        return tok

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for left, right in self.merges:
                f.write(f"{left} {right}\n")

    # -- properties ----------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        if self._ext_vocab is not None:
            return self._ext_vocab
        return 256 + len(self.merges) + self.n_special

    @property
    def backend(self) -> str:
        return "native" if self._native is not None else "python"

    def special_id(self, name: str) -> int:
        """pad/bos/eos ids: the checkpoint's (HF tokenizer.json) or the top
        of the native id space."""
        if self._special_ids:
            try:
                return self._special_ids[name]
            except KeyError:
                raise ValueError(f"tokenizer has no {name} token") from None
        idx = SPECIAL_TOKENS.index(f"<{name}>")
        if idx >= self.n_special:
            raise ValueError(f"tokenizer has no <{name}> (n_special={self.n_special})")
        return 256 + len(self.merges) + idx

    def token_id(self, content: str) -> Optional[int]:
        """External id of an added/special token by its literal content
        (e.g. ``"<|eot_id|>"``); None when absent."""
        return self._token_ids.get(content)

    # -- encode / decode -----------------------------------------------------
    def encode(self, text: str | bytes) -> list[int]:
        if self._pretok is not None and isinstance(text, bytes):
            # HF pre-tokenization is defined on text; bytes must not
            # silently bypass it (ids would diverge from the HF library).
            # Invalid UTF-8 raises rather than encode out-of-distribution.
            text = text.decode("utf-8")
        if self._pretok is not None and isinstance(text, str):
            # HF semantics: BPE runs per pre-token chunk, merges never
            # cross chunk boundaries. finditer + explicit gap handling:
            # findall would return group captures for patterns with
            # capturing groups and silently DROP unmatched spans — every
            # input byte must reach the encoder.
            ids: list[int] = []
            pos = 0
            for m in self._pretok.finditer(text):
                if m.start() > pos:
                    ids.extend(self._encode_raw(text[pos : m.start()].encode("utf-8")))
                if m.group(0):
                    ids.extend(self._encode_raw(m.group(0).encode("utf-8")))
                pos = m.end()
            if pos < len(text):
                ids.extend(self._encode_raw(text[pos:].encode("utf-8")))
            return self._map_out(ids)
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        return self._map_out(self._encode_raw(data))

    def _encode_raw(self, data: bytes) -> list[int]:
        if self._native is not None:
            return self._encode_native(data)
        return self._encode_python(data)

    def _map_out(self, ids: list[int]) -> list[int]:
        if self._ext_of is None:
            return ids
        return [self._ext_of[i] for i in ids if self._ext_of[i] >= 0]

    def decode(self, ids: list[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def decode_bytes(self, ids: list[int]) -> bytes:
        """The RAW bytes behind ``ids`` — a single byte-level BPE token
        can hold a FRAGMENT of a multi-byte character, and consumers that
        reassemble text across token boundaries (the OpenAI logprobs
        ``bytes`` field) need the true fragment, not the replacement
        character ``decode`` would substitute."""
        if self._int_of is not None:
            # external ids without a byte-level piece (specials) carry no text
            ids = [self._int_of[i] for i in ids if i in self._int_of]
        if self._native is not None:
            return self._decode_native(ids)
        top = 256 + len(self.merges)
        return b"".join(self._pieces[i] for i in ids if 0 <= i < top)

    def _encode_native(self, data: bytes) -> list[int]:
        lib = self._native
        cap = max(len(data), 1)
        buf = (ctypes.c_int32 * cap)()
        n = lib.gofr_tok_encode(self._handle, data, len(data), buf, cap)
        return list(buf[: min(n, cap)])  # n <= len(data) always: merges only shrink

    def _decode_native(self, ids: list[int]) -> bytes:
        lib = self._native
        arr = (ctypes.c_int32 * max(len(ids), 1))(*ids)
        # every id decodes to >=1 byte; longest piece bounds the need
        cap = max(1, sum(len(self._pieces[i]) if 0 <= i < len(self._pieces) else 0 for i in ids))
        buf = (ctypes.c_uint8 * cap)()
        n = lib.gofr_tok_decode(self._handle, arr, len(ids), buf, cap)
        return bytes(buf[: min(n, cap)])

    def _encode_python(self, data: bytes) -> list[int]:
        """O(n log n) greedy merge: linked list + lazy min-heap, identical
        candidate ordering (rank, then leftmost) to the native encode."""
        import heapq

        n = len(data)
        if n == 0:
            return []
        ids = list(data)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        dead = [False] * n
        ranks = self._ranks
        heap: list[tuple[int, int, int, int]] = []
        for i in range(n - 1):
            rank = ranks.get((ids[i], ids[i + 1]))
            if rank is not None:
                heap.append((rank, i, ids[i], ids[i + 1]))
        heapq.heapify(heap)
        while heap:
            rank, i, left, right = heapq.heappop(heap)
            j = -1 if dead[i] else nxt[i]
            if j < 0 or dead[i] or dead[j] or ids[i] != left or ids[j] != right:
                continue  # stale candidate
            ids[i] = 256 + rank
            dead[j] = True
            nxt[i] = nxt[j]
            if nxt[j] >= 0:
                prv[nxt[j]] = i
            for a in (prv[i], i):
                b = nxt[a] if a >= 0 else -1
                if a >= 0 and b >= 0:
                    r = ranks.get((ids[a], ids[b]))
                    if r is not None:
                        heapq.heappush(heap, (r, a, ids[a], ids[b]))
        out = []
        i = 0
        while i >= 0:
            out.append(ids[i])
            i = nxt[i]
        return out

    def stream_decoder(self) -> "StreamDecoder":
        """Incremental decoder for token streams: buffers partial UTF-8
        sequences across token boundaries so multi-byte characters split
        over tokens decode correctly (SSE/gRPC streaming)."""
        return StreamDecoder(self)

    def __del__(self):  # noqa: D105
        lib, handle = getattr(self, "_native", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            try:
                lib.gofr_tok_free(handle)
            except Exception:
                pass


class StreamDecoder:
    """Feeds token ids one at a time, emitting text as soon as complete
    UTF-8 sequences are available; trailing partial bytes stay buffered."""

    def __init__(self, tokenizer: Tokenizer):
        import codecs

        self._tok = tokenizer
        self._dec = codecs.getincrementaldecoder("utf-8")(errors="replace")

    def feed(self, token_id: int) -> str:
        if self._tok._int_of is not None:
            internal = self._tok._int_of.get(token_id)
            if internal is None:
                return ""  # special/oob external ids carry no bytes
            token_id = internal
        pieces = self._tok._pieces
        if not 0 <= token_id < len(pieces):
            return ""  # special/oob ids carry no bytes
        return self._dec.decode(pieces[token_id])

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)


def train_bpe(
    corpus: str | bytes,
    vocab_size: int,
    n_special: int = len(SPECIAL_TOKENS),
) -> Tokenizer:
    """Count-based BPE training: repeatedly merge the most frequent adjacent
    pair until the vocabulary reaches ``vocab_size`` (or no pair repeats).
    Simple full-recount per merge — training is offline, serving is not."""
    data = corpus.encode("utf-8") if isinstance(corpus, str) else bytes(corpus)
    n_merges = vocab_size - 256 - n_special
    if n_merges < 0:
        raise ValueError(f"vocab_size must be >= {256 + n_special}")
    ids = list(data)
    merges: list[tuple[int, int]] = []
    for _ in range(n_merges):
        counts = Counter(zip(ids, ids[1:]))
        if not counts:
            break
        pair, freq = counts.most_common(1)[0]
        if freq < 2:
            break
        new_id = 256 + len(merges)
        merges.append(pair)
        out = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        ids = out
    return Tokenizer(merges, n_special)


def _compile_pretokenizer(pre: Optional[dict]):
    """Compile the Split regex out of an HF pre_tokenizer spec (Sequence /
    Split / ByteLevel). Returns a compiled ``regex`` pattern or None (no
    pre-splitting: BPE over the whole byte string)."""
    if not pre:
        return None
    try:
        import regex
    except ImportError:  # pragma: no cover - regex ships in this image
        return None
    nodes = [pre]
    if pre.get("type") == "Sequence":
        nodes = pre.get("pretokenizers", [])
    for node in nodes:
        if node.get("type") == "Split":
            pattern = node.get("pattern", {})
            if "Regex" in pattern:
                return regex.compile(pattern["Regex"])
    for node in nodes:
        if node.get("type") == "ByteLevel" and node.get("use_regex", True):
            return regex.compile(_GPT2_SPLIT)
    return None


def load_tokenizer(config) -> Optional[Tokenizer]:
    """Container wiring: TOKENIZER_PATH (HF tokenizer.json when the file is
    .json, else a merges file) > TOKENIZER=byte > None (id-only
    endpoints)."""
    path = config.get("TOKENIZER_PATH")
    if path:
        if path.endswith(".json"):
            return Tokenizer.from_hf_json(path)
        return Tokenizer.from_file(path)
    if config.get_or_default("TOKENIZER", "") == "byte":
        return Tokenizer.byte_level()
    return None
