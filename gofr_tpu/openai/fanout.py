"""Generation fan-out: the streaming consumer with host-side stop
matching, and n/best_of candidate generation with mean-logprob ranking."""

from __future__ import annotations

from typing import Any

from gofr_tpu.openai.parse import _StopScanner, _sampler

from gofr_tpu.errors import HTTPError

def _consume_stream(
    ctx: Any, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, need_lp: bool, adapter: Any,
) -> tuple[list, Any, str, str]:
    """Generate through the streaming bridge, matching multi-token stop
    strings host-side as text streams off the device and CANCELLING the
    background decode at the first match (closing the iterator frees the
    pool slot — a matched stop must not keep generating to max_tokens).
    Returns (tokens, logprobs_or_None, text, finish_reason); ``text`` is
    truncated before the stop string, tokens/logprobs cover everything
    actually generated (usage accounting)."""
    tok = ctx.tpu.tokenizer  # _parse_stops guarantees one for stop_strs
    dec = tok.stream_decoder()
    scan = _StopScanner(stop_strs)
    it = ctx.tpu.generate_stream(
        prompt_ids, max_tokens, sampler=sampler, stop_tokens=stop_ids,
        adapter=adapter, logprobs=need_lp,
    )
    toks: list = []
    lps: list = []
    parts: list = []
    starts: list = []  # decoded-text offset where each token's text began
    decoded = 0
    finish = None
    try:
        for item in it:
            t, lp = item if need_lp else (item, None)
            toks.append(t)
            if lp is not None:
                lps.append(lp)
            piece = dec.feed(t)
            starts.append(decoded)
            decoded += len(piece)
            emit, done = scan.feed(piece)
            parts.append(emit)
            if done:
                finish = "stop"
                break
        if finish is None:
            emit, done = scan.feed(dec.flush())
            parts.append(emit)
            if done:
                finish = "stop"
            else:
                parts.append(scan.flush())
                finish = "length" if len(toks) >= max_tokens else "stop"
    finally:
        it.close()
    if need_lp and scan.match_pos is not None:
        # align response logprobs with the TRUNCATED text: keep tokens
        # whose text starts before the match (usage still bills the full
        # toks list — the tokens were generated)
        vis = sum(1 for s in starts if s < scan.match_pos)
        lps = lps[:vis]
    return toks, (lps if need_lp else None), "".join(parts), finish


def _fanout_generate(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int,
    sampler: Any, stop_ids: Any, stop_strs: list, want_logprobs: bool,
    top_n: int, adapter: Any, n: int, best_of: int,
) -> tuple[list, int]:
    """Generate ``best_of`` candidates and keep the ``n`` best. Returns
    ([(tokens, logprobs_or_None, tops_or_None, text_or_None,
    finish_or_None), ...] of length n, total tokens generated across ALL
    candidates — usage must count discarded best_of candidates too, the
    OpenAI accounting).
    ``text``/``finish`` are set only on the multi-token-stop path (the
    host-matched truncation IS the text); otherwise the caller decodes
    the ids itself. ``top_n`` > 0 also collects the top-k alternatives
    per position (tops; None otherwise) — rejected with stop_strs at
    the call sites, so the two never combine here.

    - Deterministic requests (temperature 0) produce identical candidates:
      ONE generation is replicated, not recomputed (and billed once per
      replica, matching what the response carries).
    - Sampled candidates run CONCURRENTLY: the continuous-batching pool
      decodes unseeded requests in one lockstep dispatch, so n streams
      cost ~one stream's wall time. A seeded request derives per-candidate
      seeds (seed + index) so the whole fan-out stays reproducible.
    - best_of > n ranks by mean token logprob (generated with logprobs
      internally; stripped from the response unless requested)."""
    score = best_of > n
    need_lp = want_logprobs or score

    def one(s):
        if stop_strs:
            toks, lps, text, finish = _consume_stream(
                ctx, prompt_ids, max_tokens, s, stop_ids, stop_strs,
                need_lp, adapter,
            )
            return toks, lps, None, text, finish
        if top_n:
            toks, lps, tops = ctx.tpu.generate(
                prompt_ids, max_tokens, sampler=s, stop_tokens=stop_ids,
                adapter=adapter, logprobs=True, top_logprobs=True,
            )
            return toks, lps, tops, None, None
        out = ctx.tpu.generate(
            prompt_ids, max_tokens, sampler=s, stop_tokens=stop_ids,
            adapter=adapter, logprobs=need_lp,
        )
        toks, lps = out if need_lp else (out, None)
        return toks, lps, None, None, None

    if sampler.greedy:
        toks, lps, tops, text, finish = one(sampler)
        if not want_logprobs:
            lps = None
        return [(toks, lps, tops, text, finish)] * n, len(toks) * n

    seed = body.get("seed")
    if seed is not None:
        try:
            seed = int(seed)
        except (TypeError, ValueError):
            raise HTTPError(400, '"seed" must be an integer') from None
    samplers = [
        _sampler({**body, "seed": seed + i} if seed is not None else body)
        for i in range(best_of)
    ]
    if best_of == 1:
        results = [one(samplers[0])]
    else:
        from concurrent.futures import ThreadPoolExecutor

        # concurrency scales with the DEPLOYMENT, not the request: a
        # fixed best_of-wide fan-out would let one n=16 request occupy
        # every decode-pool slot (or spawn 16 solo seeded decodes) and
        # starve concurrent traffic. Default: ~3/4 of the pool slots;
        # candidates beyond it serialize through pool.map. A seeded
        # fan-out decodes solo, so the same bound caps its thread count.
        raw = ctx.config.get_or_default("OPENAI_FANOUT_WORKERS", "")
        if raw:
            try:
                workers = max(1, min(best_of, int(raw)))
            except ValueError:
                raise HTTPError(
                    500, "OPENAI_FANOUT_WORKERS must be an integer"
                ) from None
        else:
            slots = getattr(
                getattr(ctx.tpu, "decode_pool", None), "n_slots", None
            ) or 4
            workers = max(1, min(best_of, (slots * 3) // 4 or 1))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            results = list(pool.map(one, samplers))
    generated = sum(len(r[0]) for r in results)
    if score:
        def mean_lp(item):
            lps = item[1]
            return sum(lps) / len(lps) if lps else float("-inf")

        results = sorted(results, key=mean_lp, reverse=True)[:n]
    if not want_logprobs:
        results = [(toks, None, tops, text, finish)
                   for toks, _, tops, text, finish in results]
    return results, generated
