"""Generation fan-out: the streaming consumer with host-side stop
matching, and n/best_of candidate generation with mean-logprob ranking."""

from __future__ import annotations

from typing import Any

from gofr_tpu.openai.parse import _StopScanner, _sampler

from gofr_tpu.errors import HTTPError

STREAM_END = object()  # per-index end marker on the multiplex queue


class _LinkedCancel:
    """Event-like stop for ONE fan-out candidate: reads as set when
    either the shared client-abort event or this candidate's own
    teardown tripped. ``set()`` marks only the local side — a finished
    candidate's generator close (``_stream_iter``'s ``finally:
    stop.set()``) must never cancel its still-decoding siblings, while
    a real client abort (the shared event) must cancel all of them.
    The decode paths only ever ``is_set()`` their stop events, so this
    is the full surface they need."""

    __slots__ = ("_shared", "_local")

    def __init__(self, shared: Any):
        import threading

        self._shared = shared
        self._local = threading.Event()

    def set(self) -> None:
        self._local.set()

    def is_set(self) -> bool:
        return self._local.is_set() or (
            self._shared is not None and self._shared.is_set()
        )


def _candidate_samplers(body: dict, count: int) -> list:
    """Per-candidate samplers with the seed+index derivation — THE
    reproducibility contract the stream and non-stream fan-outs share
    (stream candidates must byte-match non-stream candidates)."""
    seed = body.get("seed")
    if seed is not None:
        try:
            seed = int(seed)
        except (TypeError, ValueError):
            raise HTTPError(400, '"seed" must be an integer') from None
    return [
        _sampler({**body, "seed": seed + i} if seed is not None else body)
        for i in range(count)
    ]


def _fanout_workers_override(ctx: Any) -> Any:
    """OPENAI_FANOUT_WORKERS, validated — the operator's explicit
    fan-out concurrency bound (None when unset). Both fan-out paths
    OBEY it in both directions: raising and lowering."""
    raw = ctx.config.get_or_default("OPENAI_FANOUT_WORKERS", "")
    if not raw:
        return None
    try:
        return max(1, int(raw))
    except ValueError:
        raise HTTPError(
            500, "OPENAI_FANOUT_WORKERS must be an integer"
        ) from None


def _fanout_workers(ctx: Any, default_slots: int = 4) -> int:
    """Deployment-scaled fan-out concurrency bound, shared by both
    paths: ~3/4 of the decode pool's slots (one wide request must not
    occupy every slot, nor spawn that many solo seeded decodes);
    OPENAI_FANOUT_WORKERS overrides."""
    override = _fanout_workers_override(ctx)
    if override is not None:
        return override
    slots = getattr(
        getattr(ctx.tpu, "decode_pool", None), "n_slots", None
    ) or default_slots
    return max(1, (slots * 3) // 4 or 1)


def _stream_candidates(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int,
    sampler: Any, stop_ids: Any, adapter: Any, want_logprobs: bool,
    n: int, cancel: Any = None,
) -> list:
    """Construct the n candidate stream iterators for interleaved SSE.
    Built BEFORE the 200 commits (parameter errors must 400 first).
    Seeded fan-outs derive per-candidate seeds via _candidate_samplers;
    unseeded candidates share the continuous-batching pool. Unlike the
    non-stream path, candidates past the concurrency bound cannot
    serialize (all indexes must progress for interleaved output), so an
    over-wide n is a 400 scaled to the deployment: n may use up to the
    pool's full slot count (OPENAI_FANOUT_WORKERS overrides). The
    caller owns closing every iterator."""
    if n == 1:
        return [ctx.tpu.generate_stream(
            prompt_ids, max_tokens, sampler=sampler, stop_tokens=stop_ids,
            adapter=adapter, logprobs=want_logprobs, cancel=cancel,
        )]
    override = _fanout_workers_override(ctx)
    if override is not None:
        bound = override  # explicit operator bound: obeyed in BOTH directions
    else:
        # default: streamed candidates may use up to the pool's full slot
        # count (they cannot serialize — all indexes must progress)
        bound = getattr(
            getattr(ctx.tpu, "decode_pool", None), "n_slots", None
        ) or 4
    if n > bound:
        raise HTTPError(
            400, f'"n" is capped at {bound} when streaming on this '
            "deployment (candidates stream concurrently and cannot be "
            "serialized; raise DECODE_SLOTS or OPENAI_FANOUT_WORKERS)"
        )
    samplers = _candidate_samplers(body, n)
    iters = []
    try:
        for s in samplers:
            # a client abort must free EVERY candidate's slot/KV — but
            # one candidate finishing first must not cancel the rest:
            # each candidate stops on (shared abort OR its own teardown)
            iters.append(ctx.tpu.generate_stream(
                prompt_ids, max_tokens, sampler=s, stop_tokens=stop_ids,
                adapter=adapter, logprobs=want_logprobs,
                cancel=_LinkedCancel(cancel),
            ))
    except BaseException:
        for it in iters:  # a late candidate failing must free the early ones
            it.close()
        raise
    return iters


def _usage_chunk(
    object_name: str, resp_id: str, created: int, model: str,
    prompt_tokens: int, completion_tokens: int,
) -> str:
    """The ONE pre-[DONE] usage frame both endpoints emit under
    stream_options.include_usage: empty choices + the usage object (a
    shape change here must hit both endpoints' billing identically)."""
    import json as _json

    return _json.dumps({
        "id": resp_id, "object": object_name, "created": created,
        "model": model, "choices": [],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens,
        },
    })


def _index_feed_text(
    dec: Any, scan: Any, finish: list, i: int, emitted: list, token: int,
) -> tuple:
    """Decode one token for candidate ``i`` through its stop scanner —
    the ONE copy of the per-index feed state machine both endpoints'
    fan-outs share. Returns (text_or_None, stopped): text None means an
    id-only deployment (no tokenizer; the caller emits the token
    extension), stopped True means the stop matched (finish set; the
    returned text is the pre-stop remainder)."""
    emitted[i] += 1
    if dec is None:
        return None, False
    text = dec.feed(token)
    if scan is not None:
        text, done = scan.feed(text)
        if done:
            finish[i] = "stop"
            return text, True
    return text, False


def _index_tail_text(
    dec: Any, scan: Any, finish: list, i: int, emitted: list,
    max_tokens: int,
) -> str:
    """Flush candidate ``i``'s decoder through its stop scanner and
    settle its finish reason — the ONE copy of the per-index tail state
    machine (the subtlest stop/length logic; it must not fork per
    endpoint). Returns the tail text ('' when already finished)."""
    t = dec.flush() if dec is not None else ""
    if finish[i] is not None:
        return ""
    if scan is not None:
        t, done = scan.feed(t)
        if done:
            finish[i] = "stop"
        else:
            t += scan.flush()
    if finish[i] is None:
        finish[i] = "length" if emitted[i] >= max_tokens else "stop"
    return t


def _drive_stream_fanout(
    iters: list, replicate: bool, n: int, finish: list,
    want_logprobs: bool, open_frames: Any, feed: Any, tail: Any,
    error_frame: Any, usage_frames: Any = None,
) -> Any:
    """The ONE interleaved-SSE driver both endpoints share: replicate
    mode consumes a single iterator and fans frames across indexes;
    multiplex mode merges n pump threads. ``finish`` is the caller's
    per-index finish-reason list — ``feed``/``tail`` mutate it; when a
    feed marks an index finished (stop match), its decode is cancelled
    and anything else that index produces — including an error from the
    cancellation itself — is dropped rather than aborting the healthy
    candidates. Errors from UNFINISHED indexes abort the whole stream
    with one error frame (the transport cannot re-status a committed
    200)."""
    cancels: list = []
    try:
        yield from open_frames()
        if replicate:
            for item in iters[0]:
                token, lp = item if want_logprobs else (item, None)
                for i in range(n):
                    if finish[i] is None:
                        yield from feed(i, token, lp)
                if all(f is not None for f in finish):
                    break
            for i in range(n):
                yield from tail(i)
        else:
            q, cancels_ = _multiplex(iters)
            cancels.extend(cancels_)
            active = n
            while active:
                i, item = q.get()
                if item is STREAM_END:
                    active -= 1
                    yield from tail(i)
                    continue
                if finish[i] is not None:
                    continue  # stop-matched: drop tokens AND late errors
                if (
                    isinstance(item, tuple) and len(item) == 2
                    and item[0] == "error"
                ):
                    raise item[1]
                token, lp = item if want_logprobs else (item, None)
                yield from feed(i, token, lp)
                if finish[i] is not None:
                    cancels[i].set()  # stop matched: free its decode early
        if usage_frames is not None:
            # stream_options.include_usage: one final pre-[DONE] chunk
            # with empty choices and the usage object
            yield from usage_frames()
        yield "[DONE]"
    except Exception as exc:
        yield error_frame(exc)
    finally:
        if replicate:
            iters[0].close()  # same thread drives it: legal
        else:
            for ev in cancels:
                ev.set()  # pump threads close their own iterators


def _multiplex(iters: list) -> tuple:
    """Merge n token iterators into ONE queue of (index, item) pairs;
    each stream's end posts (index, STREAM_END), an error posts
    (index, ("error", exc)) then STREAM_END. Returns (queue, cancels):
    the PUMP thread owns each iterator's lifecycle — a raw generator
    cannot be close()d from another thread while it executes — so the
    consumer cancels index i by setting cancels[i]; the pump notices at
    its next item, closes the iterator (the device's stop event cancels
    the background decode), and posts STREAM_END."""
    import queue as _queue
    import threading

    out: "_queue.Queue" = _queue.Queue()
    cancels = [threading.Event() for _ in iters]

    def pump(i: int, it: Any) -> None:
        try:
            for item in it:
                if cancels[i].is_set():
                    break
                out.put((i, item))
        except Exception as exc:  # surfaced as an SSE error frame
            out.put((i, ("error", exc)))
        finally:
            # STREAM_END must post even if close() raises (a cancellation
            # tearing down the decode can error): a lost sentinel would
            # wedge the consumer in q.get() forever, hanging the response
            try:
                it.close()  # suspended here, owned by this thread: legal
            except Exception:
                pass  # the index already ended; nothing left to deliver
            finally:
                out.put((i, STREAM_END))

    for i, it in enumerate(iters):
        threading.Thread(
            target=pump, args=(i, it), daemon=True,
            name=f"gofr-sse-fanout-{i}",
        ).start()
    return out, cancels


def _consume_stream(
    ctx: Any, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, need_lp: bool, adapter: Any,
) -> tuple[list, Any, str, str]:
    """Generate through the streaming bridge, matching multi-token stop
    strings host-side as text streams off the device and CANCELLING the
    background decode at the first match (closing the iterator frees the
    pool slot — a matched stop must not keep generating to max_tokens).
    Returns (tokens, logprobs_or_None, text, finish_reason); ``text`` is
    truncated before the stop string, tokens/logprobs cover everything
    actually generated (usage accounting)."""
    tok = ctx.tpu.tokenizer  # _parse_stops guarantees one for stop_strs
    dec = tok.stream_decoder()
    scan = _StopScanner(stop_strs)
    it = ctx.tpu.generate_stream(
        prompt_ids, max_tokens, sampler=sampler, stop_tokens=stop_ids,
        adapter=adapter, logprobs=need_lp,
    )
    toks: list = []
    lps: list = []
    parts: list = []
    starts: list = []  # decoded-text offset where each token's text began
    decoded = 0
    finish = None
    try:
        for item in it:
            t, lp = item if need_lp else (item, None)
            toks.append(t)
            if lp is not None:
                lps.append(lp)
            piece = dec.feed(t)
            starts.append(decoded)
            decoded += len(piece)
            emit, done = scan.feed(piece)
            parts.append(emit)
            if done:
                finish = "stop"
                break
        if finish is None:
            emit, done = scan.feed(dec.flush())
            parts.append(emit)
            if done:
                finish = "stop"
            else:
                parts.append(scan.flush())
                finish = "length" if len(toks) >= max_tokens else "stop"
    finally:
        it.close()
    if need_lp and scan.match_pos is not None:
        # align response logprobs with the TRUNCATED text: keep tokens
        # whose text starts before the match (usage still bills the full
        # toks list — the tokens were generated)
        vis = sum(1 for s in starts if s < scan.match_pos)
        lps = lps[:vis]
    return toks, (lps if need_lp else None), "".join(parts), finish


def _fanout_generate(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int,
    sampler: Any, stop_ids: Any, stop_strs: list, want_logprobs: bool,
    top_n: int, adapter: Any, n: int, best_of: int,
) -> tuple[list, int]:
    """Generate ``best_of`` candidates and keep the ``n`` best. Returns
    ([(tokens, logprobs_or_None, tops_or_None, text_or_None,
    finish_or_None), ...] of length n, total tokens generated across ALL
    candidates — usage must count discarded best_of candidates too, the
    OpenAI accounting).
    ``text``/``finish`` are set only on the multi-token-stop path (the
    host-matched truncation IS the text); otherwise the caller decodes
    the ids itself. ``top_n`` > 0 also collects the top-k alternatives
    per position (tops; None otherwise) — rejected with stop_strs at
    the call sites, so the two never combine here.

    - Deterministic requests (temperature 0) produce identical candidates:
      ONE generation is replicated, not recomputed (and billed once per
      replica, matching what the response carries).
    - Sampled candidates run CONCURRENTLY: the continuous-batching pool
      decodes unseeded requests in one lockstep dispatch, so n streams
      cost ~one stream's wall time. A seeded request derives per-candidate
      seeds (seed + index) so the whole fan-out stays reproducible.
    - best_of > n ranks by mean token logprob (generated with logprobs
      internally; stripped from the response unless requested)."""
    score = best_of > n
    need_lp = want_logprobs or score

    def one(s):
        if stop_strs:
            toks, lps, text, finish = _consume_stream(
                ctx, prompt_ids, max_tokens, s, stop_ids, stop_strs,
                need_lp, adapter,
            )
            return toks, lps, None, text, finish
        if top_n:
            toks, lps, tops = ctx.tpu.generate(
                prompt_ids, max_tokens, sampler=s, stop_tokens=stop_ids,
                adapter=adapter, logprobs=True, top_logprobs=True,
            )
            return toks, lps, tops, None, None
        out = ctx.tpu.generate(
            prompt_ids, max_tokens, sampler=s, stop_tokens=stop_ids,
            adapter=adapter, logprobs=need_lp,
        )
        toks, lps = out if need_lp else (out, None)
        return toks, lps, None, None, None

    if sampler.greedy:
        toks, lps, tops, text, finish = one(sampler)
        if not want_logprobs:
            lps = None
        return [(toks, lps, tops, text, finish)] * n, len(toks) * n

    samplers = _candidate_samplers(body, best_of)
    if best_of == 1:
        results = [one(samplers[0])]
    else:
        import contextvars
        from concurrent.futures import ThreadPoolExecutor

        # concurrency scales with the DEPLOYMENT, not the request
        # (_fanout_workers): candidates beyond the bound serialize
        # through pool.map; a seeded fan-out decodes solo, so the same
        # bound caps its thread count.
        workers = min(best_of, _fanout_workers(ctx))
        # one context COPY per candidate (a single Context cannot run
        # concurrently), snapshotted HERE in the handler thread: pool
        # workers inherit nothing, and without this the request's span
        # and flight record would be invisible to the generation —
        # orphan traces, empty telemetry
        snapshots = [contextvars.copy_context() for _ in samplers]
        with ThreadPoolExecutor(max_workers=workers) as pool:
            results = list(pool.map(
                lambda pair: pair[0].run(one, pair[1]),
                zip(snapshots, samplers),
            ))
    generated = sum(len(r[0]) for r in results)
    if score:
        def mean_lp(item):
            lps = item[1]
            return sum(lps) / len(lps) if lps else float("-inf")

        results = sorted(results, key=mean_lp, reverse=True)[:n]
    if not want_logprobs:
        results = [(toks, None, tops, text, finish)
                   for toks, _, tops, text, finish in results]
    return results, generated
