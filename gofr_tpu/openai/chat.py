"""POST /v1/chat/completions: messages -> assistant message. Same
generation core as completions; only prompt construction (chat
template) and response shapes differ."""

from __future__ import annotations

import time
import uuid
from typing import Any

from gofr_tpu.openai.fanout import _fanout_generate
from gofr_tpu.openai.logprobs import _chat_logprobs_obj, _chat_lp_entry
from gofr_tpu.openai.parse import (
    _StopScanner,
    _parse_fanout,
    _parse_request,
    _stream_usage_opt,
)
from gofr_tpu.openai.template import render_chat_prompt

from gofr_tpu.errors import HTTPError


def _stream_chat(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, want_logprobs: bool, top_n: int,
    adapter: Any, n: int, chat_id: str, created: int, model: str,
    tok: Any, include_usage: bool = False,
) -> Any:
    """The SSE branch of /v1/chat/completions: delta chunks with the
    role first, host-side stop matching, terminated by [DONE]. ``n`` > 1
    streams candidates concurrently as interleaved chunks carrying their
    choice ``index`` (greedy requests replicate one stream — the
    non-stream fan-out's replication rule)."""
    if top_n:
        raise HTTPError(
            400, "top-logprob alternatives are not supported when "
            "streaming; drop \"stream\" or request chosen-token "
            "logprobs only"
        )
    import json as _json

    from gofr_tpu.http.response import Stream

    def chunk(delta: dict, finish: Any = None, lp: Any = None,
              token_id: Any = None, index: int = 0) -> str:
        choice: dict[str, Any] = {
            "index": index, "delta": delta, "finish_reason": finish,
        }
        if want_logprobs:
            if lp is not None and token_id is not None:
                e = _chat_lp_entry(tok, token_id, lp)
                e["top_logprobs"] = []  # alternatives reject with stream
                choice["logprobs"] = {
                    # the modern chat shape stock SDKs parse, plus
                    # the legacy field this server has always sent
                    "content": [e],
                    "token_logprobs": [lp],
                }
            else:
                choice["logprobs"] = None
        frame = {
            "id": chat_id, "object": "chat.completion.chunk",
            "created": created, "model": model, "choices": [choice],
        }
        if include_usage:
            frame["usage"] = None
        return _json.dumps(frame)

    def usage_frame(completion_tokens: int) -> str:
        from gofr_tpu.openai.fanout import _usage_chunk

        return _usage_chunk("chat.completion.chunk", chat_id, created, model,
                            len(prompt_ids), completion_tokens)

    if n > 1:
        return _stream_chat_fanout(
            ctx, body, prompt_ids, max_tokens, sampler, stop_ids,
            stop_strs, want_logprobs, adapter, n, chunk, tok,
            usage_frame if include_usage else None,
        )

    from gofr_tpu.openai.parse import _abortable

    cancel, on_abort = _abortable(ctx)
    stream_iter = ctx.tpu.generate_stream(
        prompt_ids, max_tokens, sampler=sampler, stop_tokens=stop_ids,
        adapter=adapter, logprobs=want_logprobs, cancel=cancel,
    )

    def events():
        emitted = 0
        finish = None
        dec = tok.stream_decoder()
        scan = _StopScanner(stop_strs) if stop_strs else None
        yield chunk({"role": "assistant"})  # role arrives first
        try:
            for item in stream_iter:
                token, lp = item if want_logprobs else (item, None)
                emitted += 1
                text = dec.feed(token)
                if scan is not None:
                    text, done = scan.feed(text)
                    if done:
                        if text:
                            # no lp: the matched token's text is
                            # excluded from the stream
                            yield chunk({"content": text})
                        finish = "stop"
                        break
                if text or lp is not None:
                    yield chunk({"content": text}, lp=lp, token_id=token)
            tail = dec.flush()
            if finish is None:
                if scan is not None:
                    tail, done = scan.feed(tail)
                    if done:
                        finish = "stop"
                    else:
                        tail += scan.flush()
                if finish is None:
                    finish = "length" if emitted >= max_tokens else "stop"
            else:
                tail = ""
            if tail:
                yield chunk({"content": tail})
            yield chunk({}, finish)
            if include_usage:
                yield usage_frame(emitted)
            yield "[DONE]"
        except Exception as exc:
            yield _json.dumps({"error": {"message": str(exc)}})
        finally:
            stream_iter.close()  # no-op if already exhausted

    # ids=True: frames carry monotonic SSE ids so the fleet router can
    # resume a deterministic chat stream by replaying from zero and
    # filtering already-delivered frames (chat frames are not 1:1 with
    # tokens, so there is no replica-side X-Resume-From shortcut here)
    return Stream(events(), ids=True, on_abort=on_abort)


def _stream_chat_fanout(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, want_logprobs: bool, adapter: Any,
    n: int, chunk: Any, tok: Any, usage_frame: Any = None,
) -> Any:
    """Interleaved multi-index chat SSE: n candidates stream
    concurrently, each delta carrying its choice ``index``; every index
    opens with its own role chunk and closes with its own finish. The
    shared driver (_drive_stream_fanout) owns the replicate/multiplex
    loops, stop-cancellation, and cleanup; this function supplies only
    the chat frame shapes."""
    import json as _json

    from gofr_tpu.http.response import Stream
    from gofr_tpu.openai.fanout import (
        _drive_stream_fanout,
        _index_feed_text,
        _index_tail_text,
        _stream_candidates,
    )
    from gofr_tpu.openai.parse import _abortable, _StopScanner

    replicate = sampler.greedy
    cancel, on_abort = _abortable(ctx)
    iters = _stream_candidates(
        ctx, body, prompt_ids, max_tokens, sampler, stop_ids, adapter,
        want_logprobs, 1 if replicate else n, cancel=cancel,
    )
    decs = [tok.stream_decoder() for _ in range(n)]
    scans = [_StopScanner(stop_strs) if stop_strs else None
             for _ in range(n)]
    emitted = [0] * n
    finish: list = [None] * n

    def open_frames():
        for i in range(n):
            yield chunk({"role": "assistant"}, index=i)

    def feed(i, token, lp):
        text, stopped = _index_feed_text(
            decs[i], scans[i], finish, i, emitted, token
        )
        if stopped:  # the matched token's lp is excluded with its text
            return [chunk({"content": text}, index=i)] if text else []
        if text or lp is not None:
            return [chunk({"content": text}, lp=lp, token_id=token,
                          index=i)]
        return []

    def tail(i):
        t = _index_tail_text(decs[i], scans[i], finish, i, emitted,
                             max_tokens)
        frames = []
        if t:
            frames.append(chunk({"content": t}, index=i))
        frames.append(chunk({}, finish[i], index=i))
        return frames

    def error_frame(exc):
        return _json.dumps({"error": {"message": str(exc)}})

    usage_frames = (
        (lambda: [usage_frame(sum(emitted))])
        if usage_frame is not None else None
    )
    return Stream(
        _drive_stream_fanout(
            iters, replicate, n, finish, want_logprobs, open_frames, feed,
            tail, error_frame, usage_frames,
        ),
        on_abort=on_abort,
    )


def chat_completions(ctx: Any) -> Any:
    """Messages -> assistant message. Same generation core as
    ``completions``; only the prompt construction (chat template) and the
    response shapes (chat.completion / chat.completion.chunk with deltas)
    differ."""
    (body, max_tokens, sampler, stop_ids, stop_strs, want_logprobs, top_n,
     adapter) = _parse_request(ctx, default_max=64)
    tok = ctx.tpu.tokenizer
    if tok is None:
        raise HTTPError(
            400, "chat completions need a tokenizer (set TOKENIZER_PATH)"
        )
    prompt_text = render_chat_prompt(ctx, body.get("messages"))
    prompt_ids = tok.encode(prompt_text)
    if not prompt_ids:
        raise HTTPError(400, "messages encoded to zero tokens")
    model = adapter or ctx.tpu.model_name  # adapters serve under their name
    # gofrlint: wall-clock — OpenAI API `created` is epoch seconds by contract
    created = int(time.time())
    chat_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"

    n, _, _ = _parse_fanout(body, allow_best_of=False)
    if top_n and stop_strs:
        raise HTTPError(
            400, "top-logprob alternatives with multi-token stop "
            'sequences are not supported; use "stop_token_ids"'
        )

    include_usage = _stream_usage_opt(body)  # validates even sans stream
    # flight record (rides a contextvar so the batcher/pool/device stamp
    # it downstream); the Flight guard owns ok/error/drop semantics
    from gofr_tpu.telemetry import flight

    with flight(
        getattr(ctx.container, "telemetry", None),
        model=model, endpoint="/v1/chat/completions",
        trace_id=ctx.trace_id or "", tokens_in=len(prompt_ids),
        stream=bool(body.get("stream")),
    ) as fl:
        if body.get("stream"):
            # defer: the record completes when the stream ends
            return fl.defer(_stream_chat(
                ctx, body, prompt_ids, max_tokens, sampler, stop_ids,
                stop_strs, want_logprobs, top_n, adapter, n, chat_id,
                created, model, tok, include_usage,
            ))
        results, generated = _fanout_generate(
            ctx, body, prompt_ids, max_tokens, sampler, stop_ids, stop_strs,
            want_logprobs, top_n, adapter, n, n,
        )
    from gofr_tpu.http.response import Raw

    choices = [
        {
            "index": i,
            "message": {
                "role": "assistant",
                "content": text if text is not None else tok.decode(out),
            },
            "finish_reason": (
                finish if finish is not None
                else ("length" if len(out) >= max_tokens else "stop")
            ),
            "logprobs": (
                _chat_logprobs_obj(tok, logprobs, out, tops, top_n)
                if logprobs is not None else None
            ),
        }
        for i, (out, logprobs, tops, text, finish) in enumerate(results)
    ]
    return Raw({
        "id": chat_id,
        "object": "chat.completion",
        "created": created,
        "model": model,
        "choices": choices,
        "usage": {
            "prompt_tokens": len(prompt_ids),
            "completion_tokens": generated,
            "total_tokens": len(prompt_ids) + generated,
        },
    })
