"""Response logprobs objects: the legacy completions shape
(token_logprobs/tokens/top_logprobs/text_offset) and the modern chat
``content`` entries with true token bytes."""

from __future__ import annotations

from typing import Any


def _logprobs_obj(
    tok: Any, lp_list: list, lp_ids: list, tops: Any, top_n: int,
    prompt_positions: int = 0,
) -> dict:
    """The choice-level logprobs object: token_logprobs always; a
    ``tokens`` list (single-token decodes, or stringified ids without a
    tokenizer) aligned with it; ``text_offset`` — each token's character
    start within the choice text, the field eval harnesses use to locate
    the prompt/continuation boundary under echo; and, when ``top_n`` > 0,
    per-position ``top_logprobs`` maps of the N best alternatives (null
    for echoed prompt positions — the prompt is scored chosen-only)."""

    def key(t: int) -> str:
        return tok.decode([t]) if tok is not None else str(t)

    def alt_map(alts: list) -> dict:
        # distinct ids can decode to the same string; alts is best-first,
        # so keep the FIRST (best) value instead of letting a worse
        # duplicate overwrite it
        m: dict[str, float] = {}
        for i, v in alts[:top_n]:
            m.setdefault(key(i), v)
        return m

    # slice, never assume: a host-matched stop truncates lp_list to
    # the visible prefix while the ids keep the full generation for
    # usage accounting — tokens must stay ALIGNED with token_logprobs
    visible = lp_ids[: len(lp_list)]
    tokens = [key(t) for t in visible]
    # offsets come from the STREAM decoder, not the per-token decode
    # lengths: a byte-level BPE token can hold a fragment of a multi-byte
    # character, and only incremental decoding tiles the choice text the
    # response actually carries (per-token decode yields U+FFFD per
    # fragment and would shift every later offset)
    offsets: list[int] = []
    pos = 0
    if tok is not None:
        dec = tok.stream_decoder()
        for t in visible:
            offsets.append(pos)
            pos += len(dec.feed(t))
    else:
        for t in tokens:
            offsets.append(pos)
            pos += len(t)
    obj: dict[str, Any] = {
        "token_logprobs": lp_list,
        "tokens": tokens,
        "text_offset": offsets,
    }
    if top_n and tops is not None:
        obj["top_logprobs"] = (
            [None] * prompt_positions
            + [alt_map(alts) for alts in tops]
        )
    return obj


def _chat_lp_entry(tok: Any, token_id: int, lp: float) -> dict:
    """One {token, logprob, bytes} content entry. ``bytes`` carries the
    token's TRUE bytes (a byte-level BPE token can hold a fragment of a
    multi-byte character — the field exists so clients can reassemble
    text across such splits; round-tripping through the replaced string
    would corrupt them)."""
    raw = tok.decode_bytes([token_id])
    return {
        "token": raw.decode("utf-8", errors="replace"),
        "logprob": lp,
        "bytes": list(raw),
    }


def _chat_logprobs_obj(
    tok: Any, lp_list: list, out_ids: list, tops: Any, top_n: int,
) -> dict:
    """Chat logprobs in the CURRENT OpenAI chat shape — a ``content``
    list of {token, logprob, bytes, top_logprobs} entries that stock
    SDKs parse (top_logprobs is ALWAYS present, [] when no alternatives
    were requested — typed clients treat it as required) — alongside
    this server's legacy completions-style fields
    (token_logprobs/tokens/top_logprobs) for back-compat."""
    obj = _logprobs_obj(tok, lp_list, out_ids, tops, top_n)
    content = []
    for j, (t, lp) in enumerate(zip(out_ids[: len(lp_list)], lp_list)):
        e = _chat_lp_entry(tok, t, lp)
        e["top_logprobs"] = (
            [_chat_lp_entry(tok, i, v) for i, v in tops[j][:top_n]]
            if top_n and tops is not None else []
        )
        content.append(e)
    obj["content"] = content
    return obj
