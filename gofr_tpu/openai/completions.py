"""POST /v1/completions: prompt in, text out; SSE when streaming;
echo+logprobs teacher-forcing scoring; n/best_of fan-out."""

from __future__ import annotations

import time
import uuid
from typing import Any

from gofr_tpu.openai.fanout import _fanout_generate
from gofr_tpu.openai.logprobs import _logprobs_obj
from gofr_tpu.openai.parse import (
    _StopScanner,
    _parse_fanout,
    _parse_request,
    _prompt_tokens,
    _stream_usage_opt,
)

from gofr_tpu.errors import HTTPError


def _stream_completion(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, want_logprobs: bool, top_n: int,
    adapter: Any, n: int, best_of: int, echo: bool,
    cmpl_id: str, created: int, model: str, tok: Any,
    include_usage: bool = False, resume_from: int = 0,
) -> Any:
    """The SSE branch of /v1/completions: per-token text chunks with
    host-side stop matching, terminated by ``data: [DONE]``. ``n`` > 1
    streams candidates CONCURRENTLY as interleaved chunks carrying their
    ``index`` (the OpenAI shape): unseeded candidates share the decode
    pool, seeded ones derive per-candidate seeds, and deterministic
    (greedy) requests replicate one stream across every index — the
    non-stream fan-out's replication rule, so billing and content
    match it."""
    if best_of > n:
        raise HTTPError(
            400, '"best_of" > "n" is not supported when streaming '
            "(candidates cannot be ranked and discarded mid-stream)"
        )
    if max_tokens == 0:
        raise HTTPError(
            400, 'streaming needs "max_tokens" >= 1 (use the '
            "non-stream form for pure echo scoring)"
        )
    if top_n:
        raise HTTPError(
            400, "top-logprob alternatives are not supported when "
            "streaming; drop \"stream\" or request chosen-token "
            "logprobs only"
        )
    if resume_from:
        # resume (X-Resume-From) restores an interrupted stream at a
        # frame offset. n > 1 refuses outright: candidate interleaving
        # is thread-timing-dependent, so the frame sequence is not
        # reproducible and no resume strategy can splice it.
        if n > 1:
            raise HTTPError(
                400, "resume is not supported on n > 1 streams (the "
                "candidate interleave is not reproducible)"
            )
        # the SKIP-AHEAD shortcut (regenerate only positions >= k) is
        # sound only when each frame depends on its own token alone.
        # A tokenizer stream decoder and a stop-sequence scanner carry
        # cross-token state (partial UTF-8 bytes, a half-matched stop
        # string) that a mid-stream restart cannot rebuild, echo
        # prepends replay frames, and logprob values are not journaled
        # — those streams fall back to FULL regeneration from frame 0:
        # deterministic by the resume precondition, renumbered
        # identically, and the router's id filter drops the frames the
        # client already holds. Slower, never wrong.
        if echo or want_logprobs or stop_strs or tok is not None:
            resume_from = 0
    import json as _json

    from gofr_tpu.http.response import Stream

    def chunk(text: str, lp: Any = None, finish: Any = None,
              token: Any = None, index: int = 0) -> str:
        choice: dict[str, Any] = {
            "text": text, "index": index, "finish_reason": finish,
        }
        if token is not None:
            # no tokenizer: bare str(token) text would concatenate
            # ambiguously ("12"+"3" == "1"+"23") — ids ride a tokens
            # extension instead, matching the non-stream path
            choice["tokens"] = [token]
        if want_logprobs:
            choice["logprobs"] = (
                {"token_logprobs": [lp]} if lp is not None else None
            )
        frame = {
            "id": cmpl_id, "object": "text_completion",
            "created": created, "model": model, "choices": [choice],
        }
        if include_usage:
            frame["usage"] = None
        return _json.dumps(frame)

    def usage_frame(completion_tokens: int) -> str:
        from gofr_tpu.openai.fanout import _usage_chunk

        return _usage_chunk("text_completion", cmpl_id, created, model,
                            len(prompt_ids), completion_tokens)

    if n > 1:
        return _stream_completion_fanout(
            ctx, body, prompt_ids, max_tokens, sampler, stop_ids,
            stop_strs, want_logprobs, adapter, n, echo, chunk, tok,
            usage_frame if include_usage else None,
        )

    # constructed OUTSIDE events(): parameter errors (unknown adapter,
    # bad sampler) must 400 before the SSE 200 commits. resume_from is
    # clamped to the token budget: a client interrupted between the
    # last token frame and [DONE] resumes straight into the tail
    from gofr_tpu.openai.parse import _abortable

    cancel, on_abort = _abortable(ctx)
    stream_iter = ctx.tpu.generate_stream(
        prompt_ids, max_tokens, sampler=sampler, stop_tokens=stop_ids,
        adapter=adapter, logprobs=want_logprobs,
        resume_from=min(resume_from, max_tokens), cancel=cancel,
    )

    def events():
        # a resumed stream's token iterator starts at the resume
        # position; the emitted counter must keep counting ABSOLUTE
        # positions or finish_reason ("length" vs "stop") would drift
        # from the uninterrupted run's
        emitted = min(resume_from, max_tokens)
        finish = None
        dec = tok.stream_decoder() if tok is not None else None
        # stop_strs imply a tokenizer (enforced at parse), so dec
        # is always live when the scanner is
        scan = _StopScanner(stop_strs) if stop_strs else None
        try:
            if echo:
                # prompt replay first, matching the non-stream shape
                if dec is not None:
                    yield chunk(tok.decode(prompt_ids))
                else:
                    for t in prompt_ids:
                        yield chunk("", token=t)
            for item in stream_iter:
                token, lp = item if want_logprobs else (item, None)
                emitted += 1
                if dec is None:
                    yield chunk("", lp, token=token)
                    continue
                text = dec.feed(token)
                if scan is not None:
                    text, done = scan.feed(text)
                    if done:
                        # matched mid-stream: emit up to the stop and
                        # cancel the decode (frees the pool slot). No
                        # lp: the matched token's text is excluded, so
                        # its logprob must not ride this chunk either
                        yield chunk(text, None)
                        finish = "stop"
                        break
                yield chunk(text, lp)
            tail = dec.flush() if dec is not None else ""
            if finish is None:
                if scan is not None:
                    tail, done = scan.feed(tail)
                    if done:
                        finish = "stop"
                    else:
                        tail += scan.flush()
                if finish is None:
                    finish = "length" if emitted >= max_tokens else "stop"
            else:
                tail = ""
            yield chunk(tail, None, finish)
            if include_usage:
                yield usage_frame(emitted)
            yield "[DONE]"
        except Exception as exc:
            yield _json.dumps({"error": {"message": str(exc)}})
        finally:
            stream_iter.close()  # no-op if already exhausted

    # ids=True: every frame carries its monotonic SSE id (anchored at
    # the resume offset), making the stream resumable through the fleet
    # router's journal — see docs/advanced-guide/fleet.md
    return Stream(events(), ids=True, id_offset=resume_from,
                  on_abort=on_abort)


def _stream_completion_fanout(
    ctx: Any, body: dict, prompt_ids: list, max_tokens: int, sampler: Any,
    stop_ids: Any, stop_strs: list, want_logprobs: bool, adapter: Any,
    n: int, echo: bool, chunk: Any, tok: Any, usage_frame: Any = None,
) -> Any:
    """Interleaved multi-index SSE: n candidates stream concurrently,
    each chunk carrying its choice ``index``. Deterministic (greedy)
    requests run ONE stream replicated across indexes. The shared
    driver (_drive_stream_fanout) owns the replicate/multiplex loops,
    stop-cancellation, and cleanup; this function supplies only the
    completions frame shapes."""
    import json as _json

    from gofr_tpu.http.response import Stream
    from gofr_tpu.openai.fanout import (
        _drive_stream_fanout,
        _index_feed_text,
        _index_tail_text,
        _stream_candidates,
    )
    from gofr_tpu.openai.parse import _abortable, _StopScanner

    replicate = sampler.greedy
    cancel, on_abort = _abortable(ctx)
    iters = _stream_candidates(
        ctx, body, prompt_ids, max_tokens, sampler, stop_ids, adapter,
        want_logprobs, 1 if replicate else n, cancel=cancel,
    )
    decs = [tok.stream_decoder() if tok is not None else None
            for _ in range(n)]
    scans = [_StopScanner(stop_strs) if stop_strs else None
             for _ in range(n)]
    emitted = [0] * n
    finish: list = [None] * n

    def open_frames():
        if not echo:
            return
        for i in range(n):
            if tok is not None:
                yield chunk(tok.decode(prompt_ids), index=i)
            else:
                for t in prompt_ids:
                    yield chunk("", token=t, index=i)

    def feed(i, token, lp):
        text, stopped = _index_feed_text(
            decs[i], scans[i], finish, i, emitted, token
        )
        if text is None:  # id-only deployment: tokens extension
            return [chunk("", lp, token=token, index=i)]
        if stopped:  # the matched token's lp is excluded with its text
            return [chunk(text, None, index=i)]
        return [chunk(text, lp, index=i)]

    def tail(i):
        t = _index_tail_text(decs[i], scans[i], finish, i, emitted,
                             max_tokens)
        return [chunk(t, None, finish[i], index=i)]

    def error_frame(exc):
        return _json.dumps({"error": {"message": str(exc)}})

    usage_frames = (
        (lambda: [usage_frame(sum(emitted))])
        if usage_frame is not None else None
    )
    return Stream(
        _drive_stream_fanout(
            iters, replicate, n, finish, want_logprobs, open_frames, feed,
            tail, error_frame, usage_frames,
        ),
        on_abort=on_abort,
    )


def completions(ctx: Any) -> Any:
    (body, max_tokens, sampler, stop_ids, stop_strs, want_logprobs, top_n,
     adapter) = _parse_request(ctx, default_max=16)
    n, best_of, echo = _parse_fanout(body, allow_best_of=True)
    if echo and want_logprobs and body.get("stream"):
        raise HTTPError(
            400, '"echo" with "logprobs" is not supported when streaming'
        )
    if top_n and stop_strs:
        raise HTTPError(
            400, "top-logprob alternatives with multi-token stop "
            'sequences are not supported; use "stop_token_ids"'
        )
    if "prompt" not in body:
        # a missing prompt is almost always a caller bug (misspelled key):
        # generating from a magic default would 200 on garbage
        raise HTTPError(400, 'missing "prompt"')
    prompt_ids = _prompt_tokens(ctx, body["prompt"])
    model = adapter or ctx.tpu.model_name  # adapters serve under their name
    # gofrlint: wall-clock — OpenAI API `created` is epoch seconds by contract
    created = int(time.time())
    cmpl_id = f"cmpl-{uuid.uuid4().hex[:24]}"
    tok = ctx.tpu.tokenizer

    include_usage = _stream_usage_opt(body)  # validates even sans stream
    # flight record (rides a contextvar so the batcher/pool/device stamp
    # it downstream); the Flight guard owns ok/error/drop semantics
    from gofr_tpu.telemetry import flight

    with flight(
        getattr(ctx.container, "telemetry", None),
        model=model, endpoint="/v1/completions",
        trace_id=ctx.trace_id or "", tokens_in=len(prompt_ids),
        stream=bool(body.get("stream")),
    ) as fl:
        if body.get("stream"):
            # X-Resume-From: the fleet router (or a reconnecting
            # client) holds frames 0..k-1 of an interrupted stream and
            # asks for the rest — journal-backed teacher-forced resume
            # when this replica served the original, deterministic
            # replay otherwise (device.generate_stream owns the rules)
            resume_from = 0
            raw_resume = ctx.request.header("X-Resume-From")
            if raw_resume:
                try:
                    resume_from = int(raw_resume)
                except ValueError:
                    raise HTTPError(
                        400, '"X-Resume-From" must be an integer frame '
                        "offset"
                    ) from None
                if resume_from < 0:
                    raise HTTPError(400, '"X-Resume-From" must be >= 0')
            # defer: the record completes when the stream ends
            return fl.defer(_stream_completion(
                ctx, body, prompt_ids, max_tokens, sampler, stop_ids,
                stop_strs, want_logprobs, top_n, adapter, n, best_of, echo,
                cmpl_id, created, model, tok, include_usage, resume_from,
            ))

        prompt_lps = None
        if echo and want_logprobs:
            # teacher-forcing prompt scoring: log p(t_i | t_<i), with null
            # for the first token (no conditional) — the OpenAI convention
            # and the eval-harness loglikelihood pattern. The request's
            # adapter scores too (and an unknown one 400s even on the
            # max_tokens=0 path, where no generation would catch it)
            prompt_lps = [None] + ctx.tpu.score(prompt_ids, adapter=adapter)
        elif max_tokens == 0 and adapter is not None:
            # pure echo without logprobs still must validate the adapter name.
            # list_adapters (not a direct runner read): it waits for readiness,
            # so a request landing mid background-boot blocks like every other
            # path instead of 500ing on a not-yet-built runner
            loaded = ctx.tpu.list_adapters()
            if adapter not in loaded:
                from gofr_tpu.errors import InvalidParamError

                raise InvalidParamError(
                    f"adapter '{adapter}' (loaded: {loaded})"
                )
        if max_tokens == 0:
            # pure scoring (echo-only, enforced at parse): no decode at all
            results = [
                ([], [] if want_logprobs else None, [] if top_n else None,
                 None, "length")
            ] * n
            generated = 0
        else:
            results, generated = _fanout_generate(
                ctx, body, prompt_ids, max_tokens, sampler, stop_ids, stop_strs,
                want_logprobs, top_n, adapter, n, best_of,
            )
    choices = []
    for i, (out, logprobs, tops, text, finish) in enumerate(results):
        if text is None:
            text_ids = (prompt_ids + out) if echo else out
            text_val = tok.decode(text_ids) if tok is not None else ""
            finish = "length" if len(out) >= max_tokens else "stop"
        else:
            # host-matched stop truncation: the scanner's text IS the
            # completion (a tokenizer is guaranteed on this path, so the
            # tokens extension below never applies); echo prepends the
            # decoded prompt
            text_val = (tok.decode(prompt_ids) + text) if echo else text
        lp_list = logprobs
        lp_ids = out
        if prompt_lps is not None:
            lp_list = prompt_lps + (logprobs or [])
            lp_ids = prompt_ids + out
        lp_obj = None
        if lp_list is not None:
            lp_obj = _logprobs_obj(
                tok, lp_list, lp_ids, tops, top_n,
                prompt_positions=len(prompt_ids) if prompt_lps is not None
                else 0,
            )
        choice: dict[str, Any] = {
            "text": text_val,
            "index": i,
            "finish_reason": finish,
            "logprobs": lp_obj,
        }
        if tok is None:
            choice["tokens"] = (prompt_ids + out) if echo else out
        choices.append(choice)
    from gofr_tpu.http.response import Raw

    # OpenAI clients expect the completion object at the top level, not
    # inside this framework's {"data": ...} envelope
    return Raw({
        "id": cmpl_id,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": choices,
        "usage": {
            "prompt_tokens": len(prompt_ids),
            "completion_tokens": generated,
            "total_tokens": len(prompt_ids) + generated,
        },
    })
