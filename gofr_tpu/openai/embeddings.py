"""POST /v1/embeddings (encoder models through the dynamic batcher)
and GET /v1/models (base model + loaded LoRA adapters)."""

from __future__ import annotations

from typing import Any

from gofr_tpu.errors import HTTPError

async def embeddings(ctx: Any) -> Any:
    """OpenAI embeddings shape over an encoder model (MODEL_NAME=bert-*).
    ``input`` is a string, list of strings, token-id list, or list of
    id lists; items run through the dynamic batcher CONCURRENTLY, so a
    multi-item request packs into one device dispatch."""
    import asyncio

    if ctx.tpu is None:
        raise HTTPError(503, "tpu not configured (set MODEL_NAME)")
    if not ctx.tpu.model_name.startswith("bert"):
        # checked BEFORE any inference: a decoder deployment must 400 for
        # free, not run (and cache) a full prefill per item first
        raise HTTPError(
            400,
            "embeddings need an encoder model (MODEL_NAME=bert-tiny or "
            f"bert-base); '{ctx.tpu.model_name}' is a decoder",
        )
    body = ctx.bind() if ctx.request.body else {}
    if not isinstance(body, dict):
        raise HTTPError(400, "request body must be a JSON object")
    raw = body.get("input")
    if isinstance(raw, str) or (
        isinstance(raw, list) and raw and all(isinstance(t, int) for t in raw)
    ):
        items = [raw]
    elif isinstance(raw, list) and raw:
        items = raw
    else:
        raise HTTPError(
            400,
            '"input" must be a string, list of strings, or token-id list(s)',
        )
    tok = ctx.tpu.tokenizer
    # the encoder pads/slices to one fixed bucket: over-long input must
    # 400 (OpenAI behavior), never silently embed a truncated prefix
    # while usage reports the full count. wait_ready: the bucket lives on
    # the runner, which a background boot builds late.
    ctx.tpu.wait_ready(60.0)
    bucket = getattr(ctx.tpu.runner, "bucket", None)

    def tokenize_items() -> tuple[int, list]:
        """CPU-bound BPE over possibly many strings — runs in the
        executor below, never on the event loop (the async handler
        contract: the loop is for enqueueing, not computing)."""
        n = 0
        payloads = []
        for item in items:
            if isinstance(item, str):
                if tok is None:
                    raise HTTPError(
                        400,
                        "string input needs a tokenizer (set TOKENIZER_PATH)",
                    )
                ids = tok.encode(item)
            elif isinstance(item, list) and item and all(
                isinstance(t, int) for t in item
            ):
                ids = item
            else:
                raise HTTPError(400, f"invalid input item: {item!r:.80}")
            if not ids:
                raise HTTPError(400, "input item encoded to zero tokens")
            if bucket is not None and len(ids) > bucket:
                raise HTTPError(
                    400,
                    f"input item is {len(ids)} tokens; this encoder "
                    f"accepts at most {bucket}",
                )
            n += len(ids)
            payloads.append({"tokens": ids})
        return n, payloads

    loop = asyncio.get_running_loop()
    n_tokens, payloads = await loop.run_in_executor(None, tokenize_items)
    results = await asyncio.gather(
        *(ctx.tpu.infer_async(p) for p in payloads)
    )

    def to_rows() -> list:
        import numpy as np

        return [
            {
                "object": "embedding",
                "index": i,
                "embedding": np.asarray(out).reshape(-1).tolist(),
            }
            for i, out in enumerate(results)
        ]

    data = await loop.run_in_executor(None, to_rows)
    from gofr_tpu.http.response import Raw

    return Raw({
        "object": "list",
        "model": ctx.tpu.model_name,
        "data": data,
        "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
    })


def list_models(ctx: Any) -> Any:
    if ctx.tpu is None:
        raise HTTPError(503, "tpu not configured (set MODEL_NAME)")
    from gofr_tpu.http.response import Raw

    # the base model plus every loaded LoRA adapter: gateways route by
    # model name, and a request's "model" naming an adapter selects it
    # (the multi-LoRA serving convention) — stock OpenAI clients cannot
    # send the custom "adapter" key, but they can set model
    entries = [{
        "id": ctx.tpu.model_name,
        "object": "model",
        "owned_by": "gofr_tpu",
    }]
    # non-blocking snapshot: discovery must answer instantly during a
    # background boot (list_adapters would wait for readiness)
    adapters = getattr(getattr(ctx.tpu, "runner", None), "adapters", None) or {}
    for name in sorted(adapters):
        entries.append({
            "id": name,
            "object": "model",
            "owned_by": "gofr_tpu",
            "root": ctx.tpu.model_name,  # the base it adapts
        })
    return Raw({"object": "list", "data": entries})
