"""OpenAI-compatible completions surface over the TPU datasource.

Not a reference-parity component (GoFr has no LLM API) — a TPU-native
addition so clients speaking the de-facto completions protocol (SDKs,
load-testing harnesses, gateway routers) can hit this framework without a
translation shim. ``register_openai_routes(app)`` adds:

- ``POST /v1/completions`` — prompt in, text out; ``"stream": true``
  switches to SSE chunks terminated by ``data: [DONE]``.
- ``POST /v1/chat/completions`` — messages in, assistant message out
  (requires a tokenizer; the prompt is rendered through CHAT_TEMPLATE,
  default ``[{role}]: {content}\\n`` per message, and the assistant-turn
  opener is everything the template puts BEFORE {content} — override
  with CHAT_TEMPLATE_OPENER for formats that need more).
- ``POST /v1/embeddings`` — encoder models (MODEL_NAME=bert-*); multi-
  item inputs pack into one batcher dispatch.
- ``GET /v1/models`` — the served base model plus loaded LoRA adapters.

Scope: the completions shape (prompt string or token list, max_tokens,
temperature/top_p/seed, penalties/logit_bias, n/best_of/echo fan-out,
stop, logprobs, usage accounting). ``stop`` takes up to 4 sequences:
single-token encodings stop on-device, and every sequence is ALSO
matched host-side against the rolling decoded text (``_StopScanner``),
so multi-token stops and cross-token-boundary occurrences truncate
correctly; ``stop_token_ids`` takes raw ids. Knobs this server cannot
honor are a clear 400, never a silent ignore.

Module layout (each under 500 lines by policy): ``parse`` (request
knobs, stops, fan-out constraints), ``template`` (chat prompt
construction), ``logprobs`` (response logprob objects), ``fanout``
(candidate generation + streaming consumer), ``completions`` / ``chat``
/ ``embeddings`` (the endpoints).
"""

from __future__ import annotations

from typing import Any

from gofr_tpu.openai.chat import chat_completions
from gofr_tpu.openai.completions import completions
from gofr_tpu.openai.embeddings import embeddings, list_models
from gofr_tpu.openai.template import render_chat_prompt

__all__ = [
    "register_openai_routes",
    "completions",
    "chat_completions",
    "embeddings",
    "list_models",
    "render_chat_prompt",
]


def register_openai_routes(app: Any) -> None:
    app.post("/v1/completions", completions)
    app.post("/v1/chat/completions", chat_completions)
    app.post("/v1/embeddings", embeddings)
    app.get("/v1/models", list_models)
