"""Chat prompt construction: the simple {role}/{content} CHAT_TEMPLATE
form, jinja templates (CHAT_TEMPLATE_JINJA or the checkpoint's own
tokenizer_config.json chat_template), and the assistant-turn opener."""

from __future__ import annotations

import functools
from typing import Any

from gofr_tpu.errors import HTTPError

DEFAULT_CHAT_TEMPLATE = "[{role}]: {content}\n"

_SENTINEL = "\x00GOFR_CONTENT\x00"


def _chat_template(ctx: Any) -> tuple[str, str]:
    """(template, assistant opener), both validated — a broken operator
    template must be a clear error, not a per-request 500 from str.format
    or silently dropped message content. The opener is everything the
    template renders BEFORE the content slot for role=assistant (correct
    for markup-wrapped formats like ChatML, where stripping trailing
    newlines would emit a CLOSED empty assistant turn); override with
    CHAT_TEMPLATE_OPENER when a format needs something else."""
    template = ctx.config.get_or_default("CHAT_TEMPLATE", DEFAULT_CHAT_TEMPLATE)
    try:
        probe = template.format(role="assistant", content=_SENTINEL)
    except (KeyError, IndexError, ValueError) as exc:
        raise HTTPError(
            500,
            f"CHAT_TEMPLATE is invalid ({exc!r}) — it must use only "
            "{role} and {content} placeholders",
        )
    if _SENTINEL not in probe:
        raise HTTPError(
            500, "CHAT_TEMPLATE must contain a {content} placeholder"
        )
    opener = ctx.config.get_or_default(
        "CHAT_TEMPLATE_OPENER", probe.split(_SENTINEL)[0]
    )
    return template, opener


def _jinja_template_source(ctx: Any) -> Any:
    """The jinja chat template to use, or None for the simple
    CHAT_TEMPLATE path. Precedence: CHAT_TEMPLATE_JINJA (a file path or
    an inline template) > an explicit CHAT_TEMPLATE or
    CHAT_TEMPLATE_OPENER (either means the operator chose the simple
    form — a customized opener must never be silently ignored) > the
    checkpoint's own tokenizer_config.json chat_template next to
    TOKENIZER_PATH — serving a real instruct checkpoint through the
    wrong template silently degrades it, so the official template is
    adopted automatically. Resolution (incl. the file reads) is cached:
    config is static per process, and per-request disk I/O on the chat
    handler thread is waste."""
    return _resolve_jinja_source(
        ctx.config.get("CHAT_TEMPLATE_JINJA") or "",
        bool(ctx.config.get("CHAT_TEMPLATE"))
        or bool(ctx.config.get("CHAT_TEMPLATE_OPENER")),
        ctx.config.get("TOKENIZER_PATH") or "",
    )


@functools.lru_cache(maxsize=8)
def _resolve_jinja_source(
    explicit: str, simple_form: bool, tok_path: str
) -> Any:
    import os

    if explicit:
        if os.path.isfile(explicit):
            with open(explicit, encoding="utf-8") as fh:
                return fh.read()
        return explicit
    if simple_form:
        return None
    if tok_path.endswith(".json"):
        cfg_path = os.path.join(
            os.path.dirname(tok_path), "tokenizer_config.json"
        )
        if os.path.isfile(cfg_path):
            import json as _json

            try:
                with open(cfg_path, encoding="utf-8") as fh:
                    template = _json.load(fh).get("chat_template")
            except (OSError, ValueError) as exc:
                # a corrupt checkpoint sidecar silently falling back to
                # the generic template is EXACTLY the degradation this
                # discovery exists to prevent — fail loudly instead
                raise HTTPError(
                    500, f"cannot read {cfg_path}: {exc} — fix the "
                    "checkpoint or set CHAT_TEMPLATE explicitly"
                )
            if template is None:
                return None
            if isinstance(template, str):
                return template
            if isinstance(template, list):
                # HF multi-template form: [{"name": ..., "template": ...}]
                # — only an entry NAMED "default" is safe to adopt;
                # guessing template[0] could silently serve every chat
                # request through e.g. the tool_use template
                for entry in template:
                    if (
                        isinstance(entry, dict)
                        and entry.get("name") == "default"
                        and isinstance(entry.get("template"), str)
                    ):
                        return entry["template"]
            raise HTTPError(
                500, f"unrecognized chat_template form in {cfg_path} — "
                "set CHAT_TEMPLATE or CHAT_TEMPLATE_JINJA explicitly"
            )
    return None


@functools.lru_cache(maxsize=8)
def _compiled_jinja(source: str) -> Any:
    """Compile once per template source (config is static per process).
    The HF convention: an IMMUTABLE SANDBOXED environment — checkpoint
    templates are data, not trusted code."""
    try:
        from jinja2.sandbox import ImmutableSandboxedEnvironment
    except ImportError:
        raise HTTPError(
            500, "jinja chat templates need the jinja2 package "
            "(declared in pyproject; pip install jinja2) — or set "
            "CHAT_TEMPLATE to use the simple template form"
        ) from None

    env = ImmutableSandboxedEnvironment(trim_blocks=True, lstrip_blocks=True)

    def raise_exception(message: str) -> None:
        from jinja2.exceptions import TemplateError

        raise TemplateError(message)

    env.globals["raise_exception"] = raise_exception
    return env.from_string(source)


def _render_jinja(ctx: Any, source: str, messages: list) -> str:
    from jinja2.exceptions import TemplateError

    tok = ctx.tpu.tokenizer if ctx.tpu is not None else None
    specials = {"bos_token": "", "eos_token": ""}
    if tok is not None:
        ids = getattr(tok, "_special_ids", {})
        for content, ext_id in getattr(tok, "_token_ids", {}).items():
            for name in ("bos", "eos"):
                if ids.get(name) == ext_id:
                    specials[f"{name}_token"] = content
    try:
        return _compiled_jinja(source).render(
            messages=messages, add_generation_prompt=True, **specials
        )
    except TemplateError as exc:
        # an operator/checkpoint template problem, surfaced clearly —
        # never a bare per-request 500
        raise HTTPError(500, f"chat template failed to render: {exc}")


def render_chat_prompt(ctx: Any, messages: Any) -> str:
    """Messages -> prompt text. Jinja templates (CHAT_TEMPLATE_JINJA, or
    the checkpoint's own tokenizer_config.json chat_template) render
    with the HF conventions (``messages``, ``add_generation_prompt``,
    ``bos_token``/``eos_token``, sandboxed environment); otherwise the
    simple CHAT_TEMPLATE ({role}/{content} per message) + the assistant
    turn opener applies."""
    if not isinstance(messages, list) or not messages:
        raise HTTPError(400, '"messages" must be a non-empty list')
    for m in messages:
        if (
            not isinstance(m, dict)
            or not isinstance(m.get("role"), str)
            or not isinstance(m.get("content"), str)
        ):
            raise HTTPError(
                400,
                'each message must be {"role": str, "content": str}',
            )
    jinja_src = _jinja_template_source(ctx)
    if jinja_src is not None:
        return _render_jinja(ctx, jinja_src, messages)
    template, opener = _chat_template(ctx)
    parts = [
        template.format(role=m["role"], content=m["content"])
        for m in messages
    ]
    return "".join(parts) + opener
