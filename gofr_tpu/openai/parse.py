"""Request parsing shared by every OpenAI endpoint: prompts, stop
sequences (device ids + host-matched strings), sampling knobs, the
shared knob parse, n/best_of/echo fan-out constraints, and the
deadline/priority/brownout admission gate."""

from __future__ import annotations

from typing import Any

from gofr_tpu.errors import HTTPError, TooManyRequestsError


def _admit_request(ctx: Any, max_tokens: int) -> int:
    """Deadline + priority + brownout admission, shared by both OpenAI
    endpoints (one home — the chat/completions copies drifted once).

    Parses ``X-Request-Deadline-Ms`` (default ``REQUEST_DEADLINE_S``;
    0/absent with no header = no deadline, today's behavior) and
    ``X-Priority`` (default ``PRIORITY_DEFAULT``), activates the
    deadline contextvar so the batcher/pool/device stages read the same
    absolute budget, and consults the engine's brownout controller:
    under brownout, below-floor priorities 429 with a Retry-After and
    level 2 may clamp ``max_tokens``. Returns the (possibly clamped)
    ``max_tokens``."""
    from gofr_tpu.deadline import (
        PRIORITY_DEFAULT,
        activate_deadline,
        activate_priority,
        parse_deadline,
        parse_priority,
    )

    config = ctx.config
    default_priority = int(
        config.get_or_default("PRIORITY_DEFAULT", str(PRIORITY_DEFAULT))
    )
    priority = parse_priority(
        ctx.request.header("X-Priority"), default=default_priority
    )
    activate_priority(priority)
    default_deadline_s = float(
        config.get_or_default("REQUEST_DEADLINE_S", "0")
    )
    deadline = parse_deadline(
        ctx.request.header("X-Request-Deadline-Ms"),
        default_deadline_s, priority=priority,
    )
    activate_deadline(deadline)
    # KV-donor hint (disaggregated prefill/decode): the fleet router
    # stamps the replica likely holding this prompt's warm paged-KV
    # blocks; the device pulls them before admission. Travels like the
    # deadline — a contextvar read once by TPU.generate. A malformed
    # hint degrades to local prefill, never to a 4xx — and the device
    # acts on it only under KV_TRANSFER_TRUST_HINT=on (the hint names
    # a URL the replica will fetch into its shared prefix cache).
    from gofr_tpu.fleet.kvwire import activate_kv_hint, parse_kv_hint

    activate_kv_hint(parse_kv_hint(ctx.request.header("X-KV-Donor")))
    # fleet origin: the router-stamped request id + hop block travel the
    # same way — a contextvar the FlightRecord reads at start, so the
    # replica-side record joins the router's route record on
    # /admin/fleet/trace/<id>. Garbage headers degrade to no origin.
    from gofr_tpu.telemetry import activate_origin, origin_from_headers

    activate_origin(origin_from_headers(
        ctx.request.header("X-Gofr-Request-Id"),
        ctx.request.header("X-Gofr-Hop"),
    ))
    # hashed tenant id (same derivation as the router's admission gate:
    # X-Tenant only under FLEET_TRUST_TENANT_HEADER, else a sha256 of
    # the Authorization credential — raw keys never leave this frame):
    # rides a contextvar onto the FlightRecord, so per-tenant usage
    # meters on replicas too, router or not
    from gofr_tpu.fleet.admission import tenant_of
    from gofr_tpu.telemetry import activate_tenant

    tenant = tenant_of(
        ctx.request,
        config.get_or_default(
            "FLEET_TRUST_TENANT_HEADER", ""
        ).lower() in ("on", "1", "true", "yes"),
    )
    activate_tenant(tenant)
    brownout = getattr(ctx.tpu, "brownout", None)
    if brownout is not None:
        admitted, max_tokens, level = brownout.admit(priority, max_tokens)
        if not admitted:
            # the shed never makes a flight record, so the tenant ledger
            # meters it here; the 429 body echoes the hashed tenant id
            # so a shed client can quote the exact id /admin/tenants and
            # /admin/requests?tenant= rank it under
            tenants = getattr(ctx.container, "tenants", None)
            if tenants is not None and tenant:
                tenants.shed(tenant)
            exc = TooManyRequestsError(
                f"shed by overload brownout (level {level}, request "
                f"priority {priority}); retry later or raise X-Priority"
            )
            exc.retry_after_s = 1.0
            exc.tenant = tenant
            raise exc
    return max_tokens


def _abortable(ctx: Any) -> tuple:
    """One streaming generation's client-abort wiring, shared by every
    stream builder in chat.py/completions.py (four hand-rolled copies
    of this block once existed — same drift hazard the admission gate
    docstring records): a fresh cancel event (pass it to
    ``generate_stream`` / every fan-out candidate — the responder's
    on_abort hook trips it on a write failure so an abandoned stream
    frees its decode slot and KV within one chunk) and the matching
    ``Stream.on_abort`` callable. Returns ``(cancel, on_abort)``."""
    import threading

    from gofr_tpu.telemetry import current_record

    cancel = threading.Event()
    return cancel, _client_abort_hook(ctx, cancel, current_record())


def _client_abort_hook(ctx: Any, cancel: Any, record: Any) -> Any:
    """The Stream.on_abort callable for one streaming generation: trips
    the request's stop event (the decode loop then frees its slot and
    KV within one chunk), counts the abort, and finishes the flight
    record as cancelled (idempotent — a normally-finished stream's
    record already completed)."""
    from gofr_tpu.deadline import cancellations_counter

    container = ctx.container
    counter = cancellations_counter(container.metrics)
    telemetry = getattr(container, "telemetry", None)

    def on_abort() -> None:
        cancel.set()
        if getattr(container, "closing", False):
            # process shutdown acloses every in-flight response
            # generator: still free the compute, but a restart must not
            # masquerade as a spike of phantom client aborts
            return
        counter.inc(cause="client_abort")
        if telemetry is not None and record is not None:
            telemetry.finish(record, status="cancelled")

    return on_abort


def _prompt_tokens(ctx: Any, prompt: Any) -> list[int]:
    if isinstance(prompt, str):
        tok = ctx.tpu.tokenizer
        if tok is None:
            raise HTTPError(
                400,
                "string prompt needs a tokenizer (set TOKENIZER_PATH); "
                "token-id lists work without one",
            )
        ids = tok.encode(prompt)
        if not ids:
            raise HTTPError(400, "prompt encoded to zero tokens")
        return ids
    if (
        isinstance(prompt, list) and prompt
        and all(isinstance(t, int) for t in prompt)
    ):
        return prompt
    raise HTTPError(
        400, '"prompt" must be a non-empty string or list of token ids'
    )


def _parse_stops(ctx: Any, body: dict) -> tuple[frozenset, list]:
    """(on-device stop token ids, host-matched stop strings). A stop
    string that encodes to ONE token stops on-device (cheapest — the
    decode chunk never emits it); multi-token strings are matched
    host-side against the decoded text as it streams off the device."""
    ids = set()
    raw_ids = body.get("stop_token_ids")
    if raw_ids is not None:
        if not isinstance(raw_ids, list) or not all(
            isinstance(t, int) for t in raw_ids
        ):
            raise HTTPError(400, '"stop_token_ids" must be a list of ints')
        ids.update(raw_ids)
    stop = body.get("stop")
    if stop is None:
        return frozenset(ids), []
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or not all(
        isinstance(s, str) and s for s in stop
    ):
        raise HTTPError(400, '"stop" must be a non-empty string or list of them')
    if len(stop) > 4:
        raise HTTPError(400, '"stop" accepts at most 4 sequences (OpenAI limit)')
    tok = ctx.tpu.tokenizer
    if tok is None:
        raise HTTPError(400, '"stop" strings need a tokenizer; use "stop_token_ids"')
    strings = []
    for s in stop:
        encoded = tok.encode(s)
        if len(encoded) == 1:
            # on-device stop for the exact-token emission (cheapest), but
            # ALSO host-matched: the same text can arrive via a different
            # tokenization (" the" as " t"+"he", or inside a larger
            # token), which only the text scan catches
            ids.add(encoded[0])
        strings.append(s)
    return frozenset(ids), strings


class _StopScanner:
    """Incremental multi-token stop matching with SSE hold-back:
    ``feed`` returns (emit, done) where ``emit`` never contains a stop
    string NOR a tail that could still grow into one — a stream must not
    leak half a stop sequence it would have had to un-send."""

    def __init__(self, stops: list):
        self.stops = stops
        self.buf = ""
        self.consumed = 0  # total chars fed
        self.match_pos = None  # absolute offset of the matched stop

    def feed(self, text: str) -> tuple[str, bool]:
        self.buf += text
        self.consumed += len(text)
        hits = [p for p in (self.buf.find(s) for s in self.stops) if p >= 0]
        if hits:
            idx = min(hits)
            self.match_pos = self.consumed - len(self.buf) + idx
            return self.buf[:idx], True
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self.buf)), 0, -1):
                if self.buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        cut = len(self.buf) - hold
        emit, self.buf = self.buf[:cut], self.buf[cut:]
        return emit, False

    def flush(self) -> str:
        """End of stream: held-back text can no longer become a stop."""
        emit, self.buf = self.buf, ""
        return emit


def _sampler(body: dict) -> Any:
    from gofr_tpu.ops.sampling import Sampler

    try:
        # pass the WHOLE body through the shared parse so every natively
        # supported knob (top_k, min_p, repetition_penalty, seed) works
        # here too — only the defaults differ: OpenAI semantics default
        # to temperature 1.0 (the native /generate defaults to greedy).
        # Explicit nulls are stripped BEFORE the merge so "temperature":
        # null falls back to the OpenAI default here, not from_body's
        # greedy default (the OpenAI fields are nullable).
        return Sampler.from_body({
            "temperature": 1.0, "top_p": 1.0,
            **{k: v for k, v in body.items() if v is not None},
        })
    except (TypeError, ValueError) as exc:
        raise HTTPError(400, f"invalid sampling params: {exc}")


def _parse_request(ctx: Any, default_max: int) -> tuple:
    """Shared request parse for both endpoints: (body, max_tokens,
    sampler, stop_ids, stop_strs, want_logprobs, top_n, adapter). One
    home, so a knob added
    to completions cannot silently miss chat (they drifted once)."""
    if ctx.tpu is None:
        raise HTTPError(503, "tpu not configured (set MODEL_NAME)")
    body = ctx.bind() if ctx.request.body else {}
    if not isinstance(body, dict):
        raise HTTPError(400, "request body must be a JSON object")
    # protocol knobs this server does not implement must be a clear 400
    # when they would change output — never a silent ignore.
    # presence/frequency penalties and logit_bias run on-device via the
    # penalized decode chunk; n/best_of/echo are handled by the
    # completions fan-out (_parse_fanout).
    if body.get("suffix") is not None:
        raise HTTPError(400, '"suffix" is not supported by this server')
    # tool calling and modality knobs would change what the model is
    # ASKED to do — silently ignoring them serves wrong output to a
    # client that believes its tools were offered
    for key in ("tools", "tool_choice", "functions", "function_call",
                "modalities", "audio", "prediction"):
        value = body.get(key)
        if value is None:
            continue
        if key == "tool_choice" and value == "none":
            continue  # the documented no-tools default: a semantic no-op
        raise HTTPError(
            400, f'"{key}" is not supported by this server'
        )
    rf = body.get("response_format")
    if rf is not None:
        # {"type": "text"} is the documented default — honoring it is a
        # no-op; constrained JSON output is not implemented, and a
        # client trusting json_object/json_schema would parse free text
        if not (isinstance(rf, dict) and rf.get("type") == "text"):
            raise HTTPError(
                400, '"response_format" types other than "text" are not '
                "supported by this server (no constrained decoding)"
            )
    # nullable like the sampling knobs: explicit JSON null = the default.
    # max_tokens=0 is legal ONLY with echo (pure prompt scoring, the
    # eval-harness loglikelihood pattern) — without echo it would return
    # nothing at all
    max_tokens = body.get("max_tokens")
    if max_tokens is None:
        max_tokens = default_max
    floor = 0 if body.get("echo") is True else 1
    if not isinstance(max_tokens, int) or max_tokens < floor:
        raise HTTPError(
            400,
            '"max_tokens" must be a positive integer'
            + (" (0 allowed with echo)" if floor == 0 else ""),
        )
    # deadline + priority + brownout (after max_tokens validates, so
    # the brownout clamp never masks a type error; before any encode
    # work, so shed requests cost the server nothing)
    max_tokens = _admit_request(ctx, max_tokens)
    sampler = _sampler(body)
    stop_ids, stop_strs = _parse_stops(ctx, body)
    lp_req = body.get("logprobs")
    want_logprobs = lp_req not in (None, False, 0)
    # alternatives: an integer logprobs >= 2 (the completions form) or
    # the explicit chat-style "top_logprobs" key, which wins when both
    # are present. logprobs 1/true stays chosen-token-only — the long-
    # standing behavior of this endpoint, documented in the API guide
    # (pass top_logprobs for one alternative per position)
    top_n = 0
    if isinstance(lp_req, int) and not isinstance(lp_req, bool) and lp_req >= 2:
        top_n = lp_req
    tl = body.get("top_logprobs")
    if tl is not None:
        if not isinstance(tl, int) or isinstance(tl, bool) or tl < 0:
            raise HTTPError(400, '"top_logprobs" must be an integer >= 0')
        top_n = tl
        if tl > 0:
            want_logprobs = True
    from gofr_tpu.models.transformer import TOP_LOGPROBS

    if top_n > TOP_LOGPROBS:
        raise HTTPError(
            400, f'the maximum value for "logprobs"/"top_logprobs" is '
            f"{TOP_LOGPROBS}"
        )
    adapter = body.get("adapter")  # multi-LoRA extension
    if adapter is not None and not isinstance(adapter, str):
        raise HTTPError(400, '"adapter" must be a string')
    if adapter is None:
        # OpenAI-conventional selection: "model" naming a loaded adapter
        # routes to it (stock clients have no way to send "adapter");
        # the explicit extension key wins when both are present. An
        # UNKNOWN model name is a 404 exactly like the real API — a
        # gateway routing to an unloaded adapter must never silently get
        # base-model output (list_adapters waits for boot, so the
        # routing decision always sees the post-boot adapter set)
        requested = body.get("model")
        if isinstance(requested, str) and requested != ctx.tpu.model_name:
            loaded = ctx.tpu.list_adapters()
            if requested in loaded:
                adapter = requested
            elif ctx.config.get_or_default(
                "OPENAI_ACCEPT_UNKNOWN_MODEL", ""
            ) in ("1", "true", "on"):
                # pre-r04 behavior for clients with a hardcoded model
                # string: serve the base model whatever "model" says
                # (documented breaking-change escape hatch)
                pass
            else:
                raise HTTPError(
                    404,
                    f"model '{requested}' not found (serving: "
                    f"{[ctx.tpu.model_name, *loaded]})",
                )
    return (body, max_tokens, sampler, stop_ids, stop_strs, want_logprobs,
            top_n, adapter)


def _stream_usage_opt(body: dict) -> bool:
    """OpenAI ``stream_options``: {"include_usage": true} asks for ONE
    final pre-[DONE] chunk with empty choices and the usage object
    (and "usage": null on every other chunk — typed SDK clients treat
    the field as present-when-requested). Only legal with stream."""
    so = body.get("stream_options")
    if so is None:
        return False
    if not isinstance(so, dict):
        raise HTTPError(400, '"stream_options" must be an object')
    if not body.get("stream"):
        raise HTTPError(
            400, '"stream_options" is only allowed with "stream": true'
        )
    unknown = set(so) - {"include_usage"}
    if unknown:
        # a misspelled include_usage must not silently stream with no
        # usage frame (the client's accounting would wait forever)
        raise HTTPError(
            400, f'unknown "stream_options" keys: {sorted(unknown)}'
        )
    inc = so.get("include_usage", False)
    if not isinstance(inc, bool):
        raise HTTPError(
            400, '"stream_options.include_usage" must be a boolean'
        )
    return inc


_FANOUT_CAP = 16  # pool-slot-scale bound on n/best_of; beyond it is a 400


def _parse_fanout(body: dict, allow_best_of: bool) -> tuple[int, int, bool]:
    """(n, best_of, echo) with OpenAI constraints: best_of >= n, both
    capped, echo completions-only. Streaming fan-out is rejected at the
    call site (interleaved multi-index SSE is not implemented)."""

    def positive(key: str, default: int) -> int:
        value = body.get(key)
        if value is None:
            return default
        if not isinstance(value, int) or isinstance(value, bool) or value < 1:
            raise HTTPError(400, f'"{key}" must be a positive integer')
        if value > _FANOUT_CAP:
            raise HTTPError(
                400, f'"{key}" is capped at {_FANOUT_CAP} on this server'
            )
        return value

    n = positive("n", 1)
    best_of = positive("best_of", 1)  # type/range-checked on BOTH endpoints
    if not allow_best_of and best_of != 1:
        raise HTTPError(400, '"best_of" is a completions-only parameter')
    if body.get("best_of") is not None and best_of < n:
        raise HTTPError(400, '"best_of" must be >= "n"')
    best_of = max(n, best_of)
    echo = body.get("echo")
    if echo is None:
        echo = False
    elif not isinstance(echo, bool):
        # bool("false") is True — a loud 400 beats echoing a prompt the
        # client asked not to echo
        raise HTTPError(400, '"echo" must be a boolean')
    if not allow_best_of and echo:
        raise HTTPError(400, '"echo" is a completions-only parameter')
    return n, best_of, echo
