"""Shared anomaly vocabulary + bounded evidence ring (host-side).

The anomaly ring was born in the dispatch cost model
(``gofr_tpu/tpu/costmodel.py``) as the evidence store behind
``GET /admin/anomalies``. The SLO engine (``gofr_tpu/slo.py``) lands its
burn-rate verdicts in the SAME ring — one anomaly surface, whether the
evidence is a dispatch blowing its prediction or an error budget
burning — but it must be constructible on processes that never wire a
device (fleet routers, bare containers), and ``gofr_tpu.tpu``'s package
init pays the jax import. So the ring and the cause vocabulary live
here, import-free of jax; ``costmodel.py`` re-exports both, and every
existing ``from gofr_tpu.tpu.costmodel import AnomalyRing`` keeps
working.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Optional

# anomaly causes (the `cause` label of gofr_tpu_dispatch_anomalies_total
# and the `?cause=` filter of GET /admin/anomalies)
ANOMALY_CAUSES = (
    "slow_dispatch",  # one dispatch exceeded COSTMODEL_ANOMALY_FACTOR x prediction
    "ema_drift",      # a family's residual EMA drifted past COSTMODEL_EMA_BAND
    "slo_fast_burn",  # an SLO objective burned past SLO_BURN_FAST_RATE on both fast windows
    "slo_slow_burn",  # an SLO objective burned past SLO_BURN_SLOW_RATE on both slow windows
)


class AnomalyRing:
    """Bounded, thread-safe ring of typed anomaly events with monotonic
    sequence numbers — the evidence store behind ``GET /admin/anomalies``
    (and the ``anomalies`` block of every postmortem bundle)."""

    def __init__(self, capacity: int = 256):
        self._ring: "deque[dict[str, Any]]" = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._by: dict[tuple, int] = {}  # (kind, cause) -> count
        self._total = 0
        self._last_ts: Optional[float] = None

    def record(self, **event: Any) -> dict[str, Any]:
        # gofrlint: wall-clock — anomaly event display/correlation ts
        ts = time.time()
        entry = {"seq": next(self._seq), "ts": ts, **event}
        key = (event.get("kind", ""), event.get("cause", ""))
        with self._lock:
            self._ring.append(entry)
            self._by[key] = self._by.get(key, 0) + 1
            self._total += 1
            self._last_ts = ts
        return entry

    def events(
        self,
        limit: int = 100,
        kind: Optional[str] = None,
        cause: Optional[str] = None,
    ) -> list[dict[str, Any]]:
        """Most-recent-first events, optionally filtered."""
        with self._lock:
            snapshot = list(self._ring)
        out: list[dict[str, Any]] = []
        for entry in reversed(snapshot):
            if kind is not None and entry.get("kind") != kind:
                continue
            if cause is not None and entry.get("cause") != cause:
                continue
            out.append(dict(entry))
            if len(out) >= limit:
                break
        return out

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def total(self) -> int:
        with self._lock:
            return self._total

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "total": self._total,
                "retained": len(self._ring),
                "capacity": self._ring.maxlen,
                "by": {"/".join(k): v for k, v in sorted(self._by.items())},
                "last_ts": self._last_ts,
            }
