"""Pure-stdlib RSA-OAEP(SHA1) — the `cryptography` fallback for the
MySQL ``caching_sha2_password`` full-auth exchange.

MySQL's non-TLS full auth encrypts the nonce-whitened password with the
server's RSA public key under OAEP/MGF1-SHA1. The client normally uses
the ``cryptography`` package for this; environments without it (the
jax_graft serving containers ship no OpenSSL bindings) would otherwise
lose the full-auth path entirely — including the in-process
:class:`~gofr_tpu.datasource.minimysql.MiniMySQL` tests that prove the
client drives the sub-protocol correctly. This module implements just
enough, in auditable stdlib Python:

- OAEP-SHA1 encrypt against a PEM/DER ``SubjectPublicKeyInfo`` key
  (the shape a real MySQL server hands over in the key packet);
- key generation + OAEP-SHA1 decrypt for the FAKE server side.

Scope warning: textbook modular exponentiation is not constant-time.
That is acceptable here — the encrypt path protects a password in
transit against a PASSIVE observer exactly as the real exchange does,
and the decrypt path exists only inside the test fake. When
``cryptography`` is installed, callers prefer it (see
``mysql.rsa_encrypt_password``).
"""

from __future__ import annotations

import base64
import hashlib
import os
from typing import Optional

_SHA1_LEN = 20


# -- minimal DER --------------------------------------------------------------

def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_int(value: int) -> bytes:
    body = value.to_bytes((value.bit_length() + 8) // 8 or 1, "big")
    return b"\x02" + _der_len(len(body)) + body


def _der_seq(*parts: bytes) -> bytes:
    body = b"".join(parts)
    return b"\x30" + _der_len(len(body)) + body


class _DERReader:
    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def _read_len(self) -> int:
        first = self._data[self._pos]
        self._pos += 1
        if first < 0x80:
            return first
        n_bytes = first & 0x7F
        if n_bytes == 0 or n_bytes > 4:
            raise ValueError("unsupported DER length encoding")
        value = int.from_bytes(
            self._data[self._pos:self._pos + n_bytes], "big"
        )
        self._pos += n_bytes
        return value

    def expect(self, tag: int) -> bytes:
        if self._pos >= len(self._data) or self._data[self._pos] != tag:
            raise ValueError(
                f"DER tag 0x{tag:02x} expected at offset {self._pos}"
            )
        self._pos += 1
        length = self._read_len()
        body = self._data[self._pos:self._pos + length]
        if len(body) != length:
            raise ValueError("DER value truncated")
        self._pos += length
        return body


# OID 1.2.840.113549.1.1.1 (rsaEncryption) + NULL params
_RSA_ALG_ID = bytes.fromhex("300d06092a864886f70d0101010500")


def load_public_key(pem_or_der: bytes) -> tuple[int, int]:
    """Parse a SubjectPublicKeyInfo (PEM or raw DER) into ``(n, e)``."""
    data = pem_or_der.strip()
    if data.startswith(b"-----"):
        lines = [
            line for line in data.splitlines()
            if line and not line.startswith(b"-----")
        ]
        data = base64.b64decode(b"".join(lines), validate=True)
    spki = _DERReader(data)
    inner = _DERReader(spki.expect(0x30))
    if inner.expect(0x30) != _RSA_ALG_ID[2:]:
        raise ValueError("not an rsaEncryption SubjectPublicKeyInfo")
    bitstring = inner.expect(0x03)
    if not bitstring or bitstring[0] != 0:
        raise ValueError("unsupported BIT STRING padding")
    rsa_key = _DERReader(bitstring[1:])
    seq = _DERReader(rsa_key.expect(0x30))
    n = int.from_bytes(seq.expect(0x02), "big")
    e = int.from_bytes(seq.expect(0x02), "big")
    return n, e


def public_key_pem(n: int, e: int) -> bytes:
    """Encode ``(n, e)`` as a PEM SubjectPublicKeyInfo — byte-compatible
    with what ``cryptography`` (and a real MySQL server) emits."""
    rsa_key = _der_seq(_der_int(n), _der_int(e))
    spki = _der_seq(_RSA_ALG_ID, b"\x03" + _der_len(len(rsa_key) + 1)
                    + b"\x00" + rsa_key)
    b64 = base64.b64encode(spki)
    body = b"\n".join(b64[i:i + 64] for i in range(0, len(b64), 64))
    return (b"-----BEGIN PUBLIC KEY-----\n" + body
            + b"\n-----END PUBLIC KEY-----\n")


# -- key generation (test-fake server side) -----------------------------------

_SMALL_PRIMES = (
    3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61,
    67, 71, 73, 79, 83, 89, 97,
)


def _is_probable_prime(n: int, rounds: int = 40) -> bool:
    if n < 2:
        return False
    for p in _SMALL_PRIMES:
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = int.from_bytes(os.urandom((n.bit_length() + 7) // 8), "big")
        a = a % (n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = pow(x, 2, n)
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int) -> int:
    while True:
        candidate = int.from_bytes(os.urandom(bits // 8), "big")
        candidate |= (1 << (bits - 1)) | 1  # full width, odd
        if _is_probable_prime(candidate):
            return candidate


class PrivateKey:
    """An RSA keypair for the fake server: holds ``(n, e, d)``; the
    public half exports as PEM for the wire."""

    def __init__(self, n: int, e: int, d: int):
        self.n = n
        self.e = e
        self.d = d

    def public_pem(self) -> bytes:
        return public_key_pem(self.n, self.e)

    def decrypt_oaep_sha1(self, ciphertext: bytes) -> bytes:
        return _oaep_decrypt(self, ciphertext)


def generate_key(bits: int = 1024) -> PrivateKey:
    """Generate an RSA keypair. 1024 bits keeps test-fake keygen fast;
    the strength of the TEST exchange is not a production property
    (a real server brings its own key)."""
    e = 65537
    while True:
        p = _random_prime(bits // 2)
        q = _random_prime(bits // 2)
        if p == q:
            continue
        n = p * q
        phi = (p - 1) * (q - 1)
        if phi % e == 0:
            continue
        d = pow(e, -1, phi)
        return PrivateKey(n, e, d)


# -- OAEP (SHA1 / MGF1-SHA1, empty label) -------------------------------------

def _mgf1(seed: bytes, length: int) -> bytes:
    out = b""
    counter = 0
    while len(out) < length:
        out += hashlib.sha1(seed + counter.to_bytes(4, "big")).digest()
        counter += 1
    return out[:length]


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def oaep_encrypt(pub: tuple[int, int], message: bytes,
                 seed: Optional[bytes] = None) -> bytes:
    """RSAES-OAEP-ENCRYPT (RFC 8017 §7.1.1) with SHA1/MGF1-SHA1 and an
    empty label — the parameters MySQL's full-auth exchange fixes."""
    n, e = pub
    k = (n.bit_length() + 7) // 8
    if len(message) > k - 2 * _SHA1_LEN - 2:
        raise ValueError(f"message too long for a {k * 8}-bit OAEP key")
    l_hash = hashlib.sha1(b"").digest()
    padding = b"\x00" * (k - len(message) - 2 * _SHA1_LEN - 2)
    data_block = l_hash + padding + b"\x01" + message
    seed = seed or os.urandom(_SHA1_LEN)
    masked_db = _xor(data_block, _mgf1(seed, k - _SHA1_LEN - 1))
    masked_seed = _xor(seed, _mgf1(masked_db, _SHA1_LEN))
    em = b"\x00" + masked_seed + masked_db
    return pow(int.from_bytes(em, "big"), e, n).to_bytes(k, "big")


def _oaep_decrypt(key: PrivateKey, ciphertext: bytes) -> bytes:
    k = (key.n.bit_length() + 7) // 8
    if len(ciphertext) != k:
        raise ValueError("ciphertext length mismatch")
    em = pow(int.from_bytes(ciphertext, "big"), key.d, key.n).to_bytes(
        k, "big"
    )
    if em[0] != 0:
        raise ValueError("OAEP decoding error")
    masked_seed, masked_db = em[1:1 + _SHA1_LEN], em[1 + _SHA1_LEN:]
    seed = _xor(masked_seed, _mgf1(masked_db, _SHA1_LEN))
    data_block = _xor(masked_db, _mgf1(seed, k - _SHA1_LEN - 1))
    l_hash = hashlib.sha1(b"").digest()
    if data_block[:_SHA1_LEN] != l_hash:
        raise ValueError("OAEP decoding error")
    sep = data_block.find(b"\x01", _SHA1_LEN)
    if sep < 0:
        raise ValueError("OAEP decoding error")
    if any(data_block[_SHA1_LEN:sep]):
        raise ValueError("OAEP decoding error")
    return data_block[sep + 1:]
