"""Redis datasource: a from-scratch RESP2 client with command logging,
tracing, pooling, and health checks.

Parity: /root/reference/pkg/gofr/datasource/redis/redis.go:16-58 (connect
with 5s ping timeout :29-57, otel tracing instrument :48), hook.go:13-58
(per-command log entry with args + µs), health.go:10-30 (INFO-backed
Health). The environment has no redis-py, so the protocol layer is
implemented here directly (RESP2 encode/decode over TCP) — the same
miniredis-style strategy the reference uses for tests applies via
``gofr_tpu.datasource.miniredis``.
"""

from __future__ import annotations

import queue
import socket
import time
from dataclasses import dataclass
from typing import Any, Optional

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.tracing import get_tracer


@dataclass
class RedisLog:
    """Typed command log (parity: redis/hook.go:25-31)."""

    command: str
    duration_us: int

    def pretty_terminal(self) -> str:
        return f"\x1b[35mREDIS\x1b[0m [{self.command}] {self.duration_us}µs"

    def log_fields(self) -> dict[str, Any]:
        return {"datasource": "redis", "command": self.command, "duration_us": self.duration_us}


class RedisError(Exception):
    pass


class RedisServerError(RedisError):
    """A ``-ERR ...`` reply: the server answered, the connection is fine."""


class _Connection:
    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- RESP2 wire format ---------------------------------------------------
    @staticmethod
    def encode_command(args: tuple) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for arg in args:
            if isinstance(arg, bytes):
                data = arg
            elif isinstance(arg, str):
                data = arg.encode("utf-8")
            elif isinstance(arg, (int, float)):
                data = str(arg).encode()
            else:
                data = str(arg).encode("utf-8")
            out.append(b"$%d\r\n%s\r\n" % (len(data), data))
        return b"".join(out)

    def send_command(self, args: tuple) -> None:
        self.sock.sendall(self.encode_command(args))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed by server")
            self.buf += chunk
        line, _, self.buf = self.buf.partition(b"\r\n")
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed by server")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def read_reply(self) -> Any:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("utf-8")
        if kind == b"-":
            raise RedisServerError(rest.decode("utf-8"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_reply() for _ in range(n)]
        raise RedisError(f"unexpected RESP type: {line[:32]!r}")


class _Commands:
    """Command surface shared by the client (immediate execution) and
    Pipeline (queued execution): each method routes through ``_do``."""

    def _do(self, *args: Any) -> Any:
        raise NotImplementedError

    def get(self, key: str) -> Any:
        return self._do("GET", key)

    def set(self, key: str, value: Any, ex: Optional[int] = None) -> Any:
        if ex is not None:
            return self._do("SET", key, value, "EX", ex)
        return self._do("SET", key, value)

    def delete(self, *keys: str) -> int:
        return self._do("DEL", *keys)

    def exists(self, *keys: str) -> int:
        return self._do("EXISTS", *keys)

    def incr(self, key: str) -> int:
        return self._do("INCR", key)

    def expire(self, key: str, seconds: int) -> int:
        return self._do("EXPIRE", key, seconds)

    def ttl(self, key: str) -> int:
        return self._do("TTL", key)

    def keys(self, pattern: str = "*") -> list:
        return self._do("KEYS", pattern)

    def hset(self, key: str, field: str, value: Any) -> int:
        return self._do("HSET", key, field, value)

    def hget(self, key: str, field: str) -> Any:
        return self._do("HGET", key, field)

    def lpush(self, key: str, *values: Any) -> int:
        return self._do("LPUSH", key, *values)

    def rpop(self, key: str) -> Any:
        return self._do("RPOP", key)

    def flushdb(self) -> Any:
        return self._do("FLUSHDB")


class RedisClient(_Commands):
    """Thread-safe pooled client. Commands return decoded replies (bulk
    strings as ``str`` where valid UTF-8, else bytes)."""

    def __init__(
        self,
        host: str,
        port: int = 6379,
        logger: Any = None,
        timeout: float = 5.0,  # parity: redis/redis.go:14 5s ping timeout
        pool_size: int = 8,
        decode: bool = True,
    ):
        self.host = host
        self.port = port
        self.logger = logger
        self.timeout = timeout
        self.decode = decode
        self._pool: "queue.Queue[_Connection]" = queue.Queue(maxsize=pool_size)
        self._pool_size = pool_size
        self._created = 0
        # connect + ping eagerly (parity: redis.go:41-46 — fail fast so the
        # container can log-and-degrade)
        conn = self._connect()
        self._put(conn)
        self.execute("PING")

    def _connect(self) -> _Connection:
        return _Connection(self.host, self.port, self.timeout)

    def _get(self) -> _Connection:
        try:
            return self._pool.get_nowait()
        except queue.Empty:
            return self._connect()

    def _put(self, conn: _Connection) -> None:
        try:
            self._pool.put_nowait(conn)
        except queue.Full:
            conn.close()

    # -- generic command execution ------------------------------------------
    def execute(self, *args: Any) -> Any:
        command = " ".join(str(a) for a in args)
        start = time.perf_counter()
        span = get_tracer().start_span(f"redis-{str(args[0]).lower()}", activate=False)
        span.set_tag("db.system", "redis")
        span.set_tag("db.statement", command[:256])
        conn = self._get()
        try:
            conn.send_command(args)
            reply = conn.read_reply()
            self._put(conn)
        except RedisServerError:
            self._put(conn)  # server replied; connection still healthy
            raise
        except (OSError, RedisError) as exc:
            conn.close()
            raise RedisError(f"redis {args[0]}: {exc}") from exc
        finally:
            span.end()
            if self.logger is not None:
                elapsed_us = int((time.perf_counter() - start) * 1e6)
                self.logger.debug(RedisLog(command=command[:128], duration_us=elapsed_us))
        return self._decode(reply)

    def _decode(self, reply: Any) -> Any:
        if not self.decode:
            return reply
        if isinstance(reply, bytes):
            try:
                return reply.decode("utf-8")
            except UnicodeDecodeError:
                return reply
        if isinstance(reply, list):
            return [self._decode(r) for r in reply]
        return reply

    # -- convenience commands route through _Commands -------------------------
    def _do(self, *args: Any) -> Any:
        return self.execute(*args)

    def ping(self) -> bool:
        return self.execute("PING") == "PONG"

    # -- pipelining (parity: redis/hook.go:38-58 logs pipelined batches) ------
    def pipeline(self) -> "Pipeline":
        """Queue commands and flush them in ONE round trip::

            with r.pipeline() as p:
                p.set("a", 1)
                p.incr("counter")
            # p.results == ["OK", 2]

        or explicitly: ``results = p.execute()``."""
        return Pipeline(self)

    def _execute_pipeline(self, cmds: list[tuple], raise_on_error: bool) -> list:
        """Send every queued command in one write, then read all replies —
        one round trip total. Per-command server errors are captured (all
        replies are always drained) and re-raised after the batch unless
        ``raise_on_error=False``."""
        if not cmds:
            return []
        summary = f"pipeline[{len(cmds)}] " + " | ".join(
            " ".join(str(a) for a in cmd)[:48] for cmd in cmds[:8]
        )
        start = time.perf_counter()
        span = get_tracer().start_span("redis-pipeline", activate=False)
        span.set_tag("db.system", "redis")
        span.set_tag("db.statement", summary[:256])
        span.set_tag("db.redis.pipeline_length", len(cmds))
        conn = self._get()
        try:
            conn.sock.sendall(b"".join(_Connection.encode_command(c) for c in cmds))
            replies: list[Any] = []
            for _ in cmds:
                try:
                    replies.append(conn.read_reply())
                except RedisServerError as exc:
                    replies.append(exc)
            self._put(conn)
        except (OSError, RedisError) as exc:
            conn.close()
            raise RedisError(f"redis pipeline: {exc}") from exc
        finally:
            span.end()
            if self.logger is not None:
                elapsed_us = int((time.perf_counter() - start) * 1e6)
                self.logger.debug(RedisLog(command=summary[:128], duration_us=elapsed_us))
        results = [
            r if isinstance(r, RedisServerError) else self._decode(r) for r in replies
        ]
        if raise_on_error:
            for r in results:
                if isinstance(r, RedisServerError):
                    raise r
        return results

    # -- health (parity: redis/health.go:10-30) -------------------------------
    def health_check(self) -> Health:
        try:
            start = time.perf_counter()
            info_raw = self.execute("INFO")
            latency_us = int((time.perf_counter() - start) * 1e6)
            details: dict[str, Any] = {
                "host": f"{self.host}:{self.port}",
                "latency_us": latency_us,
            }
            if isinstance(info_raw, str):
                for line in info_raw.splitlines():
                    if line.startswith(("redis_version", "connected_clients", "used_memory:")):
                        key, _, value = line.partition(":")
                        details[key] = value.strip()
            return Health(UP, details)
        except Exception as exc:
            return Health(DOWN, {"host": f"{self.host}:{self.port}", "error": str(exc)})

    def close(self) -> None:
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                break


class Pipeline(_Commands):
    """Queued command batch; ``execute()`` (or clean ``with``-exit) flushes
    everything in one round trip. Command methods return the pipeline for
    chaining; replies come back as a list in command order."""

    def __init__(self, client: RedisClient):
        self._client = client
        self._cmds: list[tuple] = []
        self.results: Optional[list] = None

    def _do(self, *args: Any) -> "Pipeline":
        self._cmds.append(args)
        return self

    def command(self, *args: Any) -> "Pipeline":
        """Queue an arbitrary command (the generic escape hatch)."""
        return self._do(*args)

    def __len__(self) -> int:
        return len(self._cmds)

    def execute(self, raise_on_error: bool = True) -> list:
        cmds, self._cmds = self._cmds, []
        self.results = self._client._execute_pipeline(cmds, raise_on_error)
        return self.results

    def __enter__(self) -> "Pipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.execute()


def new_client(host: str, port: int = 6379, logger: Any = None) -> RedisClient:
    """Parity: redis/redis.go:29."""
    return RedisClient(host, port, logger)
