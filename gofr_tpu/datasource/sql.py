"""SQL datasource: a DB-API pool wrapper with query logging, transactions,
reflection row binding, and health checks.

Parity: /root/reference/pkg/gofr/datasource/sql/ —
- sql.go:10-38: DBConfig from env keys, DSN build, connect + ping;
- db.go:15-117: logged Query/QueryRow/Exec and the Tx wrapper;
- db.go:148-243: reflection ``Select`` into a slice/struct using ``db:``
  tags or snake_case field names, unmatched columns discarded;
- db.go:248: ToSnakeCase; health.go:10-29: 1s ping + pool stats.

The built-in driver is stdlib sqlite3 (the environment ships no MySQL
driver); ``DB_DIALECT=mysql`` is gated behind driver availability with the
same degraded-startup behavior the container applies to all datasources.
Connections are per-thread (sqlite3 objects are not thread-safe), so the
pool plays the role of database/sql's internal pool.
"""

from __future__ import annotations

import dataclasses
import re
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator, Optional, Sequence

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.tracing import get_tracer


@dataclass
class SQLLog:
    """Typed query log (parity: sql/db.go:27-34)."""

    query: str
    duration_us: int

    def pretty_terminal(self) -> str:
        return f"\x1b[36mSQL\x1b[0m [{self.query}] {self.duration_us}µs"

    def log_fields(self) -> dict[str, Any]:
        return {"datasource": "sql", "query": self.query, "duration_us": self.duration_us}


def to_snake_case(name: str) -> str:
    """Parity: sql/db.go:248-253."""
    s1 = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s1).lower()


class DB:
    """Logged DB wrapper (parity: sql/db.go:15)."""

    _mem_counter = 0
    _mem_lock = threading.Lock()

    def __init__(self, path: str, logger: Any = None):
        self.path = path
        if path == ":memory:":
            # per-thread connections must still see ONE database; a plain
            # :memory: is private per connection, so use a shared-cache URI
            with DB._mem_lock:
                DB._mem_counter += 1
                self._uri = f"file:gofr_mem_{id(self)}_{DB._mem_counter}?mode=memory&cache=shared"
        else:
            self._uri = f"file:{path}"
        self.logger = logger
        self._local = threading.local()
        self._conns: list[sqlite3.Connection] = []
        self._conns_lock = threading.Lock()
        # connect + ping eagerly so the container can log-and-degrade; this
        # anchor connection also keeps a shared in-memory db alive
        self._conn().execute("SELECT 1")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._uri, timeout=5.0, uri=True)
            conn.row_factory = sqlite3.Row
            conn.isolation_level = None  # autocommit; explicit BEGIN for tx
            self._local.conn = conn
            with self._conns_lock:
                self._conns.append(conn)
        return conn

    # -- logged primitives (parity: db.go:36-59) -----------------------------
    def _timed(self, query: str, fn):
        start = time.perf_counter()
        span = get_tracer().start_span("sql-query", activate=False)
        span.set_tag("db.system", "sqlite")
        span.set_tag("db.statement", query[:256])
        try:
            return fn()
        finally:
            span.end()
            if self.logger is not None:
                elapsed_us = int((time.perf_counter() - start) * 1e6)
                self.logger.debug(SQLLog(query=query[:256], duration_us=elapsed_us))

    def query(self, query: str, *args: Any) -> list[sqlite3.Row]:
        return self._timed(query, lambda: self._conn().execute(query, args).fetchall())

    def query_row(self, query: str, *args: Any) -> Optional[sqlite3.Row]:
        return self._timed(query, lambda: self._conn().execute(query, args).fetchone())

    def execute(self, query: str, *args: Any) -> int:
        """Returns affected-row count (parity: Exec, db.go:52)."""

        def run() -> int:
            cur = self._conn().execute(query, args)
            return cur.rowcount if cur.rowcount >= 0 else 0

        return self._timed(query, run)

    def execute_many(self, query: str, rows: Sequence[Sequence[Any]]) -> int:
        def run() -> int:
            cur = self._conn().executemany(query, rows)
            return cur.rowcount if cur.rowcount >= 0 else 0

        return self._timed(f"{query} [batch x{len(rows)}]", run)

    # -- transactions (parity: db.go:70-117) ---------------------------------
    class _Tx:
        def __init__(self, db: "DB"):
            self.db = db

        def __enter__(self) -> "DB._Tx":
            self.db._timed("BEGIN", lambda: self.db._conn().execute("BEGIN"))
            return self

        def query(self, query: str, *args: Any) -> list[sqlite3.Row]:
            return self.db.query(query, *args)

        def execute(self, query: str, *args: Any) -> int:
            return self.db.execute(query, *args)

        def __exit__(self, exc_type, exc, tb) -> None:
            if exc_type is None:
                self.db._timed("COMMIT", lambda: self.db._conn().execute("COMMIT"))
            else:
                self.db._timed("ROLLBACK", lambda: self.db._conn().execute("ROLLBACK"))

    def begin(self) -> "DB._Tx":
        return DB._Tx(self)

    # -- reflection select (parity: db.go:148-243) ---------------------------
    def select(self, into: type, query: str, *args: Any) -> Any:
        """``into`` is a dataclass type -> list of instances; column->field
        mapping uses ``field(metadata={"db": "col"})`` or snake_case of the
        field name; unmatched columns are discarded (db.go:202-243)."""
        rows = self.query(query, *args)
        if not dataclasses.is_dataclass(into):
            raise TypeError(f"select target must be a dataclass, got {into!r}")
        field_by_column: dict[str, str] = {}
        for f in dataclasses.fields(into):
            column = f.metadata.get("db", to_snake_case(f.name))
            field_by_column[column] = f.name
        out = []
        for row in rows:
            kwargs = {}
            for column in row.keys():
                field_name = field_by_column.get(column)
                if field_name is not None:
                    kwargs[field_name] = row[column]
            out.append(into(**kwargs))
        return out

    def select_one(self, into: type, query: str, *args: Any) -> Optional[Any]:
        result = self.select(into, query, *args)
        return result[0] if result else None

    def select_value(self, query: str, *args: Any) -> Any:
        row = self.query_row(query, *args)
        return None if row is None else row[0]

    # -- health (parity: sql/health.go:10-29) --------------------------------
    def health_check(self) -> Health:
        try:
            start = time.perf_counter()
            self._conn().execute("SELECT 1").fetchone()
            latency_us = int((time.perf_counter() - start) * 1e6)
            return Health(UP, {"database": self.path, "latency_us": latency_us,
                               "open_connections": len(self._conns)})
        except Exception as exc:
            return Health(DOWN, {"database": self.path, "error": str(exc)})

    def close(self) -> None:
        with self._conns_lock:
            for conn in self._conns:
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()


def new_sql(config: Any, logger: Any = None) -> Any:
    """Config-driven constructor (parity: sql/sql.go:19-38).

    DB_DIALECT=sqlite (default): DB_NAME is the database path (or
    ``:memory:``). DB_DIALECT=mysql: the from-scratch wire-protocol client
    (datasource/mysql.py) over DB_HOST/DB_PORT/DB_USER/DB_PASSWORD/DB_NAME
    — the same env keys the reference DSN uses (sql.go:19-37). Connect
    failures raise; the container logs and degrades."""
    dialect = (config.get_or_default("DB_DIALECT", "sqlite") or "sqlite").lower()
    if dialect == "sqlite":
        name = config.get_or_default("DB_NAME", ":memory:")
        return DB(name, logger)
    if dialect == "mysql":
        from gofr_tpu.datasource.mysql import MySQLDB

        return MySQLDB(
            host=config.get_or_default("DB_HOST", "127.0.0.1"),
            port=int(config.get_or_default("DB_PORT", "3306")),
            user=config.get_or_default("DB_USER", "root"),
            password=config.get_or_default("DB_PASSWORD", ""),
            database=config.get_or_default("DB_NAME", ""),
            logger=logger,
        )
    raise RuntimeError(f"unsupported DB_DIALECT '{dialect}'")


def new_mysql(config: Any, logger: Any = None) -> Any:
    """Parity alias: sql.go:19 NewMYSQL."""
    return new_sql(config, logger)
