"""In-process mini Redis server for tests.

Parity role: the reference tests Redis against **miniredis** (go.mod:7,
redis_test.go:23 ``miniredis.Run()``) instead of real infrastructure
(SURVEY.md §4). This is the same idea: a real TCP server speaking enough
RESP2 for the framework's client and examples, running on a daemon thread.

Supported: PING ECHO SET GET DEL EXISTS INCR DECR EXPIRE TTL KEYS INFO
FLUSHDB HSET HGET HGETALL LPUSH RPUSH RPOP LPOP LRANGE QUIT.
Expiry is lazy (checked on access), like miniredis's FastForward-free mode.
"""

from __future__ import annotations

import fnmatch
import socket
import socketserver
import threading
import time
from typing import Any, Optional


class _Store:
    def __init__(self) -> None:
        self.data: dict[str, Any] = {}
        self.expiry: dict[str, float] = {}
        self.lock = threading.RLock()

    def _check_expired(self, key: str) -> None:
        deadline = self.expiry.get(key)
        if deadline is not None and time.monotonic() >= deadline:
            self.data.pop(key, None)
            self.expiry.pop(key, None)

    def get(self, key: str) -> Any:
        with self.lock:
            self._check_expired(key)
            return self.data.get(key)

    def set(self, key: str, value: Any, ex: Optional[float] = None) -> None:
        with self.lock:
            self.data[key] = value
            if ex is not None:
                self.expiry[key] = time.monotonic() + ex
            else:
                self.expiry.pop(key, None)

    def delete(self, key: str) -> bool:
        with self.lock:
            self._check_expired(key)
            existed = key in self.data
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return existed

    def keys(self) -> list[str]:
        with self.lock:
            for key in list(self.data):
                self._check_expired(key)
            return list(self.data)


def _encode(value: Any) -> bytes:
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, _Simple):
        return b"+" + value.text.encode() + b"\r\n"
    if isinstance(value, _Error):
        return b"-" + value.text.encode() + b"\r\n"
    if isinstance(value, int):
        return b":%d\r\n" % value
    if isinstance(value, (list, tuple)):
        return b"*%d\r\n" % len(value) + b"".join(_encode(v) for v in value)
    data = value if isinstance(value, bytes) else str(value).encode("utf-8")
    return b"$%d\r\n%s\r\n" % (len(data), data)


class _Simple:
    def __init__(self, text: str):
        self.text = text


class _Error:
    def __init__(self, text: str):
        self.text = text


OK = _Simple("OK")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        store: _Store = self.server.store  # type: ignore[attr-defined]
        buf = b""
        sock = self.request
        while True:
            args, buf, closed = _read_command(sock, buf)
            if closed:
                return
            if not args:
                continue
            cmd = args[0].decode("utf-8", "replace").upper()
            rest = [a.decode("utf-8", "replace") for a in args[1:]]
            if cmd == "QUIT":
                sock.sendall(_encode(OK))
                return
            try:
                reply = _dispatch(store, cmd, rest)
            except Exception as exc:  # pragma: no cover - defensive
                reply = _Error(f"ERR {exc}")
            try:
                sock.sendall(_encode(reply))
            except OSError:
                return


def _read_command(sock: socket.socket, buf: bytes) -> tuple[list[bytes], bytes, bool]:
    def need(n: int) -> bool:
        nonlocal buf
        while len(buf) < n:
            chunk = sock.recv(65536)
            if not chunk:
                return False
            buf += chunk
        return True

    def read_line() -> Optional[bytes]:
        nonlocal buf
        while b"\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                return None
            buf += chunk
        line, _, buf = buf.partition(b"\r\n")
        return line

    line = read_line()
    if line is None:
        return [], buf, True
    if not line.startswith(b"*"):
        # inline command
        return line.split(), buf, False
    n = int(line[1:])
    args: list[bytes] = []
    for _ in range(n):
        header = read_line()
        if header is None or not header.startswith(b"$"):
            return [], buf, True
        size = int(header[1:])
        if not need(size + 2):
            return [], buf, True
        args.append(buf[:size])
        buf = buf[size + 2:]
    return args, buf, False


def _cmd_ping(store: _Store, cmd: str, args: list[str]) -> Any:
    return _Simple(args[0]) if args else _Simple("PONG")


def _cmd_echo(store: _Store, cmd: str, args: list[str]) -> Any:
    return args[0]


def _cmd_set(store: _Store, cmd: str, args: list[str]) -> Any:
    ex = None
    i = 2
    while i < len(args):
        opt = args[i].upper()
        if opt == "EX" and i + 1 < len(args):
            ex = float(args[i + 1])
            i += 2
        elif opt == "PX" and i + 1 < len(args):
            ex = float(args[i + 1]) / 1000.0
            i += 2
        else:
            i += 1
    store.set(args[0], args[1], ex)
    return OK


def _cmd_get(store: _Store, cmd: str, args: list[str]) -> Any:
    value = store.get(args[0])
    if isinstance(value, (dict, list)):
        return _Error(
            "WRONGTYPE Operation against a key holding the wrong kind of value"
        )
    return value


def _cmd_del(store: _Store, cmd: str, args: list[str]) -> Any:
    return sum(1 for k in args if store.delete(k))


def _cmd_exists(store: _Store, cmd: str, args: list[str]) -> Any:
    return sum(1 for k in args if store.get(k) is not None)


def _cmd_incr(store: _Store, cmd: str, args: list[str]) -> Any:
    delta = int(args[1]) if len(args) > 1 else 1
    if cmd.startswith("DECR"):
        delta = -delta
    with store.lock:
        current = store.get(args[0])
        try:
            value = (int(current) if current is not None else 0) + delta
        except (TypeError, ValueError):
            return _Error("ERR value is not an integer or out of range")
        deadline = store.expiry.get(args[0])  # INCR preserves TTL
        store.set(args[0], str(value), None)
        if deadline is not None:
            store.expiry[args[0]] = deadline
    return value


def _cmd_expire(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        if store.get(args[0]) is None:
            return 0
        store.expiry[args[0]] = time.monotonic() + float(args[1])
        return 1


def _cmd_ttl(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        if store.get(args[0]) is None:
            return -2
        deadline = store.expiry.get(args[0])
        if deadline is None:
            return -1
        return max(0, int(round(deadline - time.monotonic())))


def _cmd_keys(store: _Store, cmd: str, args: list[str]) -> Any:
    pattern = args[0] if args else "*"
    return [k for k in store.keys() if fnmatch.fnmatchcase(k, pattern)]


def _cmd_info(store: _Store, cmd: str, args: list[str]) -> Any:
    return (
        "# Server\r\nredis_version:7.0.0-mini\r\n"
        "# Clients\r\nconnected_clients:1\r\n"
        "# Memory\r\nused_memory:1024\r\n"
    )


def _cmd_flushdb(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        store.data.clear()
        store.expiry.clear()
    return OK


def _cmd_hset(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        h = store.get(args[0])
        if h is None:
            h = {}
            store.set(args[0], h, None)
        added = 0
        for field, value in zip(args[1::2], args[2::2]):
            added += 0 if field in h else 1
            h[field] = value
        return added


def _cmd_hget(store: _Store, cmd: str, args: list[str]) -> Any:
    h = store.get(args[0])
    return None if not isinstance(h, dict) else h.get(args[1])


def _cmd_hgetall(store: _Store, cmd: str, args: list[str]) -> Any:
    h = store.get(args[0])
    if not isinstance(h, dict):
        return []
    out: list[str] = []
    for k, v in h.items():
        out.extend((k, v))
    return out


def _cmd_push(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        lst = store.get(args[0])
        if lst is None:
            lst = []
            store.set(args[0], lst, None)
        for v in args[1:]:
            lst.insert(0, v) if cmd == "LPUSH" else lst.append(v)
        return len(lst)


def _cmd_pop(store: _Store, cmd: str, args: list[str]) -> Any:
    with store.lock:
        lst = store.get(args[0])
        if not lst:
            return None
        return lst.pop(0) if cmd == "LPOP" else lst.pop()


def _cmd_lrange(store: _Store, cmd: str, args: list[str]) -> Any:
    lst = store.get(args[0]) or []
    start, stop = int(args[1]), int(args[2])
    if stop == -1:
        return lst[start:]
    return lst[start : stop + 1]


# command table: each handler takes (store, cmd, args) — variant commands
# (INCR/DECR, LPUSH/RPUSH, LPOP/RPOP) share a handler and branch on cmd
_COMMANDS: dict = {
    "PING": _cmd_ping, "ECHO": _cmd_echo, "SET": _cmd_set,
    "GET": _cmd_get, "DEL": _cmd_del, "EXISTS": _cmd_exists,
    "INCR": _cmd_incr, "DECR": _cmd_incr, "INCRBY": _cmd_incr,
    "DECRBY": _cmd_incr, "EXPIRE": _cmd_expire, "TTL": _cmd_ttl,
    "KEYS": _cmd_keys, "INFO": _cmd_info, "FLUSHDB": _cmd_flushdb,
    "HSET": _cmd_hset, "HGET": _cmd_hget, "HGETALL": _cmd_hgetall,
    "LPUSH": _cmd_push, "RPUSH": _cmd_push, "LPOP": _cmd_pop,
    "RPOP": _cmd_pop, "LRANGE": _cmd_lrange,
}


def _dispatch(store: _Store, cmd: str, args: list[str]) -> Any:
    handler = _COMMANDS.get(cmd)
    if handler is None:
        return _Error(f"ERR unknown command '{cmd}'")
    return handler(store, cmd, args)


class MiniRedis:
    """``run()`` starts the server on an OS-assigned port; ``.port`` is what
    clients dial (parity role: miniredis.Run())."""

    def __init__(self) -> None:
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self.port = 0
        self.store = _Store()

    def run(self) -> "MiniRedis":
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _Handler)
        self._server.store = self.store  # type: ignore[attr-defined]
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="gofr-miniredis",
        ).start()
        return self

    def close(self) -> None:
        if self._server:
            self._server.shutdown()
            self._server.server_close()
