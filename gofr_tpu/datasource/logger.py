"""Minimal logger protocol consumed by datasources.

Parity: /root/reference/pkg/gofr/datasource/logger.go:9-16 — datasources
depend on this tiny protocol, not on ``gofr_tpu.logging``, so the logging
package stays free to pretty-print datasource log types without an import
cycle (the consumer-defined-interface rule called out in SURVEY.md §1).
"""

from __future__ import annotations

from typing import Any, Protocol


class DatasourceLogger(Protocol):
    def debug(self, *args: Any) -> None: ...

    def debugf(self, fmt: str, *args: Any) -> None: ...

    def info(self, *args: Any) -> None: ...

    def infof(self, fmt: str, *args: Any) -> None: ...

    def warn(self, *args: Any) -> None: ...

    def error(self, *args: Any) -> None: ...

    def errorf(self, fmt: str, *args: Any) -> None: ...
