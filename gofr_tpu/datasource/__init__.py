"""Datasource layer: health model, decoupled logger protocol, and the
concrete datasources (SQL, Redis, TPU).

Parity: /root/reference/pkg/gofr/datasource/ — notably the layering rule that
datasources define their own minimal logger protocol instead of importing the
logging package (datasource/logger.go:9-16).
"""

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.datasource.logger import DatasourceLogger

__all__ = ["Health", "UP", "DOWN", "DatasourceLogger"]
