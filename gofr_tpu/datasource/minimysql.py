"""In-process fake MySQL server for tests — the sqlmock/miniredis analogue.

Parity rationale: the reference unit-tests its MySQL layer against
go-sqlmock (SURVEY.md §4) without a real server. This fake goes one step
further: it speaks the REAL wire protocol (handshake v10, auth plugin
verification, COM_QUERY text resultsets, COM_PING) over a localhost
socket, executing statements against an in-memory sqlite — so
datasource/mysql.py's client is tested through its actual socket path,
framing, auth and resultset decoding included.

Auth mirrors a default-configured MySQL 8 (the reference CI image,
mysql:8.2.0): ``caching_sha2_password`` advertised by default, with the
fast-auth scramble verified; ``full_auth=True`` demands the non-TLS RSA
public-key exchange instead (what a real server does on a cache miss);
``auth_plugin="mysql_native_password"`` reproduces legacy servers; and
``switch_to=`` sends an AuthSwitchRequest so the client's plugin-name
check is exercised.
"""

from __future__ import annotations

import os
import socket
import sqlite3
import struct
import threading
from typing import Optional

from gofr_tpu.datasource.mysql import (
    COM_PING,
    COM_QUERY,
    COM_QUIT,
    encode_lenenc_int,
    encode_lenenc_str,
    native_password_token,
    sha2_password_token,
    xor_rotating,
)

_TYPE_LONGLONG, _TYPE_DOUBLE, _TYPE_VARSTR, _TYPE_BLOB = 0x08, 0x05, 0xFD, 0xFC

_BACKSLASH_MAP = {
    "n": "\n", "r": "\r", "t": "\t", "0": "\x00", "Z": "\x1a",
    "\\": "\\", "'": "'", '"': '"', "b": "\b", "%": "\\%", "_": "\\_",
}


def _mysql_to_sqlite(sql: str) -> str:
    """Rewrite MySQL string-literal syntax into sqlite's: backslash escapes
    (MySQL default) become literal characters, quotes double. The client
    escapes for REAL MySQL; the fake must accept exactly that dialect."""
    out: list[str] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch in ("'", '"'):
            quote = ch
            i += 1
            body: list[str] = []
            while i < n:
                c = sql[i]
                if c == "\\" and i + 1 < n:
                    body.append(_BACKSLASH_MAP.get(sql[i + 1], sql[i + 1]))
                    i += 2
                    continue
                if c == quote:
                    if i + 1 < n and sql[i + 1] == quote:  # doubled quote
                        body.append(quote)
                        i += 2
                        continue
                    i += 1
                    break
                body.append(c)
                i += 1
            literal = "".join(body).replace("'", "''")
            out.append(f"'{literal}'")
            continue
        out.append(ch)
        i += 1
    return "".join(out)


class MiniMySQL:
    """``with MiniMySQL(user="u", password="p") as srv: ...`` — serves one
    wire-protocol MySQL on ``srv.port`` backed by a shared in-memory
    sqlite."""

    def __init__(self, user: str = "root", password: str = "", port: int = 0,
                 auth_plugin: str = "caching_sha2_password",
                 full_auth: bool = False, switch_to: str = ""):
        self.user, self.password = user, password
        self.auth_plugin = auth_plugin
        self.full_auth = full_auth
        self.switch_to = switch_to
        self._rsa_key = None  # generated on first full-auth exchange
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._uri = f"file:minimysql_{id(self)}?mode=memory&cache=shared"
        self._anchor = sqlite3.connect(self._uri, uri=True)  # keeps db alive
        # statement serialization: shared-cache sqlite raises
        # SQLITE_LOCKED on concurrent writers where a real MySQL blocks
        # on row locks — the fake must present MySQL's serializing
        # behavior, not sqlite's. Held around execute+fetch only; the
        # socket writes stay outside (GFL004).
        self._db_lock = threading.Lock()
        self._closed = False
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True, name="gofr-minimysql-accept"
        )
        self._accept_thread.start()

    # -- lifecycle -----------------------------------------------------------
    def __enter__(self) -> "MiniMySQL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2)
        self._anchor.close()

    # -- accept loop ---------------------------------------------------------
    def _accept(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="gofr-minimysql-conn",
            )
            t.start()
            self._threads.append(t)

    # -- packet helpers ------------------------------------------------------
    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    @classmethod
    def _read_packet(cls, conn: socket.socket) -> Optional[tuple[int, bytes]]:
        header = cls._read_exact(conn, 4)
        if header is None:
            return None
        length = int.from_bytes(header[:3], "little")
        payload = cls._read_exact(conn, length)
        if payload is None:
            return None
        return header[3], payload

    @staticmethod
    def _send(conn: socket.socket, seq: int, payload: bytes) -> int:
        conn.sendall(len(payload).to_bytes(3, "little") + bytes([seq]) + payload)
        return seq + 1

    @staticmethod
    def _ok(affected: int = 0) -> bytes:
        return (b"\x00" + encode_lenenc_int(affected) + encode_lenenc_int(0)
                + struct.pack("<HH", 0x0002, 0))  # autocommit status

    @staticmethod
    def _err(code: int, message: str) -> bytes:
        return (b"\xff" + struct.pack("<H", code) + b"#HY000"
                + message.encode("utf-8"))

    @staticmethod
    def _eof() -> bytes:
        return b"\xfe" + struct.pack("<HH", 0, 0x0002)

    # -- connection ----------------------------------------------------------
    def _serve(self, conn: socket.socket) -> None:
        db = sqlite3.connect(self._uri, uri=True)
        db.isolation_level = None
        try:
            scramble = os.urandom(20)
            greeting = (
                b"\x0a" + b"8.0.0-minimysql\x00"
                + struct.pack("<I", 1)  # thread id
                + scramble[:8] + b"\x00"
                + struct.pack("<H", 0xFFFF)  # caps low
                + b"\x2d"  # charset utf8mb4
                + struct.pack("<H", 0x0002)  # status
                + struct.pack("<H", 0x000F)  # caps high (incl PLUGIN_AUTH)
                + bytes([21])  # auth data len (8 + 12 + NUL)
                + b"\x00" * 10
                + scramble[8:] + b"\x00"
                + self.auth_plugin.encode() + b"\x00"
            )
            self._send(conn, 0, greeting)
            pkt = self._read_packet(conn)
            if pkt is None:
                return
            seq, payload = pkt[0] + 1, pkt[1]
            user, token = self._parse_handshake_response(payload)
            plugin = self.auth_plugin
            if self.switch_to:
                # real servers switch when the account's plugin differs
                # from the advertised default — exercises the client's
                # check of the plugin NAME in AuthSwitchRequest
                plugin = self.switch_to
                scramble = os.urandom(20)
                seq = self._send(
                    conn, seq,
                    b"\xfe" + plugin.encode() + b"\x00" + scramble + b"\x00",
                )
                pkt = self._read_packet(conn)
                if pkt is None:
                    return
                seq, token = pkt[0] + 1, pkt[1]
            ok, seq = self._verify_auth(conn, seq, user, token, plugin, scramble)
            if not ok:
                self._send(conn, seq, self._err(1045, f"Access denied for user '{self.user}'"))
                return
            self._send(conn, seq, self._ok())
            self._command_loop(conn, db)
        except OSError:
            pass
        finally:
            db.close()
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _parse_handshake_response(payload: bytes) -> tuple[str, bytes]:
        # HandshakeResponse41: caps(4) maxpacket(4) charset(1) filler(23)
        pos = 4 + 4 + 1 + 23
        end = payload.index(b"\x00", pos)
        user = payload[pos:end].decode("utf-8", "replace")
        pos = end + 1
        token_len = payload[pos]
        token = payload[pos + 1 : pos + 1 + token_len]
        return user, token

    def _verify_auth(
        self, conn: socket.socket, seq: int, user: str, token: bytes,
        plugin: str, scramble: bytes,
    ) -> tuple[bool, int]:
        """Verify ``token`` under ``plugin``; drives the caching_sha2
        AuthMoreData sub-protocol (0x03 fast-auth hit, or the full RSA
        exchange when ``full_auth``). Returns (ok, next_seq)."""
        if user != self.user:
            return False, seq
        if not self.password:
            return token == b"", seq
        if plugin == "mysql_native_password":
            return token == native_password_token(self.password, scramble), seq
        if plugin != "caching_sha2_password":
            return False, seq
        if not self.full_auth:
            if token != sha2_password_token(self.password, scramble):
                return False, seq
            # cache hit: fast_auth_success, then the caller's OK
            return True, self._send(conn, seq, b"\x01\x03")
        # cache miss: demand the non-TLS RSA public-key exchange (ignores
        # the scramble token, exactly like a real server on a cold cache).
        # Stdlib RSA (datasource/_rsa.py): the fake must run in containers
        # without the `cryptography` package, and the CLIENT under test
        # exercises its own preferred implementation either way.
        from gofr_tpu.datasource import _rsa

        if self._rsa_key is None:
            self._rsa_key = _rsa.generate_key(1024)
        seq = self._send(conn, seq, b"\x01\x04")  # perform_full_authentication
        pkt = self._read_packet(conn)
        if pkt is None or pkt[1] != b"\x02":  # client asks for the RSA key
            return False, seq if pkt is None else pkt[0] + 1
        pem = self._rsa_key.public_pem()
        seq = self._send(conn, pkt[0] + 1, b"\x01" + pem)
        pkt = self._read_packet(conn)
        if pkt is None:
            return False, seq
        seq = pkt[0] + 1
        try:
            plain = self._rsa_key.decrypt_oaep_sha1(pkt[1])
        except Exception:
            return False, seq
        return xor_rotating(plain, scramble) == self.password.encode() + b"\x00", seq

    # -- commands ------------------------------------------------------------
    def _command_loop(self, conn: socket.socket, db: sqlite3.Connection) -> None:
        while True:
            pkt = self._read_packet(conn)
            if pkt is None:
                return
            _, payload = pkt
            seq = 1  # responses to a command restart at seq 1
            if not payload or payload[0] == COM_QUIT:
                return
            if payload[0] == COM_PING:
                self._send(conn, seq, self._ok())
                continue
            if payload[0] != COM_QUERY:
                self._send(conn, seq, self._err(1047, f"unknown command 0x{payload[0]:02x}"))
                continue
            sql = _mysql_to_sqlite(payload[1:].decode("utf-8", "replace"))
            try:
                with self._db_lock:
                    cur = db.execute(sql)
                    rows = cur.fetchall()
                    columns = [d[0] for d in cur.description] if cur.description else []
                    affected = cur.rowcount if cur.rowcount >= 0 else 0
            except sqlite3.Error as exc:
                self._send(conn, seq, self._err(1064, str(exc)))
                continue
            if not columns:  # DML/DDL -> OK with affected rows
                self._send(conn, seq, self._ok(affected))
                continue
            seq = self._send(conn, seq, encode_lenenc_int(len(columns)))
            for i, name in enumerate(columns):
                col_type = self._column_type(rows, i)
                charset = 63 if col_type == _TYPE_BLOB else 45  # 63 = binary
                coldef = (
                    encode_lenenc_str(b"def")
                    + encode_lenenc_str(b"") * 3
                    + encode_lenenc_str(name.encode())
                    + encode_lenenc_str(name.encode())
                    + b"\x0c" + struct.pack("<H", charset) + struct.pack("<I", 1024)
                    + bytes([col_type]) + struct.pack("<H", 0) + b"\x00"
                    + b"\x00\x00"
                )
                seq = self._send(conn, seq, coldef)
            seq = self._send(conn, seq, self._eof())
            for row in rows:
                out = b""
                for value in row:
                    if value is None:
                        out += b"\xfb"
                    elif isinstance(value, bytes):
                        out += encode_lenenc_str(value)
                    else:
                        out += encode_lenenc_str(str(value).encode("utf-8"))
                seq = self._send(conn, seq, out)
            self._send(conn, seq, self._eof())

    @staticmethod
    def _column_type(rows: list, index: int) -> int:
        for row in rows:
            v = row[index]
            if v is None:
                continue
            if isinstance(v, bool) or isinstance(v, int):
                return _TYPE_LONGLONG
            if isinstance(v, float):
                return _TYPE_DOUBLE
            if isinstance(v, bytes):
                return _TYPE_BLOB
            return _TYPE_VARSTR
        return _TYPE_VARSTR
