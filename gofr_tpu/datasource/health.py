"""Shared health model aggregated by the container's health endpoint.

Parity: /root/reference/pkg/gofr/datasource/health.go:3-11 — a status string
(UP/DOWN) plus free-form details. Reused for TPU device liveness (SURVEY.md
§2 #19).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

UP = "UP"
DOWN = "DOWN"


@dataclass
class Health:
    status: str = DOWN
    details: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"status": self.status, "details": self.details}
