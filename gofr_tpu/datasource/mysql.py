"""MySQL client: a from-scratch wire-protocol implementation.

Parity: /root/reference/pkg/gofr/datasource/sql/sql.go:19-37 — the reference
is a MySQL framework (``NewMYSQL`` builds the DSN and pings). This
environment ships no MySQL driver, so the client speaks the documented
protocol directly: handshake v10, ``caching_sha2_password`` (the MySQL 8
default, incl. the non-TLS RSA full-auth exchange) and
``mysql_native_password`` auth with AuthSwitch between them,
``COM_QUERY`` with text resultsets, ``COM_PING`` health. The surface
mirrors datasource/sql.py's DB (logged query/execute/tx/select) so
``DB_DIALECT=mysql`` swaps in transparently behind the container.

Scope: classic EOF framing (CLIENT_DEPRECATE_EOF not negotiated), text
protocol only — parameters interpolate client-side with proper escaping
(the same approach as go-sql-driver's interpolateParams fast path). One
socket guarded by a mutex; MySQL connections are sequential by protocol.
No TLS: full auth on caching_sha2 always takes the RSA public-key path
(what go-sql-driver does with allowCleartextPasswords off on plain TCP).

Tested against datasource/minimysql.py, an in-process fake speaking the
same wire format (the reference tests MySQL with sqlmock the same way,
SURVEY.md §4) — including a fake demanding caching_sha2 full auth.
"""

from __future__ import annotations

import dataclasses
import hashlib
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence

from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.datasource.sql import SQLLog, to_snake_case
from gofr_tpu.tracing import get_tracer

# capability flags (protocol constants)
CLIENT_LONG_PASSWORD = 0x1
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000

COM_QUIT, COM_QUERY, COM_PING = 0x01, 0x03, 0x0E

# column type codes (text protocol conversion)
_INT_TYPES = {0x01, 0x02, 0x03, 0x08, 0x09, 0x0D}  # tiny..longlong, year
_FLOAT_TYPES = {0x04, 0x05, 0xF6}  # float, double, newdecimal
_BLOB_TYPE = 0xFC


class MySQLError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"MySQL error {code}: {message}")
        self.code = code
        self.message = message


def native_password_token(password: str, scramble: bytes) -> bytes:
    """mysql_native_password: SHA1(pass) XOR SHA1(scramble + SHA1(SHA1(pass)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(scramble + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def sha2_password_token(password: str, scramble: bytes) -> bytes:
    """caching_sha2_password fast-auth scramble:
    SHA256(pass) XOR SHA256(SHA256(SHA256(pass)) + scramble)."""
    if not password:
        return b""
    h1 = hashlib.sha256(password.encode()).digest()
    h2 = hashlib.sha256(hashlib.sha256(h1).digest() + scramble).digest()
    return bytes(a ^ b for a, b in zip(h1, h2))


def xor_rotating(data: bytes, key: bytes) -> bytes:
    """XOR ``data`` with ``key`` repeated — the pre-RSA whitening MySQL
    applies to the password in the caching_sha2 full-auth exchange."""
    return bytes(b ^ key[i % len(key)] for i, b in enumerate(data))


def rsa_encrypt_password(password: str, scramble: bytes, pem: bytes) -> bytes:
    """Non-TLS full auth: RSA-OAEP(SHA1)-encrypt the nonce-whitened
    NUL-terminated password with the server's public key. Prefers the
    audited ``cryptography`` implementation; containers without it
    (the serving image ships no OpenSSL bindings) fall back to the
    stdlib OAEP in ``datasource/_rsa.py`` — same bytes on the wire."""
    plain = xor_rotating(password.encode() + b"\x00", scramble)
    try:
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import padding as _pad
    except ImportError:
        from gofr_tpu.datasource import _rsa

        return _rsa.oaep_encrypt(_rsa.load_public_key(pem), plain)

    key = serialization.load_pem_public_key(pem)
    return key.encrypt(
        plain,
        _pad.OAEP(mgf=_pad.MGF1(hashes.SHA1()), algorithm=hashes.SHA1(), label=None),
    )


def _lenenc_int(data: bytes, pos: int) -> tuple[int, int]:
    first = data[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(data[pos + 1 : pos + 4], "little"), pos + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9
    raise MySQLError(2027, f"malformed length-encoded int 0x{first:02x}")


def _lenenc_str(data: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = _lenenc_int(data, pos)
    return data[pos : pos + n], pos + n


def encode_lenenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little")
    return b"\xfe" + struct.pack("<Q", n)


def encode_lenenc_str(s: bytes) -> bytes:
    return encode_lenenc_int(len(s)) + s


def escape_literal(value: Any) -> str:
    """Client-side parameter interpolation (text protocol has no binds)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (bytes, bytearray)):
        return "x'" + bytes(value).hex() + "'"
    s = str(value)
    s = (
        s.replace("\\", "\\\\").replace("'", "\\'").replace('"', '\\"')
        .replace("\x00", "\\0").replace("\n", "\\n").replace("\r", "\\r")
        .replace("\x1a", "\\Z")
    )
    return f"'{s}'"


def interpolate(query: str, args: Sequence[Any]) -> str:
    """Replace ``?`` placeholders outside string literals."""
    if not args:
        return query
    out: list[str] = []
    it = iter(args)
    in_str: Optional[str] = None
    i = 0
    while i < len(query):
        ch = query[i]
        if in_str:
            if ch == "\\":
                out.append(query[i : i + 2])
                i += 2
                continue
            if ch == in_str:
                in_str = None
            out.append(ch)
        elif ch in ("'", '"'):
            in_str = ch
            out.append(ch)
        elif ch == "?":
            try:
                out.append(escape_literal(next(it)))
            except StopIteration:
                raise MySQLError(2034, "not enough parameters for query") from None
        else:
            out.append(ch)
        i += 1
    return "".join(out)


class Row:
    """Result row with sqlite3.Row-compatible access: by index, by column
    name, and ``.keys()`` (datasource/sql.py's ``select`` reflection uses
    exactly this surface)."""

    __slots__ = ("_columns", "_values")

    def __init__(self, columns: Sequence[str], values: Sequence[Any]):
        self._columns = columns
        self._values = values

    def keys(self) -> Sequence[str]:
        return list(self._columns)

    def __getitem__(self, key: Any) -> Any:
        if isinstance(key, int):
            return self._values[key]
        try:
            return self._values[self._columns.index(key)]
        except ValueError:
            raise KeyError(key) from None

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self):
        return iter(self._values)

    def __repr__(self) -> str:
        return f"Row({dict(zip(self._columns, self._values))!r})"


class _Conn:
    """One authenticated connection: packet framing + command round trips."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._seq = 0
        self._handshake(user, password, database)

    # -- framing -------------------------------------------------------------
    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise MySQLError(2013, "lost connection during query")
            buf += chunk
        return buf

    def read_packet(self) -> bytes:
        payload = b""
        while True:
            header = self._read_exact(4)
            length = int.from_bytes(header[:3], "little")
            self._seq = (header[3] + 1) % 256
            payload += self._read_exact(length)
            if length < 0xFFFFFF:  # 16MB-1 means a continuation follows
                return payload

    def write_packet(self, payload: bytes) -> None:
        header = len(payload).to_bytes(3, "little") + bytes([self._seq])
        self._seq = (self._seq + 1) % 256
        self.sock.sendall(header + payload)

    # -- handshake -----------------------------------------------------------
    @staticmethod
    def _auth_token(plugin: str, password: str, scramble: bytes) -> bytes:
        """Scramble token for the plugin the SERVER named — never assume
        (a default-configured MySQL 8 advertises caching_sha2_password;
        older servers and explicit accounts use mysql_native_password)."""
        if plugin == "mysql_native_password":
            return native_password_token(password, scramble)
        if plugin == "caching_sha2_password":
            return sha2_password_token(password, scramble)
        raise MySQLError(2059, f"authentication plugin '{plugin}' not supported")

    def _handshake(self, user: str, password: str, database: str) -> None:
        greeting = self.read_packet()
        if greeting and greeting[0] == 0xFF:
            raise self._err(greeting)
        if not greeting or greeting[0] != 0x0A:
            raise MySQLError(2012, f"unsupported handshake version {greeting[:1]!r}")
        pos = 1
        end = greeting.index(b"\x00", pos)
        self.server_version = greeting[pos:end].decode("utf-8", "replace")
        pos = end + 1
        pos += 4  # thread id
        scramble = greeting[pos : pos + 8]
        pos += 8 + 1  # + filler
        pos += 2 + 1 + 2 + 2  # caps_lo, charset, status, caps_hi
        auth_len = greeting[pos] if pos < len(greeting) else 0
        pos += 1 + 10  # + reserved
        if auth_len > 8 and pos < len(greeting):
            # part 2 occupies max(13, auth_len-8) bytes, of which the first
            # 12 extend the nonce (the 13th is a NUL)
            part2_len = max(13, auth_len - 8)
            scramble += greeting[pos : pos + 12]
            pos += part2_len
        plugin = "mysql_native_password"
        if pos < len(greeting):
            nul = greeting.find(b"\x00", pos)
            name = greeting[pos : nul if nul >= 0 else len(greeting)]
            if name:
                plugin = name.decode("utf-8", "replace")

        caps = (
            CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
            | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH
        )
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        token = self._auth_token(plugin, password, scramble)
        payload = (
            struct.pack("<IIB23x", caps, 1 << 24, 45)  # caps, max packet, utf8mb4
            + user.encode() + b"\x00"
            + bytes([len(token)]) + token
            + ((database.encode() + b"\x00") if database else b"")
            + plugin.encode() + b"\x00"
        )
        self.write_packet(payload)
        self._auth_loop(password, scramble, plugin)

    def _auth_loop(self, password: str, scramble: bytes, plugin: str) -> None:
        """Drive auth to OK: AuthSwitchRequest (re-scramble under the
        plugin the server NAMES), caching_sha2 AuthMoreData (0x03 fast-auth
        hit; 0x04 full auth via the RSA public-key exchange)."""
        while True:
            reply = self.read_packet()
            if not reply:
                raise MySQLError(2013, "connection closed during auth")
            if reply[0] == 0x00:
                return
            if reply[0] == 0xFF:
                raise self._err(reply)
            if reply[0] == 0xFE:  # AuthSwitchRequest
                end = reply.index(b"\x00", 1)
                plugin = reply[1:end].decode("utf-8", "replace")
                scramble = reply[end + 1 :]
                # exactly ONE trailing NUL terminates the scramble — rstrip
                # would also eat random scramble bytes that happen to be 0x00
                if scramble.endswith(b"\x00"):
                    scramble = scramble[:-1]
                self.write_packet(self._auth_token(plugin, password, scramble))
                continue
            if reply[0] == 0x01 and plugin == "caching_sha2_password":
                status = reply[1:2]
                if status == b"\x03":  # fast_auth_success; OK follows
                    continue
                if status == b"\x04":  # perform_full_authentication
                    # no TLS on this socket: ask for the server RSA key and
                    # send the nonce-whitened password encrypted under it
                    self.write_packet(b"\x02")
                    key_pkt = self.read_packet()
                    if not key_pkt or key_pkt[0] != 0x01:
                        raise MySQLError(
                            2012,
                            f"expected RSA key, got 0x{key_pkt[:1].hex()}",
                        )
                    self.write_packet(
                        rsa_encrypt_password(password, scramble, key_pkt[1:])
                    )
                    continue
                raise MySQLError(2012, f"unexpected auth state 0x{status.hex()}")
            raise MySQLError(2012, f"unexpected auth reply 0x{reply[:1].hex()}")

    @staticmethod
    def _err(payload: bytes) -> MySQLError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:]
        if msg[:1] == b"#":  # sql state marker + 5 chars
            msg = msg[6:]
        return MySQLError(code, msg.decode("utf-8", "replace"))

    # -- commands ------------------------------------------------------------
    def query(self, sql: str) -> tuple[list[str], list[Row], int]:
        """Returns (columns, rows, affected). OK responses (DML/DDL) give
        ([], [], affected_rows)."""
        self._seq = 0
        self.write_packet(bytes([COM_QUERY]) + sql.encode("utf-8"))
        first = self.read_packet()
        if first and first[0] == 0xFF:
            raise self._err(first)
        if first and first[0] == 0x00:  # OK packet
            affected, _ = _lenenc_int(first, 1)
            return [], [], affected
        n_cols, _ = _lenenc_int(first, 0)
        columns: list[str] = []
        types: list[tuple[int, int]] = []  # (type, charset)
        for _ in range(n_cols):
            col = self.read_packet()
            pos = 0
            for _ in range(4):  # catalog, schema, table, org_table
                _, pos = _lenenc_str(col, pos)
            name, pos = _lenenc_str(col, pos)
            _, pos = _lenenc_str(col, pos)  # org_name
            pos += 1  # fixed-length-fields marker (0x0c)
            charset = struct.unpack_from("<H", col, pos)[0]
            pos += 2 + 4  # charset, column length
            types.append((col[pos], charset))
            columns.append(name.decode("utf-8", "replace"))
        eof = self.read_packet()
        if eof and eof[0] == 0xFF:
            raise self._err(eof)
        rows: list[Row] = []
        while True:
            pkt = self.read_packet()
            if pkt and pkt[0] == 0xFF:
                raise self._err(pkt)
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:  # EOF
                break
            values: list[Any] = []
            pos = 0
            for t, charset in types:
                if pkt[pos] == 0xFB:  # NULL
                    values.append(None)
                    pos += 1
                    continue
                raw, pos = _lenenc_str(pkt, pos)
                if t in _INT_TYPES:
                    values.append(int(raw))
                elif t in _FLOAT_TYPES:
                    values.append(float(raw))
                elif t == _BLOB_TYPE and charset == 63:
                    # charset 63 = binary: BLOB; TEXT shares the wire type
                    # but carries a real charset and decodes to str
                    values.append(raw)
                else:
                    values.append(raw.decode("utf-8", "replace"))
            rows.append(Row(columns, values))
        return columns, rows, 0

    def ping(self) -> bool:
        self._seq = 0
        self.write_packet(bytes([COM_PING]))
        reply = self.read_packet()
        if reply and reply[0] == 0xFF:
            raise self._err(reply)
        return bool(reply) and reply[0] == 0x00

    def close(self) -> None:
        try:
            self._seq = 0
            self.write_packet(bytes([COM_QUIT]))
        except Exception:
            pass
        try:
            self.sock.close()
        except Exception:
            pass


class MySQLDB:
    """Logged MySQL wrapper with the datasource/sql.py DB surface (query /
    query_row / execute / execute_many / begin / select / select_one /
    select_value / health_check / close). Parity: sql/db.go:15-253.

    Connections are per-thread (exactly like the sqlite DB): MySQL wire
    sessions are sequential and transactions are connection-scoped, so a
    shared socket would interleave one handler thread's BEGIN with
    another's statements. A connection that hits an I/O or protocol error
    is discarded (the wire may hold a half-read resultset — desynced
    forever); the thread reconnects on its next call."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, logger: Any = None):
        self.host, self.port, self.database = host, port, database
        self._user, self._password = user, password
        self.logger = logger
        self._local = threading.local()
        self._all: list[_Conn] = []
        self._all_lock = threading.Lock()
        self.server_version = ""
        self._get_conn()  # connect + auth eagerly: container logs-and-degrades

    def _get_conn(self) -> _Conn:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = _Conn(self.host, self.port, self._user, self._password,
                         self.database)
            self.server_version = conn.server_version
            self._local.conn = conn
            with self._all_lock:
                self._all.append(conn)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            with self._all_lock:
                if conn in self._all:
                    self._all.remove(conn)
            conn.close()

    def _timed(self, query: str, fn):
        start = time.perf_counter()
        span = get_tracer().start_span("sql-query", activate=False)
        span.set_tag("db.system", "mysql")
        span.set_tag("db.statement", query[:256])
        try:
            return fn()
        finally:
            span.end()
            if self.logger is not None:
                elapsed_us = int((time.perf_counter() - start) * 1e6)
                self.logger.debug(SQLLog(query=query[:256], duration_us=elapsed_us))

    def _run(self, query: str, args: Sequence[Any]) -> tuple[list[str], list[Row], int]:
        sql = interpolate(query, args)
        try:
            return self._get_conn().query(sql)
        except MySQLError as exc:
            # ONLY 2000-2999 are client-side CR_* codes (desynced wire);
            # 3000+ are server errors on a healthy connection — tearing it
            # down would break the thread's open transaction
            if 2000 <= exc.code < 3000:
                self._drop_conn()
            raise
        except OSError:
            self._drop_conn()
            raise

    # -- DB surface ----------------------------------------------------------
    def query(self, query: str, *args: Any) -> list[Row]:
        return self._timed(query, lambda: self._run(query, args)[1])

    def query_row(self, query: str, *args: Any) -> Optional[Row]:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def execute(self, query: str, *args: Any) -> int:
        return self._timed(query, lambda: self._run(query, args)[2])

    def execute_many(self, query: str, rows: Sequence[Sequence[Any]]) -> int:
        def run() -> int:
            return sum(self._run(query, r)[2] for r in rows)

        return self._timed(f"{query} [batch x{len(rows)}]", run)

    class _Tx:
        def __init__(self, db: "MySQLDB"):
            self.db = db

        def __enter__(self) -> "MySQLDB._Tx":
            self.db.execute("BEGIN")
            return self

        def query(self, query: str, *args: Any) -> list[Row]:
            return self.db.query(query, *args)

        def execute(self, query: str, *args: Any) -> int:
            return self.db.execute(query, *args)

        def __exit__(self, exc_type, exc, tb) -> None:
            self.db.execute("COMMIT" if exc_type is None else "ROLLBACK")

    def begin(self) -> "MySQLDB._Tx":
        return MySQLDB._Tx(self)

    def select(self, into: type, query: str, *args: Any) -> Any:
        rows = self.query(query, *args)
        if not dataclasses.is_dataclass(into):
            raise TypeError(f"select target must be a dataclass, got {into!r}")
        field_by_column = {
            f.metadata.get("db", to_snake_case(f.name)): f.name
            for f in dataclasses.fields(into)
        }
        out = []
        for row in rows:
            kwargs = {}
            for column in row.keys():
                field = field_by_column.get(column)
                if field is not None:
                    kwargs[field] = row[column]
            out.append(into(**kwargs))
        return out

    def select_one(self, into: type, query: str, *args: Any) -> Optional[Any]:
        result = self.select(into, query, *args)
        return result[0] if result else None

    def select_value(self, query: str, *args: Any) -> Any:
        row = self.query_row(query, *args)
        return None if row is None else row[0]

    def health_check(self) -> Health:
        try:
            start = time.perf_counter()
            try:
                self._get_conn().ping()
            except (OSError, MySQLError):
                self._drop_conn()
                raise
            latency_us = int((time.perf_counter() - start) * 1e6)
            return Health(UP, {
                "host": f"{self.host}:{self.port}", "database": self.database,
                "dialect": "mysql", "latency_us": latency_us,
                "server_version": self.server_version,
            })
        except Exception as exc:
            return Health(DOWN, {
                "host": f"{self.host}:{self.port}", "database": self.database,
                "dialect": "mysql", "error": str(exc),
            })

    def close(self) -> None:
        with self._all_lock:
            for conn in self._all:
                conn.close()
            self._all.clear()
