"""Dependency-injection container: logger, config, datasources, service
clients, metrics, and the TPU device.

Parity: /root/reference/pkg/gofr/container/container.go:19-95 — config-driven
conditional wiring (Redis when REDIS_HOST, SQL when DB host/name configured,
:48-86), connect errors logged but NEVER fatal (the app runs degraded,
:60-64, :80-85), health aggregation (:26-38), ``GetHTTPService`` (:93).
TPU-native additions: a ``tpu`` member wired from TPU_*/MODEL_* config keys
and a metrics registry (the reference has none, SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any, Optional

from gofr_tpu.config import Config
from gofr_tpu.datasource.health import DOWN, UP, Health
from gofr_tpu.logging import new_logger
from gofr_tpu.metrics import Registry
from gofr_tpu.postmortem import PostmortemStore
from gofr_tpu.slo import DEFAULT_TARGETS, SloEngine
from gofr_tpu.telemetry import FlightRecorder, TenantLedger, exemplar_provider
from gofr_tpu.timebase import TimebaseSampler


class Container:
    def __init__(self, config: Config, wire: bool = True):
        self.config = config
        self.logger = new_logger(config.get_or_default("LOG_LEVEL", "INFO"))
        self.metrics = Registry(
            # cardinality guard: overflow increments
            # gofr_tpu_metrics_dropped_series_total{metric} instead of
            # growing the scrape unboundedly under scanner traffic
            max_series=int(
                config.get_or_default("METRICS_MAX_SERIES", "1000")
            ),
            # histogram observations self-correlate: OpenMetrics bucket
            # exemplars carry the active trace_id/dispatch_id
            exemplar_provider=(
                exemplar_provider
                if config.get_or_default("METRICS_EXEMPLARS", "on") != "off"
                else None
            ),
        )
        # bounded per-tenant usage metering (space-saving sketch behind
        # /admin/tenants): exact for the top-K heavy hitters, aggregated
        # into ~other beyond — NEVER a per-tenant Prometheus series
        self.tenants = TenantLedger(
            size=int(config.get_or_default("TENANT_LEDGER_SIZE", "256")),
            metrics=self.metrics,
        )
        # request flight recorder: per-request inference telemetry backing
        # /admin/requests and /admin/slo plus the wide-event request log
        self.telemetry = FlightRecorder(
            capacity=int(config.get_or_default("FLIGHT_RECORDER_SIZE", "512")),
            keep=int(config.get_or_default("FLIGHT_RECORDER_KEEP", "128")),
            slow_threshold_s=float(
                config.get_or_default("FLIGHT_SLOW_MS", "2000")
            ) / 1000.0,
            logger=self.logger,
            tenants=self.tenants,
        )
        # telemetry timebase: the metric history ring behind
        # /admin/timeseries and /admin/overview (and the trend data every
        # postmortem bundle carries)
        self.timebase = TimebaseSampler(
            self.metrics,
            interval_s=float(
                config.get_or_default("TIMEBASE_INTERVAL_S", "5")
            ),
            window_s=float(
                config.get_or_default("TIMEBASE_WINDOW_S", "900")
            ),
            logger=self.logger,
            start=config.get_or_default("TIMEBASE_ENABLED", "on") != "off",
        )
        # postmortem black box: wedge/crash/manual flight-data bundles
        # (the engine listener attaches in _wire_tpu once a device exists)
        self.postmortem = PostmortemStore(
            self,
            directory=config.get_or_default("POSTMORTEM_DIR", "./postmortems"),
            keep=int(config.get_or_default("POSTMORTEM_KEEP", "20")),
            min_interval_s=float(
                config.get_or_default("POSTMORTEM_MIN_INTERVAL_S", "30")
            ),
            snapshots=int(
                config.get_or_default("POSTMORTEM_SNAPSHOTS", "60")
            ),
            logger=self.logger,
        )
        if config.get("POSTMORTEM_DIR"):
            # crash + fatal-signal hooks are process-global: armed only on
            # the operator's explicit POSTMORTEM_DIR opt-in (wedge and
            # manual bundles work either way)
            self.postmortem.install_crash_hooks()
        self.services: dict[str, Any] = {}
        self.redis: Optional[Any] = None
        self.db: Optional[Any] = None
        self.tpu: Optional[Any] = None
        # the fleet front door, when this process is a router
        # (gofr_tpu.fleet.wire_fleet sets it): readiness reads its
        # draining flag, App.shutdown drains it before stopping servers
        self.fleet: Optional[Any] = None
        self._handler_pool: Optional[Any] = None
        if wire:
            self._wire_redis()
            self._wire_sql()
            self._wire_tpu()
        # SLO engine: error budgets + multi-window burn-rate alerting over
        # the flight recorder and the timebase's shed counters. Wired
        # AFTER the device so its verdicts land in the SAME anomaly ring
        # as the dispatch cost model (one /admin/anomalies surface);
        # router/bare processes get the engine's own host-side ring. A
        # malformed SLO_TARGETS fails the boot with the clause named — an
        # objective silently not alerting is the one failure mode this
        # layer must not have.
        self.slo: Optional[SloEngine] = None
        if config.get_or_default("SLO", "on") != "off":
            costmodel = getattr(self.tpu, "costmodel", None)
            self.slo = SloEngine(
                self.telemetry,
                timebase=self.timebase,
                metrics=self.metrics,
                logger=self.logger,
                targets=config.get_or_default("SLO_TARGETS", DEFAULT_TARGETS),
                ring=getattr(costmodel, "ring", None),
                fast_s=float(config.get_or_default("SLO_BURN_FAST_S", "300")),
                fast_long_s=float(
                    config.get_or_default("SLO_BURN_FAST_LONG_S", "3600")
                ),
                slow_s=float(
                    config.get_or_default("SLO_BURN_SLOW_S", "21600")
                ),
                slow_long_s=float(
                    config.get_or_default("SLO_BURN_SLOW_LONG_S", "259200")
                ),
                fast_rate=float(
                    config.get_or_default("SLO_BURN_FAST_RATE", "14.4")
                ),
                slow_rate=float(
                    config.get_or_default("SLO_BURN_SLOW_RATE", "6")
                ),
                interval_s=float(
                    config.get_or_default("SLO_EVAL_INTERVAL_S", "15")
                ),
                start=True,
            )

    # -- conditional wiring (parity: container.go:48-86) ---------------------
    def _wire_redis(self) -> None:
        host = self.config.get("REDIS_HOST")
        if not host:
            return
        port = int(self.config.get_or_default("REDIS_PORT", "6379"))
        try:
            from gofr_tpu.datasource.redis import new_client

            self.redis = new_client(host, port, self.logger)
            self.logger.infof("connected to redis at %s:%s", host, port)
        except Exception as exc:  # non-fatal degraded startup
            self.logger.errorf("could not connect to redis at %s:%s, error: %s", host, port, exc)
            self.redis = None

    def _wire_sql(self) -> None:
        name = self.config.get("DB_NAME")
        host = self.config.get("DB_HOST")
        if not name and not host:
            return
        try:
            from gofr_tpu.datasource.sql import new_sql

            self.db = new_sql(self.config, self.logger)
            self.logger.infof("connected to database '%s'", name or host)
        except Exception as exc:
            self.logger.errorf("could not connect to database, error: %s", exc)
            self.db = None

    def _wire_tpu(self) -> None:
        enabled = (self.config.get_or_default("TPU_ENABLED", "") or "").lower()
        model = self.config.get("MODEL_NAME")
        if enabled not in ("true", "1", "yes") and not model:
            return
        try:
            from gofr_tpu.tpu import new_device

            # the multi-host join happens inside the device BOOT path
            # (before its device probe): jax.distributed.initialize blocks
            # until peers arrive, and blocking container wiring would hang
            # the server before it listens — the exact failure
            # TPU_BOOT=background exists to avoid
            self.tpu = new_device(self.config, self.logger, self.metrics)
            # a wedged or boot-failed engine writes its own black-box
            # bundle the moment the state machine says so
            self.postmortem.watch_engine(self.tpu.engine)
            # the recovery supervisor writes its bundle SYNCHRONOUSLY
            # before quarantining the stuck dispatch (the quarantine
            # destroys the live watchdog evidence a bundle must carry;
            # rate limiting dedupes against the listener's own write)
            self.tpu.recovery.postmortem = (
                lambda detail: self.postmortem.write(
                    reason="wedged", detail=detail
                )
            )
            if self.config.get_or_default("TPU_BOOT", "") == "background":
                # the device logs its describe() line once probe+warmup end
                self.logger.infof(
                    "TPU datasource booting in background (model=%s); "
                    "readiness at /.well-known/ready",
                    self.config.get("MODEL_NAME"),
                )
            else:
                self.logger.infof("TPU datasource ready: %s", self.tpu.describe())
        except Exception as exc:
            self.logger.errorf("could not initialize TPU datasource, error: %s", exc)
            self.tpu = None

    # -- health (parity: container.go:26-38) ---------------------------------
    def health(self) -> dict[str, Any]:
        details: dict[str, Any] = {}
        overall = UP
        for name, source in (("redis", self.redis), ("sql", self.db), ("tpu", self.tpu)):
            if source is None:
                continue
            try:
                h: Health = source.health_check()
            except Exception as exc:
                h = Health(DOWN, {"error": str(exc)})
            details[name] = h.to_dict()
            if h.status != UP:
                overall = DOWN
        # NOTE: registered service clients are NOT probed here (parity:
        # container.go:26-38 checks only datasources). Probing downstreams
        # from the health endpoint recurses when a service points at this
        # same app (the reference example does exactly that).
        return {"status": overall, "details": details}

    def get_http_service(self, name: str) -> Any:
        """Parity: container.go:93 — nil-safe lookup."""
        return self.services.get(name)

    @property
    def handler_executor(self) -> Any:
        """Dedicated thread pool for SYNC handlers (HANDLER_THREADS,
        default 64). asyncio's default executor is sized cpu_count+4 —
        five threads on a 1-CPU serving VM — and sync handlers BLOCK (a
        token generation holds its thread for seconds), so the default
        silently caps concurrent requests at the executor size: measured
        8 decode streams collapsing to 5 concurrent + 3 queued for
        seconds. Blocking handlers need I/O-sized pools, not CPU-sized."""
        if self._handler_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            workers = int(self.config.get_or_default("HANDLER_THREADS", "64"))
            self._handler_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="gofr-handler"
            )
        return self._handler_pool

    def close(self) -> None:
        if self.slo is not None:
            self.slo.close()  # stops the gofr-slo evaluator thread
        if self.fleet is not None:
            try:
                self.fleet.close()  # stops the health-prober thread
            except Exception:
                pass
        for source in (self.redis, self.db, self.tpu):
            closer = getattr(source, "close", None)
            if closer:
                try:
                    closer()
                except Exception:
                    pass
        self.timebase.close()
        self.postmortem.detach()
        if self._handler_pool is not None:
            self._handler_pool.shutdown(wait=False)


def new_container(config: Config) -> Container:
    """Parity: container/container.go:40."""
    return Container(config)
