"""gofr_tpu — a TPU-native microservice framework.

Built from scratch with the capabilities of GoFr (reference surveyed in
SURVEY.md): one ``App`` object yields an HTTP server, a gRPC server, or a
CLI app sharing a single transport-agnostic handler signature
``handler(ctx) -> result``; an injected container provides env-file config,
leveled structured logging, tracing, Redis and SQL datasources, inter-service
HTTP clients, and health checks.

On top of that GoFr-equivalent core, TPU is a first-class inference
datasource: ``gofr_tpu.tpu`` compiles JAX/pjit models (Pallas kernels for the
hot ops), handlers enqueue dynamically batched forward passes via
``ctx.tpu``, metrics export device utilization, and the health probe checks
device liveness.

Parity map: /root/reference/pkg/gofr (see SURVEY.md §2 for the full
component inventory this package mirrors).
"""

from gofr_tpu.version import __version__

__all__ = ["App", "Context", "new", "new_cmd", "__version__"]


def __getattr__(name):  # PEP 562: lazy so leaf modules import without transports
    try:
        if name in ("App", "new", "new_cmd"):
            from gofr_tpu import app

            return getattr(app, name)
        if name == "Context":
            from gofr_tpu.context import Context

            return Context
    except ImportError as exc:
        raise AttributeError(f"gofr_tpu.{name} unavailable: {exc}") from exc
    raise AttributeError(f"module 'gofr_tpu' has no attribute {name!r}")
