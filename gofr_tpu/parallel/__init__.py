"""Parallelism: device meshes, parameter sharding rules, and collectives.

The reference has zero distributed components (SURVEY.md §2 "parallelism
strategies: absent") — scale is Kubernetes replicas. The TPU build makes
parallelism first-class the XLA way: a named Mesh (dp/fsdp/sp/tp axes),
NamedSharding PartitionSpec trees over the model's param dicts, and jit —
GSPMD inserts the collectives (psum/all-gather/reduce-scatter) over ICI.
Host-to-host coordination rides the framework's own gRPC/HTTP service layer
over DCN (SURVEY.md §2 "distributed communication backend").
"""

from gofr_tpu.parallel.expert import (
    make_moe_forward,
    make_moe_loss,
    moe_param_specs,
    place_moe_params,
)
from gofr_tpu.parallel.mesh import axis_size, make_mesh, mesh_shape_for
from gofr_tpu.parallel.pipeline import (
    make_pipeline_forward,
    make_pipeline_loss,
    place_pipeline_params,
)
from gofr_tpu.parallel.ring import make_ring_forward, make_ring_loss, ring_attention
from gofr_tpu.parallel.ulysses import (
    make_ulysses_forward,
    make_ulysses_loss,
    ulysses_attention,
)
from gofr_tpu.parallel.sharding import (
    batch_spec,
    cache_specs,
    param_specs,
    shard_params,
)

__all__ = [
    "make_mesh", "mesh_shape_for", "axis_size",
    "param_specs", "batch_spec", "cache_specs", "shard_params",
    "ring_attention", "make_ring_forward", "make_ring_loss",
    "ulysses_attention", "make_ulysses_forward", "make_ulysses_loss",
    "make_pipeline_forward", "make_pipeline_loss", "place_pipeline_params",
    "make_moe_forward", "make_moe_loss", "moe_param_specs", "place_moe_params",
]
