"""PartitionSpec rules for the model families.

Name-based rules over the plain-dict param trees (the reason models keep
params as dicts, models/__init__.py): given a param tree, produce a
matching tree of jax.sharding.PartitionSpec.

Transformer layout (stacked layer weights have a leading n_layers axis that
is never sharded):

| weight              | shape              | spec                      |
|---------------------|--------------------|---------------------------|
| embed               | [V, D]             | P(None, 'tp')             |
| lm_head             | [D, V]             | P('fsdp', 'tp')           |
| wq / wk / wv        | [L, D, H*hd]       | P(None, 'fsdp', 'tp')     |
| wo                  | [L, D, D]          | P(None, 'tp', 'fsdp')     |
| w_gate / w_up       | [L, D, F]          | P(None, 'fsdp', 'tp')     |
| w_down              | [L, F, D]          | P(None, 'tp', 'fsdp')     |
| norms / biases      | [...]              | replicated                |

This is the Megatron pattern: column-parallel in-projections, row-parallel
out-projections — XLA inserts the psum on the row-parallel output. ``fsdp``
shards the other matmul dimension (ZeRO-3); gradients reduce-scatter over
``fsdp`` and all-reduce over ``dp`` automatically under jit.

Quantized weights shard like the underlying weight: int8 {"q", "scale"}
(and w8a8 {"q8", "scale"}) scales follow the output axis; int4
{"q4", "scale"} scales take the full
weight spec (their group axis follows the input axis).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# name -> (spec for plain 2-D [in, out], stacked 3-D gets None prepended)
_COL_PARALLEL = {"wq", "wk", "wv", "w_gate", "w_up", "w_in", "wqkv"}  # out dim -> tp
_ROW_PARALLEL = {"wo", "w_down", "w_out"}  # in dim -> tp


def _spec_for(name: str, ndim: int) -> P:
    if name == "embed" or name == "tok_embed":
        # vocab axis replicated: token gather over a vocab-sharded table
        # is ambiguous for GSPMD (would need collective gather); hidden
        # axis over tp keeps activations sharded from the start
        return P(None, "tp")
    if name == "lm_head":
        return P("fsdp", "tp")
    if name == "pos_embed":
        return P(None, "fsdp")
    if name in _COL_PARALLEL:
        base = ("fsdp", "tp")
    elif name in _ROW_PARALLEL:
        base = ("tp", "fsdp")
    else:  # norms, biases, scalars: replicate
        return P()
    pad = (None,) * (ndim - 2)
    return P(*pad, *base)


def param_specs(params: Any, _name: str = "") -> Any:
    """Mirror a param tree with PartitionSpecs (name-based rules)."""

    def walk(tree: Any, name: str) -> Any:
        if isinstance(tree, dict):
            keys = set(tree)
            if keys == {"w", "lora_a", "lora_b", "lora_scale"}:
                # LoRA leaf: base shards by its own rule under the same
                # name; A shards its in axis, B its out axis, like the
                # weight (the rank axis replicates)
                w_spec = walk(tree["w"], name)
                q_spec = _spec_for(name, tree["lora_a"].ndim)
                pad = (None,) * (tree["lora_a"].ndim - 2)
                a_in = q_spec[-2] if len(q_spec) >= 2 else None
                b_out = q_spec[-1] if len(q_spec) >= 1 else None
                return {
                    "w": w_spec,
                    "lora_a": P(*pad, a_in, None),
                    "lora_b": P(*pad, None, b_out),
                    "lora_scale": P(),
                }
            if keys in ({"q", "scale"}, {"q4", "scale"}, {"q8", "scale"}):
                # packed leaf pair; w8a8 ({"q8"}) shards exactly like int8
                q_key = next(k for k in ("q", "q4", "q8") if k in tree)
                q_spec = _spec_for(name, tree[q_key].ndim)
                if q_key == "q4" and tree["scale"].shape[-2] > 1:
                    # int4 scale [..., groups, out]: the group axis follows
                    # the weight's in axis, so it takes the SAME spec (a
                    # row-parallel weight shards its groups over tp). A
                    # single-group scale (group clamped to a small dim)
                    # degenerates to the int8 rule below — a size-1 axis
                    # cannot split
                    return {q_key: q_spec, "scale": q_spec}
                # int8 scale is [..., 1, out]: only the out axis is
                # shardable (the size-1 axis cannot split)
                tail = q_spec[-1] if len(q_spec) > 0 else None
                scale_pad = (None,) * (tree["scale"].ndim - 1)
                return {q_key: q_spec, "scale": P(*scale_pad, tail)}
            return {k: walk(v, k) for k, v in tree.items()}
        return _spec_for(name, getattr(tree, "ndim", 0))

    return walk(params, _name)


def batch_spec(sp: bool = False) -> P:
    """Token batches [B, S]: batch over dp(+fsdp), optionally sequence over
    sp (ring attention path)."""
    return P(("dp", "fsdp"), "sp" if sp else None)


def cache_specs(cache: Any) -> Any:
    """KV cache [L, B, S, Hkv, hd]: batch over dp(+fsdp), kv heads over tp."""
    return {
        "k": P(None, ("dp", "fsdp"), None, "tp", None),
        "v": P(None, ("dp", "fsdp"), None, "tp", None),
        "lengths": P(("dp", "fsdp")),
    }


def kv_arena_spec() -> P:
    """Paged-KV block arena [L, n_blocks, bt, Hkv, hd]: kv heads over tp
    (the same head split :func:`cache_specs` gives the compute caches,
    so scatter/gather between blocks and rows moves no bytes across the
    tp axis); block and token axes stay unsharded — block ids are
    mesh-agnostic bookkeeping."""
    return P(None, None, None, "tp", None)


def shard_params(params: Any, mesh: Mesh, specs: Optional[Any] = None) -> Any:
    """Place a param tree onto the mesh with NamedShardings."""
    if specs is None:
        specs = param_specs(params)

    def walk(p: Any, s: Any) -> Any:
        # explicit recursion: PartitionSpec is itself a tuple, so a generic
        # tree_map over the spec tree would descend INTO the specs
        if isinstance(p, dict):
            return {k: walk(p[k], s[k]) for k in p}
        return jax.device_put(p, NamedSharding(mesh, s))

    return walk(params, specs)
