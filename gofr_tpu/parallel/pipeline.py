"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2 "parallelism
strategies: PP: absent"); the TPU build makes it first-class the XLA way:

- the transformer's stacked layer weights ``[L, ...]`` are sharded over
  ``pp`` on the leading axis — device i holds the contiguous layer block
  ``[i·L/pp, (i+1)·L/pp)``, i.e. stage i. No reshape, no per-stage param
  trees: the sharding IS the stage assignment;
- inside ``shard_map`` every device runs the same program (SPMD lockstep):
  a ``lax.scan`` over the classic GPipe schedule of ``M + pp - 1`` ticks.
  Stage 0 injects microbatch t at tick t; every stage applies its layer
  block; activations rotate to the next stage with ``jax.lax.ppermute``
  (ICI neighbor exchange, overlapped with the next tick's matmuls by XLA);
  the last stage records each exiting microbatch into an output buffer;
- bubbles are the standard GPipe ``(pp-1)/(M+pp-1)`` fraction — raise
  ``n_micro`` to amortize;
- backward needs no hand-written schedule: ``jax.grad`` differentiates
  through the scan + ppermute (transpose of ppermute is the reversed
  permutation), yielding the reverse pipeline automatically, and the
  transpose of replicated in_specs psums grads for the shared embed /
  lm_head / norm weights across stages;
- combines with data parallelism by sharding the batch over ``dp``/``fsdp``
  in the same shard_map (each pp ring serves one dp shard) and with tensor
  parallelism by leaving ``tp`` to GSPMD outside the shard_map.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gofr_tpu.models.quant import mm as _mm
from gofr_tpu.models.transformer import TransformerConfig, _block, _cached_freqs
from gofr_tpu.ops.loss import next_token_nll
from gofr_tpu.ops.norms import rms_norm


def pipeline_param_specs(params: Optional[dict] = None) -> Any:
    """shard_map in_specs prefix tree: stacked ``layers`` sharded over pp on
    the leading (layer) axis, everything else replicated. Derived from the
    actual param tree when given so placement and in_specs cannot drift."""
    keys = tuple(params) if params is not None else ("embed", "norm_f", "lm_head", "layers")
    return {k: (P("pp") if k == "layers" else P()) for k in keys}


def _check_stages(cfg: TransformerConfig, mesh: Mesh) -> None:
    pp = mesh.shape.get("pp", 1)
    if cfg.n_layers % pp:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={pp} — each pipeline "
            "stage needs an equal contiguous layer block"
        )


def _stage_forward(
    cfg: TransformerConfig, stage_layers: Any, x: jnp.ndarray, freqs: jnp.ndarray
) -> jnp.ndarray:
    """Apply this device's contiguous layer block to one microbatch."""
    positions = jnp.arange(x.shape[1])

    def body(carry, p):
        y, _, _ = _block(cfg, p, carry, freqs, positions)
        return y, None

    y, _ = lax.scan(body, x, stage_layers)
    return y


def _pipe_hidden(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    n_micro: int,
    axis_name: str,
) -> tuple[jnp.ndarray, jnp.ndarray, int]:
    """Run the GPipe schedule. Returns (hidden [B, S, D] — real data only on
    the LAST stage, zeros elsewhere —, stage index, n_stages)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s = tokens.shape
    if b % n_micro:
        raise ValueError(f"local batch {b} not divisible by n_micro={n_micro}")
    mb = b // n_micro
    freqs = jnp.asarray(_cached_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta))
    emb = params["embed"][tokens].reshape(n_micro, mb, s, cfg.dim)

    state0 = jnp.zeros((mb, s, cfg.dim), emb.dtype)
    outs0 = jnp.zeros((n_micro, mb, s, cfg.dim), emb.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        state, outs = carry
        # stage 0 injects microbatch t (replays the last one during drain
        # ticks; that output exits after the loop ends and is never read)
        inject = emb[jnp.minimum(t, n_micro - 1)]
        x_in = jnp.where(idx == 0, inject, state)
        y = _stage_forward(cfg, params["layers"], x_in, freqs)
        # microbatch injected at tick t exits the last stage at tick t+n-1,
        # so at tick t the exiting microbatch is o = t-(n-1)
        o = t - (n - 1)
        write = jnp.logical_and(idx == n - 1, o >= 0)
        upd = lax.dynamic_update_slice_in_dim(
            outs, y[None].astype(outs.dtype), jnp.clip(o, 0, n_micro - 1), axis=0
        )
        outs = jnp.where(write, upd, outs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(n_micro + n - 1))
    return outs.reshape(b, s, cfg.dim), idx, n


def make_pipeline_forward(
    cfg: TransformerConfig,
    mesh: Mesh,
    n_micro: Optional[int] = None,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
):
    """Jitted pipeline-parallel forward: tokens [B, S] -> logits [B, S, V]
    (replicated across pp via a final psum). Batch is sharded over
    ``batch_axes``; ``n_micro`` defaults to 2·pp (halves the bubble)."""
    _check_stages(cfg, mesh)
    n_micro = n_micro or 2 * mesh.shape.get("pp", 1)

    def per_shard(params, tokens):
        hidden, idx, n = _pipe_hidden(params, tokens, cfg, n_micro, "pp")
        h = rms_norm(hidden, params["norm_f"], cfg.norm_eps)
        logits = _mm(h, params["lm_head"]).astype(jnp.float32)
        # only the last stage holds real activations; psum replicates its
        # logits to the whole pp ring
        logits = jnp.where(idx == n - 1, logits, 0.0)
        return lax.psum(logits, "pp")

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(pipeline_param_specs(), P(batch_axes)),
        out_specs=P(batch_axes),
        check_vma=False,
    )
    return jax.jit(fn)


def make_pipeline_loss(
    cfg: TransformerConfig,
    mesh: Mesh,
    n_micro: Optional[int] = None,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
):
    """Jitted pipeline-parallel next-token loss: tokens [B, S] -> scalar.
    The loss (not the [B, S, V] logits) crosses the pp ring — one scalar
    psum instead of an all-reduce of logits."""
    _check_stages(cfg, mesh)
    n_micro = n_micro or 2 * mesh.shape.get("pp", 1)

    def per_shard(params, tokens):
        hidden, idx, n = _pipe_hidden(params, tokens[:, :-1], cfg, n_micro, "pp")
        h = rms_norm(hidden, params["norm_f"], cfg.norm_eps)
        logits = _mm(h, params["lm_head"]).astype(jnp.float32)
        nll = next_token_nll(logits, tokens[:, 1:])
        loss = lax.psum(jnp.where(idx == n - 1, nll.mean(), 0.0), "pp")
        for ax in batch_axes:
            loss = lax.pmean(loss, ax)
        return loss

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(pipeline_param_specs(), P(batch_axes)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def place_pipeline_params(params: dict, mesh: Mesh) -> dict:
    """Shard the param tree for the pipeline (same spec rule shard_map's
    in_specs use, via ``pipeline_param_specs``) — device_put with
    NamedShardings so the jitted step never reshuffles."""
    from jax.sharding import NamedSharding

    specs = pipeline_param_specs(params)

    def put(tree: Any, spec: P) -> Any:
        if isinstance(tree, dict):
            return {k: put(v, spec) for k, v in tree.items()}
        return jax.device_put(tree, NamedSharding(mesh, spec))

    return {k: put(v, specs[k]) for k, v in params.items()}
