"""Ulysses-style context parallelism: all-to-all sequence parallelism over
the ``sp`` mesh axis.

The second of the two first-class long-context strategies (SURVEY.md §5;
ring attention is parallel/ring.py). Where ring attention keeps the
sequence sharded and rotates K/V around the ring, Ulysses re-shards with
two ``all_to_all`` collectives per attention: heads scatter, sequence
gathers — each device then holds the FULL sequence for H/sp of the heads
and runs an ordinary (flash) attention locally, after which a second
all_to_all restores sequence sharding.

Trade-offs vs ring (how they map to TPU):
- Ulysses does 2 all-to-alls of activation size per attention call, ring
  does n-1 neighbor exchanges of K/V size; on an ICI torus both ride
  nearest-neighbor links, but Ulysses needs head-count divisibility
  (n_heads % sp == 0) while ring scales to any shard count.
- Ulysses attention itself is the unmodified single-device kernel (the
  Pallas flash path applies as-is); ring re-implements the online softmax
  around the permute loop.

Everything except attention is sequence-pointwise, so the per-shard
transformer body is shared with ring (ring._shard_forward, attn_fn
injection).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gofr_tpu.models.transformer import TransformerConfig
from gofr_tpu.ops.attention import attention
from gofr_tpu.parallel.ring import _shard_forward, _shard_loss


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """All-to-all attention over sequence shards.

    Must run inside ``shard_map`` with the sequence axis sharded over
    ``axis_name``. q: [B, S_local, Hq, D], k/v: [B, S_local, Hkv, D] per
    device. Requires Hq % sp == 0; Hkv that doesn't divide is repeated up
    to Hq first (GQA degrades toward MHA under high sp — the KV all_to_all
    then moves more bytes, the usual Ulysses+GQA trade)."""
    n = jax.lax.axis_size(axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % n:
        raise ValueError(
            f"n_heads={hq} not divisible by sp={n} — Ulysses shards the "
            "head axis; use ring attention for this shard count"
        )
    if hkv % n:
        reps = hq // hkv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)

    # heads scatter, sequence gathers: [B, S_loc, H, D] -> [B, S, H/n, D]
    gather = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    out = attention(
        gather(q), gather(k), gather(v), causal=causal, scale=scale, impl=impl
    )
    # restore sequence sharding: [B, S, Hq/n, D] -> [B, S_loc, Hq, D]
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def _attn_fn(axis_name: str):
    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name=axis_name, causal=True)

    return fn


def make_ulysses_forward(cfg: TransformerConfig, mesh: Mesh, batch_axes=("dp", "fsdp")):
    """Jitted full-sequence forward with the sequence axis sharded over
    ``sp``: tokens [B, S] -> logits [B, S, V] (mirror of
    ring.make_ring_forward with all-to-all attention)."""
    fwd = jax.shard_map(
        functools.partial(
            _shard_forward, cfg=cfg, axis_name="sp", attn_fn=_attn_fn("sp")
        ),
        mesh=mesh,
        in_specs=(P(), P(batch_axes, "sp")),
        out_specs=P(batch_axes, "sp", None),
        check_vma=False,
    )
    return jax.jit(fwd)


def make_ulysses_loss(cfg: TransformerConfig, mesh: Mesh, batch_axes=("dp", "fsdp")):
    """Jitted sequence-parallel next-token loss: tokens [B, S] -> scalar."""

    def per_shard(params, tokens):
        loss = _shard_loss(params, tokens, cfg, axis_name="sp", attn_fn=_attn_fn("sp"))
        for ax in batch_axes:
            loss = jax.lax.pmean(loss, ax)
        return loss

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(batch_axes, "sp")),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)
