"""Mesh construction.

Axis convention (jax-ml scaling-book style):
- ``dp``   — pure data parallelism (batch split, gradients all-reduced)
- ``fsdp`` — data parallelism with parameter sharding (ZeRO-3 style;
             params/optimizer sharded, all-gathered per layer)
- ``sp``   — sequence/context parallelism (ring attention over ICI)
- ``pp``   — pipeline parallelism (layer stages, GPipe microbatches)
- ``ep``   — expert parallelism (MoE experts, all_to_all token dispatch)
- ``tp``   — tensor parallelism (heads / hidden dim split)

On a physical slice the trailing axes should map to the fastest ICI links;
jax.make_mesh handles device ordering. Single-process multi-device (one host
of a v5e slice) and the CPU-backed virtual mesh used by tests/dryrun are
built the same way.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

AXES = ("dp", "fsdp", "sp", "pp", "ep", "tp")


def mesh_shape_for(
    n_devices: int,
    tp: int = 1,
    sp: int = 1,
    fsdp: int = 1,
    pp: int = 1,
    ep: int = 1,
) -> dict[str, int]:
    """Fill ``dp`` with whatever remains after the explicit axes."""
    denom = tp * sp * fsdp * pp * ep
    if n_devices % denom != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by tp*sp*fsdp*pp*ep={denom}"
        )
    return {
        "dp": n_devices // denom, "fsdp": fsdp, "sp": sp,
        "pp": pp, "ep": ep, "tp": tp,
    }


def make_mesh(
    shape: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh. Default: all local devices on ``dp``."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = mesh_shape_for(len(devices))
    sizes = tuple(shape.get(a, 1) for a in AXES)
    total = 1
    for s in sizes:
        total *= s
    if total != len(devices):
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {len(devices)}")
    # Auto axes: GSPMD owns propagation and inserts collectives freely
    # (jax 0.9 defaults some paths to explicit sharding-in-types, which
    # rejects mixed-axis contractions instead of resolving them). Older
    # jax (< 0.5) predates AxisType — its meshes are Auto by definition,
    # so the plain two-argument call is the same semantics.
    axis_type = getattr(
        getattr(jax.sharding, "AxisType", None), "Auto", None
    )
    if axis_type is None:
        return jax.make_mesh(sizes, AXES, devices=devices)
    return jax.make_mesh(sizes, AXES, (axis_type,) * len(AXES), devices=devices)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def mesh_axes(mesh: Optional[Mesh]) -> Optional[dict[str, int]]:
    """The mesh's non-trivial axes as a plain dict ({"tp": 2, "dp": 4})
    — the shape observability carries (``/admin/engine`` ``mesh``,
    ``gofr_tpu_mesh_axis_size{axis}``, FlightRecord ``mesh_axes``).
    None when no mesh (single chip)."""
    if mesh is None:
        return None
    # a mesh whose axes are all size 1 yields {} (a mesh, trivially) —
    # distinct from the None of no mesh at all
    return {a: s for a, s in mesh.shape.items() if s > 1}
