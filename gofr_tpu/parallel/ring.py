"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context is first-class (SURVEY.md §5 "long-context/sequence
parallelism" — absent in the reference, required of the TPU build): a
sequence too long for one chip's HBM is sharded across the ``sp`` axis,
each device holding a [B, S/sp] slice of tokens, activations, K and V.

Design (Liu et al. blockwise ring attention, the scaling-book recipe):
- run the WHOLE transformer under ``shard_map`` with the sequence axis
  sharded over ``sp``: embedding gather, norms, and MLP are pointwise over
  sequence so they need no communication; RoPE uses absolute positions
  computed from the shard index;
- attention rotates K/V shards around the ring with ``jax.lax.ppermute``
  (XLA lowers to ICI neighbor exchange, overlapping the transfer with the
  current chunk's compute), combining chunks with the same online-softmax
  update the flash kernel uses — max/sum-exp accumulators, one pass, no
  [S, S] materialization;
- the causal mask between chunk pairs is applied elementwise; fully-masked
  pairs (source chunk strictly after the query chunk) burn one masked
  matmul rather than branching — SPMD keeps all devices in lockstep
  through the ring anyway;
- next-token loss under sequence sharding shifts targets across shard
  boundaries with one more ppermute and a validity mask for the global
  last position; means reduce with psum over (sp, dp-like) axes.

All public entry points take the mesh and build the shard_map; the inner
functions are plain per-shard JAX, jit-compiled once.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gofr_tpu.models.quant import mm as _mm
from gofr_tpu.models.transformer import TransformerConfig, _block, _cached_freqs
from gofr_tpu.ops.loss import next_token_nll
from gofr_tpu.ops.norms import rms_norm

_NEG_INF = float(-1e30)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Blockwise ring attention over sequence shards.

    Must run inside ``shard_map`` with the sequence axis sharded over
    ``axis_name``. q, k, v: per-device shards [B, S_local, H(q|kv), D] at
    shard index ``axis_index(axis_name)``; position of local row j is
    ``idx * S_local + j``. Returns the attention output shard.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    groups = hq // hkv
    if scale is None:
        scale = d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    q_pos = idx * sq + jnp.arange(sq)  # [sq] absolute

    # online-softmax accumulators in the grouped layout [b, hkv, g, sq, ·]
    m = jnp.full((b, hkv, groups, sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, groups, sq, 1), jnp.float32)
    acc = jnp.zeros((b, hkv, groups, sq, d), jnp.float32)

    # send to the right neighbor; after t steps we hold chunk (idx - t) % n
    perm = [(i, (i + 1) % n) for i in range(n)]

    k_cur, v_cur = k, v
    for step in range(n):
        src = (idx - step) % n
        kv_pos = src * skv + jnp.arange(skv)  # [skv] absolute

        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, k_cur, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            mask = (kv_pos[None, :] <= q_pos[:, None])[None, None, None]
            s = jnp.where(mask, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        m = m_new

        if step < n - 1:
            # one combined neighbor exchange over ICI; XLA overlaps it
            # with the next chunk's matmuls
            k_cur, v_cur = jax.lax.ppermute((k_cur, v_cur), axis_name, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)  # masked rows (none when causal) → 0
    # [b, hkv, g, sq, d] -> [b, sq, hq, d]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, sq, hq, d)
    return out.astype(q.dtype)


def _shard_forward(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    axis_name: str,
    attn_fn=None,
) -> jnp.ndarray:
    """Per-shard transformer forward: tokens [B, S_local] at shard
    ``axis_index``; everything except attention is sequence-pointwise, so
    the canonical decoder block (models.transformer._block) is reused with
    the sequence-parallel attention injected via ``attn_fn`` (default: ring;
    parallel.ulysses passes its all-to-all attention)."""
    b, s = tokens.shape
    n = jax.lax.axis_size(axis_name)
    if s * n > cfg.max_seq:
        raise ValueError(
            f"global sequence {s * n} exceeds cfg.max_seq {cfg.max_seq} "
            "(RoPE table bound) — raise max_seq for long-context configs"
        )
    idx = jax.lax.axis_index(axis_name)
    freqs = jnp.asarray(_cached_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta))
    positions = idx * s + jnp.arange(s)  # absolute positions of this shard
    x = params["embed"][tokens]

    if attn_fn is None:
        def attn_fn(q, k, v):
            return ring_attention(q, k, v, axis_name=axis_name, causal=True)

    def body(carry, p):
        y, _, _ = _block(cfg, p, carry, freqs, positions, attn_fn=attn_fn)
        return y, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["norm_f"], cfg.norm_eps)
    return _mm(x, params["lm_head"]).astype(jnp.float32)


def make_ring_forward(cfg: TransformerConfig, mesh: Mesh, batch_axes=("dp", "fsdp")):
    """Jitted full-sequence forward with the sequence axis sharded over
    ``sp``: tokens [B, S] -> logits [B, S, V], S split across the ring.
    Params replicate over sp (combine with fsdp/tp via the outer sharding
    as usual — GSPMD handles the interplay outside the shard_map)."""
    fwd = jax.shard_map(
        functools.partial(_shard_forward, cfg=cfg, axis_name="sp"),
        mesh=mesh,
        in_specs=(P(), P(batch_axes, "sp")),
        out_specs=P(batch_axes, "sp", None),
        check_vma=False,
    )
    return jax.jit(fwd)


def _shard_loss(
    params: dict,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    axis_name: str,
    attn_fn=None,
) -> jnp.ndarray:
    """Per-shard next-token loss. The target for the shard's last position
    is the FIRST token of the right neighbor's shard (ppermute); the global
    last position is masked out."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s = tokens.shape
    logits = _shard_forward(params, tokens, cfg, axis_name, attn_fn)  # [B, S_local, V]

    # left-rotate first tokens: shard i receives shard (i+1)'s tokens[:, 0]
    perm = [(i, (i - 1) % n) for i in range(n)]
    next_first = jax.lax.ppermute(tokens[:, :1], axis_name, perm)  # [B, 1]
    targets = jnp.concatenate([tokens[:, 1:], next_first], axis=1)  # [B, S_local]
    nll = next_token_nll(logits, targets)
    # mask the global final position (no next token exists)
    is_last_shard = idx == (n - 1)
    pos_weight = jnp.where(
        jnp.logical_and(is_last_shard, jnp.arange(s) == s - 1), 0.0, 1.0
    )[None, :]
    local_sum = jnp.sum(nll * pos_weight)
    local_cnt = jnp.sum(jnp.broadcast_to(pos_weight, nll.shape))
    total = jax.lax.psum(jnp.stack([local_sum, local_cnt]), axis_name)
    return total[0] / total[1]


def make_ring_loss(cfg: TransformerConfig, mesh: Mesh, batch_axes=("dp", "fsdp")):
    """Jitted sequence-parallel next-token loss: tokens [B, S] -> scalar.
    Batch-axis averaging happens implicitly: each dp shard computes its own
    mean and the jit-level output spec replicates (psum over sp happens
    inside; outer mean over batch shards via jnp.mean of per-shard means
    is exact because all shards see the same position count)."""

    def per_shard(params, tokens):
        loss = _shard_loss(params, tokens, cfg, axis_name="sp")
        # average over batch-sharding axes too so the replicated output is
        # the global mean
        for ax in batch_axes:
            loss = jax.lax.pmean(loss, ax)
        return loss

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(batch_axes, "sp")),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)
