"""Multi-host runtime initialization: the DCN coordination layer.

SURVEY.md §2/§5 first-class checklist ("distributed communication
backend"): intra-slice collectives ride ICI inside compiled executables
(GSPMD emits them; the framework never issues collectives), but a
multi-host deployment (llama3-70b DP/TP over v5e-16, BASELINE config 4)
needs every process to join ONE runtime so jax.devices() spans the slice
and pjit compiles global SPMD programs. The reference's analogue is NCCL/
MPI bootstrap; here it is the JAX distributed service (gRPC over DCN) —
one coordinator, N processes.

Config keys (12-factor, same mechanism as every other datasource):

- ``TPU_COORDINATOR``   host:port of process 0 (unset -> single host)
- ``TPU_NUM_PROCESSES`` world size
- ``TPU_PROCESS_ID``    this process's rank

``examples/http-server`` on a v5e-16 becomes: same binary on each host,
same env except TPU_PROCESS_ID; application-level coordination (health
fan-out, request routing) stays on the framework's own inter-service
HTTP/gRPC clients (gofr_tpu.service) — the split SURVEY.md §2 prescribes.

Tested without a cluster (tests/test_multihost.py): two local processes
join a coordinator on localhost with CPU devices — the same fake-backend
strategy the reference uses for Redis/SQL (SURVEY.md §4).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_lock = threading.Lock()
_initialized = False


def init_from_config(config: Any, logger: Any = None) -> bool:
    """Join the multi-host runtime when ``TPU_COORDINATOR`` is configured.
    Returns True when distributed init ran (or already had). Idempotent;
    raising is left to the caller's degraded-startup policy."""
    global _initialized
    coordinator = config.get("TPU_COORDINATOR")
    if not coordinator:
        return False
    with _lock:
        if _initialized:
            return True
        import jax

        num_processes = int(config.get_or_default("TPU_NUM_PROCESSES", "1"))
        process_id = int(config.get_or_default("TPU_PROCESS_ID", "0"))
        if logger is not None:
            logger.infof(
                "joining multi-host runtime: coordinator=%s process %d/%d",
                coordinator, process_id, num_processes,
            )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        return True


def process_info() -> dict[str, int]:
    """Rank/world/device counts of the joined runtime (health details)."""
    import jax

    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def shutdown() -> None:
    global _initialized
    with _lock:
        if not _initialized:
            return
        import jax

        try:
            jax.distributed.shutdown()
        finally:
            _initialized = False


def global_psum_check() -> Optional[float]:
    """One cross-host collective as a liveness probe: sums 1 over every
    global device — equals the global device count iff all hosts
    participate. Returns None on single-process runtimes.

    SPMD: EVERY process must call this at the same point (e.g. a
    coordinated startup check), exactly like any jit over a global mesh —
    calling it from one host's request handler would block forever
    waiting for peers. Per-host liveness belongs on /.well-known/health
    fanned out over the service layer instead."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if jax.process_count() <= 1:
        return None
    devices = jax.devices()
    mesh = Mesh(devices, ("dp",))
    ones = jax.make_array_from_callback(
        (len(devices),),
        NamedSharding(mesh, P("dp")),
        lambda idx: jnp.ones((1,), jnp.float32),
    )
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(ones)
    return float(total)
