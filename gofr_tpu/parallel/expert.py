"""Expert parallelism: MoE token dispatch over the ``ep`` mesh axis.

The reference has no distributed components (SURVEY.md §2 "EP: absent");
this is the TPU-native design:

- expert weights ``[L, E, D, F]`` shard over ``ep`` on the expert axis —
  each device owns ``E/ep`` experts (attention/router/embed replicate
  over ep, batch shards over ALL of dp·fsdp·ep so attention stays pure
  data-parallel);
- inside ``shard_map`` each device routes its local tokens (GShard
  capacity-bounded dispatch, static shapes), then ``jax.lax.all_to_all``
  over ``ep`` exchanges token blocks so every device receives exactly the
  tokens routed to ITS experts, computes its experts' SwiGLU, and a second
  all_to_all returns outputs to the tokens' home devices — two ICI
  all-to-alls per MoE layer, the canonical TPU MoE pattern;
- gradients flow through both all_to_alls (transpose of all_to_all is the
  reverse all_to_all); aux losses psum/pmean across the mesh.

Numerical contract: with ample capacity this path equals the exact dense
mixture (gofr_tpu.models.moe.moe_forward) — tested against it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gofr_tpu.models.moe import (
    MoEConfig,
    _expert_ffn,
    _routing,
    moe_forward,
)
from gofr_tpu.ops.loss import next_token_nll

_EXPERT_KEYS = ("w_gate", "w_up", "w_down")


_LAYER_KEYS = (
    "attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
    "router", "w_gate", "w_up", "w_down",
)


def moe_param_specs(params: Optional[dict] = None) -> Any:
    """Spec tree: stacked expert weights [L, E, D, F] shard E over ep;
    everything else replicates (tp/fsdp composition happens outside the
    shard_map via GSPMD as usual). Derived from the actual param tree when
    given so placement and shard_map in_specs cannot drift."""
    top = tuple(params) if params is not None else ("embed", "norm_f", "lm_head", "layers")
    layer_keys = tuple(params["layers"]) if params is not None else _LAYER_KEYS

    def layer_specs() -> dict:
        return {
            k: (P(None, "ep") if k in _EXPERT_KEYS else P()) for k in layer_keys
        }

    return {k: (layer_specs() if k == "layers" else P()) for k in top}


def place_moe_params(params: dict, mesh: Mesh) -> dict:
    """device_put the tree with the same spec rule the shard_map uses."""
    from gofr_tpu.parallel.sharding import shard_params

    return shard_params(params, mesh, moe_param_specs(params))


def _capacity(tokens_local: int, cfg: MoEConfig) -> int:
    cap = int(tokens_local * cfg.top_k * cfg.capacity_factor / cfg.n_experts)
    return max(cap, 1)


def _moe_mlp_ep(
    p: dict, x: jnp.ndarray, cfg: MoEConfig, axis_name: str = "ep"
) -> tuple[jnp.ndarray, dict]:
    """Expert-parallel MoE MLP: x [B_loc, S, D]; expert weights arrive
    sharded [E/ep, D, F]. Two all_to_alls move tokens to their experts'
    devices and back."""
    b, s, d = x.shape
    t = b * s
    capacity = _capacity(t, cfg)
    xt = x.reshape(t, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), p["router"])
    dispatch, combine, aux = _routing(logits, cfg.top_k, capacity)

    # gather each expert's token block: [E, C, D] (E = GLOBAL expert count)
    xs = jnp.einsum("tec,td->ecd", dispatch.astype(xt.dtype), xt)
    # scatter expert blocks to their owners; collect peers' tokens along C:
    # [E, C, D] -> [E/ep, ep·C, D]
    xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1, tiled=True)
    ys = _expert_ffn(p["w_gate"], p["w_up"], p["w_down"], xs)
    # return outputs to the tokens' home devices: [E/ep, ep·C, D] -> [E, C, D]
    ys = lax.all_to_all(ys, axis_name, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("ecd,tec->td", ys, combine.astype(ys.dtype))
    return out.reshape(b, s, d).astype(x.dtype), aux


def make_moe_forward(
    cfg: MoEConfig,
    mesh: Mesh,
    batch_axes: tuple[str, ...] = ("dp", "fsdp", "ep"),
):
    """Jitted expert-parallel forward: tokens [B, S] -> (logits [B, S, V],
    aux). Batch shards over dp·fsdp·ep; experts over ep."""
    _check_experts(cfg, mesh)

    def per_shard(params, tokens):
        logits, aux = moe_forward(params, tokens, cfg, moe_mlp=_moe_mlp_ep)
        # aux statistics are per-device (local batch); average them so the
        # replicated output is the global value, not an arbitrary shard's
        for ax in batch_axes:
            aux = {k: lax.pmean(v, ax) for k, v in aux.items()}
        return logits, aux

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(moe_param_specs(), P(batch_axes)),
        out_specs=(P(batch_axes), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def make_moe_loss(
    cfg: MoEConfig,
    mesh: Mesh,
    batch_axes: tuple[str, ...] = ("dp", "fsdp", "ep"),
):
    """Jitted expert-parallel loss: next-token NLL + weighted aux losses,
    pmean'd over the whole mesh."""
    _check_experts(cfg, mesh)

    def per_shard(params, tokens):
        logits, aux = moe_forward(
            params, tokens[:, :-1], cfg, moe_mlp=_moe_mlp_ep
        )
        loss = next_token_nll(logits, tokens[:, 1:]).mean()
        loss = loss + cfg.aux_weight * aux["load_balance"] + cfg.z_weight * aux["router_z"]
        for ax in batch_axes:
            loss = lax.pmean(loss, ax)
        return loss

    fn = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(moe_param_specs(), P(batch_axes)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def _check_experts(cfg: MoEConfig, mesh: Mesh) -> None:
    ep = mesh.shape.get("ep", 1)
    if cfg.n_experts % ep:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by ep={ep} — each device "
            "needs an equal expert block"
        )
