"""SLO engine: declarative objectives, windowed error budgets, and
multi-window multi-burn-rate alerting.

``/admin/slo`` (PR 1) reports rolling percentiles with no notion of a
*target*: nothing says whether p99 TTFT of 800ms is fine or an incident,
and nobody is told when the answer flips. This module closes that loop
the Google-SRE way:

- **Objectives** (``SLO_TARGETS``): a semicolon-separated list of
  ``[scope:]metric=target`` clauses. Metrics: ``availability`` (good =
  not error / not deadline-exceeded; target is the good fraction, e.g.
  0.999), ``shed_rate`` (target is the allowed shed fraction, measured
  from the brownout + router shed counters via timebase snapshots),
  and latency-percentile bounds ``ttft_p95_ms`` / ``ttft_p99_ms`` /
  ``tpot_p95_ms`` / ``tpot_p99_ms`` (target is the millisecond bound;
  the implied good fraction is the percentile — "p95 under 200ms"
  means at most 5% of requests may exceed 200ms). Scopes:
  ``model=<name>:``, ``tier=<n>:``, ``tier>=<n>:`` (priority tiers), or
  none (global).

- **Error budgets**: budget = 1 − good-fraction (for ``shed_rate``, the
  target itself). The windowed bad fraction comes from the
  FlightRecorder ring (cancelled excluded — a client hanging up is its
  verdict, not ours); ``budget_remaining`` is measured over the long
  slow window (default 3d), clipped implicitly to what the ring and the
  process uptime retain.

- **Multi-window multi-burn-rate alerts**: burn = bad-fraction /
  budget. The **fast** page fires when burn exceeds
  ``SLO_BURN_FAST_RATE`` (14.4) on BOTH the 5m and 1h windows; the
  **slow** ticket fires past ``SLO_BURN_SLOW_RATE`` (6) on both 6h and
  3d. Verdicts are latched per (objective, pair) — one anomaly event
  per excursion, re-armed when the burn clears — and land in the SAME
  anomaly ring as the dispatch cost model (``gofr_tpu/anomaly.py``;
  on replicas the container points the engine at
  ``tpu.costmodel.ring``, so ``GET /admin/anomalies`` shows
  ``slo_fast_burn`` next to ``slow_dispatch``), on
  ``gofr_tpu_slo_burn_alerts_total{objective,window}``, and in every
  postmortem bundle.

- **Surfaces**: ``gofr_tpu_slo_burn_rate{objective,window}`` and
  ``gofr_tpu_slo_budget_remaining{objective}`` gauges,
  ``GET /admin/slo/budget`` (the full ledger), headline rows on
  ``/admin/overview``, ``/admin/engine`` (scraped by the fleet prober),
  and ``/admin/fleet/overview``.

A healthy echo run evaluates to zero alerts (the tier-1 e2e asserts
exactly that, same discipline as the cost model's zero-anomaly
invariant); the default targets are deliberately loose enough that only
real fault bursts burn.

Host-side only: evaluation is a single ring scan plus float arithmetic
per objective (bench.py's slo_microbench keeps it honest) on a named
daemon thread every ``SLO_EVAL_INTERVAL_S``, and lazily on every
``/admin/slo/budget`` read.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from gofr_tpu.anomaly import AnomalyRing

DEFAULT_TARGETS = "availability=0.999;shed_rate=0.05;tier=9:availability=0.9995"

LATENCY_METRICS = ("ttft_p95_ms", "ttft_p99_ms", "tpot_p95_ms", "tpot_p99_ms")
METRICS = ("availability", "shed_rate") + LATENCY_METRICS

# a record's terminal statuses that consume availability budget;
# "cancelled" is the CLIENT's verdict (they hung up), not the server's
BAD_STATUSES = ("error", "deadline_exceeded")

# shed counters summed for shed_rate objectives (replica brownout 429s +
# router-tier sheds) — counter deltas via TimebaseSampler.counter_delta
SHED_COUNTERS = ("gofr_tpu_brownout_shed_total", "gofr_tpu_router_shed_total")


def _window_name(seconds: float) -> str:
    """Human window label for the gauge's ``window`` dimension: "5m",
    "1h", "6h", "3d" at the defaults; a generic seconds form otherwise
    (label values must stay stable per config, not per call)."""
    s = int(seconds)
    if s % 86400 == 0:
        return f"{s // 86400}d"
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


class Objective:
    """One parsed SLO clause: metric + target + optional scope."""

    __slots__ = (
        "id", "metric", "target", "model", "tier", "tier_ge",
        "budget", "threshold_s",
    )

    def __init__(
        self,
        metric: str,
        target: float,
        model: Optional[str] = None,
        tier: Optional[int] = None,
        tier_ge: Optional[int] = None,
    ):
        if metric not in METRICS:
            raise ValueError(
                f"SLO_TARGETS: unknown metric {metric!r} "
                f"(expected one of {', '.join(METRICS)})"
            )
        self.metric = metric
        self.target = float(target)
        self.model = model
        self.tier = tier
        self.tier_ge = tier_ge
        self.threshold_s: Optional[float] = None
        if metric == "availability":
            if not (0.0 < self.target < 1.0):
                raise ValueError(
                    "SLO_TARGETS: availability target must be in (0, 1)"
                )
            self.budget = 1.0 - self.target
        elif metric == "shed_rate":
            if not (0.0 < self.target <= 1.0):
                raise ValueError(
                    "SLO_TARGETS: shed_rate target must be in (0, 1]"
                )
            if model is not None or tier is not None or tier_ge is not None:
                # the shed counters carry no model/tenant dimension
                # (brownout sheds by priority, router sheds by reason) —
                # a scoped clause would silently measure the global rate
                raise ValueError(
                    "SLO_TARGETS: shed_rate objectives are global "
                    "(the shed counters carry no model/tier scope)"
                )
            self.budget = self.target
        else:  # latency-percentile bound
            if self.target <= 0:
                raise ValueError(
                    f"SLO_TARGETS: {metric} target must be > 0 (ms)"
                )
            self.threshold_s = self.target / 1000.0
            # ttft_p95_ms -> 5% of requests may exceed the bound
            percentile = float(metric.rsplit("_", 2)[1][1:]) / 100.0
            self.budget = 1.0 - percentile
        if model is not None:
            prefix = f"{model}."
        elif tier is not None:
            prefix = f"tier{tier}."
        elif tier_ge is not None:
            prefix = f"tier_ge{tier_ge}."
        else:
            prefix = ""
        self.id = prefix + metric

    def matches(self, record: Any) -> bool:
        """Does ``record`` (a FlightRecord) fall in this objective's
        scope? Tier scopes need a priority on the record; records
        admitted without one never consume a tier budget."""
        if self.model is not None and record.model != self.model:
            return False
        if self.tier is not None or self.tier_ge is not None:
            priority = record.priority
            if not isinstance(priority, int):
                return False
            if self.tier is not None and priority != self.tier:
                return False
            if self.tier_ge is not None and priority < self.tier_ge:
                return False
        return True

    def judge(self, record: Any) -> Optional[bool]:
        """True = this record burned budget, False = it was good, None =
        not eligible (out of scope, cancelled, or no measurement)."""
        if not self.matches(record):
            return None
        if record.status == "cancelled":
            return None
        if self.metric == "availability":
            return record.status in BAD_STATUSES
        # latency bound: judge only records that produced the
        # measurement — but a deadline-exceeded request with no first
        # token IS a latency violation, not a missing sample
        value = record.ttft if self.metric.startswith("ttft") else record.tpot
        if value is None:
            return True if record.status in BAD_STATUSES else None
        return value > self.threshold_s

    def to_dict(self) -> dict[str, Any]:
        scope: Optional[dict[str, Any]] = None
        if self.model is not None:
            scope = {"model": self.model}
        elif self.tier is not None:
            scope = {"tier": self.tier}
        elif self.tier_ge is not None:
            scope = {"tier_ge": self.tier_ge}
        return {
            "objective": self.id,
            "metric": self.metric,
            "target": self.target,
            "budget": round(self.budget, 6),
            "scope": scope,
        }


def parse_targets(spec: str) -> list[Objective]:
    """Parse ``SLO_TARGETS``: semicolon-separated ``[scope:]metric=target``
    clauses (see module docstring). Malformed clauses raise ValueError —
    a misconfigured objective silently not alerting is the one failure
    mode this subsystem must not have."""
    objectives: list[Objective] = []
    seen: set[str] = set()
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        scope_part, sep, rest = clause.rpartition(":")
        body = rest if sep else clause
        model: Optional[str] = None
        tier: Optional[int] = None
        tier_ge: Optional[int] = None
        if sep:
            scope_part = scope_part.strip()
            if scope_part.startswith("model="):
                model = scope_part[len("model="):].strip()
                if not model:
                    raise ValueError(
                        f"SLO_TARGETS: empty model scope in {clause!r}"
                    )
            elif scope_part.startswith("tier>="):
                tier_ge = _parse_tier(scope_part[len("tier>="):], clause)
            elif scope_part.startswith("tier="):
                tier = _parse_tier(scope_part[len("tier="):], clause)
            else:
                raise ValueError(
                    f"SLO_TARGETS: bad scope {scope_part!r} in {clause!r} "
                    "(expected model=<name>, tier=<n>, or tier>=<n>)"
                )
        metric, sep, target_raw = body.partition("=")
        if not sep:
            raise ValueError(
                f"SLO_TARGETS: clause {clause!r} is not metric=target"
            )
        try:
            target = float(target_raw.strip())
        except ValueError:
            raise ValueError(
                f"SLO_TARGETS: target {target_raw.strip()!r} in {clause!r} "
                "is not a number"
            )
        objective = Objective(
            metric.strip(), target, model=model, tier=tier, tier_ge=tier_ge
        )
        if objective.id in seen:
            raise ValueError(
                f"SLO_TARGETS: duplicate objective {objective.id!r}"
            )
        seen.add(objective.id)
        objectives.append(objective)
    return objectives


def _parse_tier(raw: str, clause: str) -> int:
    try:
        tier = int(raw.strip())
    except ValueError:
        raise ValueError(f"SLO_TARGETS: bad tier {raw!r} in {clause!r}")
    if not (0 <= tier <= 9):
        raise ValueError(f"SLO_TARGETS: tier must be 0..9 in {clause!r}")
    return tier


class SloEngine:
    """Windowed error-budget ledger + burn-rate alerting over the
    FlightRecorder ring and the timebase's shed counters.

    ``ring`` is the anomaly evidence store the burn verdicts land in.
    The container points it at ``tpu.costmodel.ring`` when a device is
    wired (one `/admin/anomalies` surface); router/bare processes keep
    the engine's own host-side ring."""

    WINDOW_PAIRS = ("fast", "slow")

    def __init__(
        self,
        telemetry: Any,
        timebase: Any = None,
        metrics: Any = None,
        logger: Any = None,
        targets: str = DEFAULT_TARGETS,
        fast_s: float = 300.0,
        fast_long_s: float = 3600.0,
        slow_s: float = 21600.0,
        slow_long_s: float = 259200.0,
        fast_rate: float = 14.4,
        slow_rate: float = 6.0,
        interval_s: float = 15.0,
        ring: Optional[AnomalyRing] = None,
        start: bool = False,
    ):
        if not (0 < fast_s <= fast_long_s <= slow_s <= slow_long_s):
            raise ValueError(
                "SLO burn windows must satisfy 0 < SLO_BURN_FAST_S <= "
                "SLO_BURN_FAST_LONG_S <= SLO_BURN_SLOW_S <= "
                "SLO_BURN_SLOW_LONG_S"
            )
        if fast_rate <= 0 or slow_rate <= 0:
            raise ValueError("SLO burn-rate thresholds must be > 0")
        if interval_s <= 0:
            raise ValueError("SLO_EVAL_INTERVAL_S must be > 0")
        self.telemetry = telemetry
        self.timebase = timebase
        self.logger = logger
        self.targets_spec = targets
        self.objectives = parse_targets(targets)
        self.fast_s = float(fast_s)
        self.fast_long_s = float(fast_long_s)
        self.slow_s = float(slow_s)
        self.slow_long_s = float(slow_long_s)
        self.fast_rate = float(fast_rate)
        self.slow_rate = float(slow_rate)
        self.interval_s = float(interval_s)
        self.ring = ring if ring is not None else AnomalyRing()
        # one latch per (objective, pair): an excursion records ONE
        # anomaly event, re-armed when the burn drops back under the
        # threshold (mirrors the cost model's ema_drift latch)
        self._latched: dict[tuple[str, str], bool] = {}
        self._alerts_total = 0
        self._evaluations = 0
        self._last_report: Optional[dict[str, Any]] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._burn_gauge = (
            metrics.gauge(
                "gofr_tpu_slo_burn_rate",
                "error-budget burn rate per objective and window "
                "(1.0 = burning exactly the budget; the fast page fires "
                "past SLO_BURN_FAST_RATE on both fast windows)",
                labels=("objective", "window"),
            )
            if metrics is not None else None
        )
        self._budget_gauge = (
            metrics.gauge(
                "gofr_tpu_slo_budget_remaining",
                "fraction of the error budget left over the long slow "
                "window (1.0 = untouched, <= 0 = exhausted)",
                labels=("objective",),
            )
            if metrics is not None else None
        )
        self._alert_counter = (
            metrics.counter(
                "gofr_tpu_slo_burn_alerts_total",
                "burn-rate alert excursions (latched: one per entry "
                "into the burning state)",
                labels=("objective", "window"),
            )
            if metrics is not None else None
        )
        if start:
            self.start()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gofr-slo", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as exc:  # evaluation must never kill the thread
                if self.logger is not None:
                    try:
                        self.logger.errorf("slo evaluation failed: %r", exc)
                    except Exception:
                        # gofrlint: disable=GFL006 — the logger itself
                        # failed; nothing left to report to
                        pass

    # -- measurement ----------------------------------------------------------
    def _shed_fraction(self, window_s: float, completed: int) -> tuple[float, int, int]:
        """(bad_fraction, bad, total) for shed_rate over ``window_s``:
        sheds from counter deltas (timebase snapshots — sheds never make
        flight records), demand = sheds + completed requests in the
        window."""
        if self.timebase is None:
            return 0.0, 0, completed
        sheds = sum(
            self.timebase.counter_delta(name, window=window_s)
            for name in SHED_COUNTERS
        )
        total = int(sheds) + completed
        if total <= 0:
            return 0.0, 0, 0
        return sheds / total, int(sheds), total

    def _window_stats(
        self, objective: Objective, records: list, now: float, window_s: float
    ) -> dict[str, Any]:
        horizon = now - window_s
        recent = [r for r in records if r.t_done >= horizon]
        if objective.metric == "shed_rate":
            frac, bad, total = self._shed_fraction(window_s, len(recent))
        else:
            verdicts = [
                v for v in (objective.judge(r) for r in recent)
                if v is not None
            ]
            total = len(verdicts)
            bad = sum(1 for v in verdicts if v)
            frac = bad / total if total else 0.0
        return {
            "window_s": window_s,
            "bad": bad,
            "total": total,
            "bad_fraction": round(frac, 6),
            "burn": round(frac / objective.budget, 3),
        }

    def evaluate(self) -> dict[str, Any]:
        """One full evaluation pass: windowed burn rates per objective,
        budget ledger, latched alert transitions into the anomaly ring,
        gauge updates. Returns the report ``/admin/slo/budget`` serves."""
        now = time.perf_counter()
        with self._lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: float) -> dict[str, Any]:
        windows = (self.fast_s, self.fast_long_s, self.slow_s,
                   self.slow_long_s)
        records = self.telemetry.finished_since(now - max(windows))
        pairs = {
            "fast": (self.fast_s, self.fast_long_s, self.fast_rate),
            "slow": (self.slow_s, self.slow_long_s, self.slow_rate),
        }
        rows: list[dict[str, Any]] = []
        for objective in self.objectives:
            by_window: dict[str, dict[str, Any]] = {}
            for window_s in windows:
                name = _window_name(window_s)
                if name in by_window:
                    continue  # degenerate config: two equal windows
                stats = self._window_stats(objective, records, now, window_s)
                by_window[name] = stats
                if self._burn_gauge is not None:
                    self._burn_gauge.set(
                        stats["burn"], objective=objective.id, window=name
                    )
            # budget ledger over the long slow window: fraction of the
            # allowed bad requests still unspent
            ledger = by_window[_window_name(self.slow_long_s)]
            if ledger["total"]:
                consumed = ledger["bad_fraction"] / objective.budget
            else:
                consumed = 0.0
            remaining = round(1.0 - consumed, 4)
            if self._budget_gauge is not None:
                self._budget_gauge.set(remaining, objective=objective.id)
            alerts: dict[str, bool] = {}
            for pair, (short_s, long_s, rate) in pairs.items():
                short = by_window[_window_name(short_s)]
                long = by_window[_window_name(long_s)]
                burning = short["burn"] > rate and long["burn"] > rate
                alerts[pair] = burning
                key = (objective.id, pair)
                if burning and not self._latched.get(key):
                    self._latched[key] = True
                    self._alerts_total += 1
                    if self._alert_counter is not None:
                        self._alert_counter.inc(
                            objective=objective.id, window=pair
                        )
                    self.ring.record(
                        kind="slo",
                        cause=f"slo_{pair}_burn",
                        objective=objective.id,
                        metric=objective.metric,
                        window=pair,
                        burn_short=short["burn"],
                        burn_long=long["burn"],
                        window_short_s=short_s,
                        window_long_s=long_s,
                        threshold=rate,
                        budget_remaining=remaining,
                        detail=(
                            f"{objective.id} burning "
                            f"{short['burn']}x budget over "
                            f"{_window_name(short_s)} "
                            f"({long['burn']}x over {_window_name(long_s)}; "
                            f"page threshold {rate}x)"
                        ),
                    )
                elif not burning:
                    self._latched[key] = False
            rows.append(dict(
                objective.to_dict(),
                windows=by_window,
                budget_remaining=remaining,
                budget_consumed=round(consumed, 4),
                alerting=alerts,
            ))
        self._evaluations += 1
        report = {
            "targets": self.targets_spec,
            "burn": {
                "fast": {
                    "short_s": self.fast_s, "long_s": self.fast_long_s,
                    "threshold": self.fast_rate,
                },
                "slow": {
                    "short_s": self.slow_s, "long_s": self.slow_long_s,
                    "threshold": self.slow_rate,
                },
            },
            "budget_window_s": self.slow_long_s,
            "objectives": rows,
            "alerts_total": self._alerts_total,
            "evaluations": self._evaluations,
            # gofrlint: wall-clock — report display/correlation timestamp
            "ts": time.time(),
        }
        self._last_report = report
        return report

    # -- read side ------------------------------------------------------------
    def budget(self) -> dict[str, Any]:
        """The ``/admin/slo/budget`` payload: a fresh evaluation plus
        the most recent burn-alert evidence from the anomaly ring."""
        report = self.evaluate()
        return dict(
            report,
            recent_alerts=self.ring.events(limit=20, kind="slo"),
        )

    def headline(self) -> dict[str, Any]:
        """Compact rollup for /admin/overview and the /admin/engine
        scrape: the worst fast burn, the thinnest budget, who is
        alerting, lifetime alert count. Reuses the freshest evaluator
        report (the thread keeps it warm) rather than re-scanning."""
        with self._lock:
            report = self._last_report
        if report is None:
            report = self.evaluate()
        fast_name = _window_name(self.fast_s)
        worst_burn = 0.0
        worst_objective = None
        remaining_min = None
        alerting: list[str] = []
        for row in report["objectives"]:
            burn = row["windows"].get(fast_name, {}).get("burn", 0.0)
            if worst_objective is None or burn > worst_burn:
                worst_burn, worst_objective = burn, row["objective"]
            remaining = row["budget_remaining"]
            if remaining_min is None or remaining < remaining_min:
                remaining_min = remaining
            if row["alerting"]["fast"] or row["alerting"]["slow"]:
                alerting.append(row["objective"])
        return {
            "objectives": len(report["objectives"]),
            "worst_burn": worst_burn,
            "worst_objective": worst_objective,
            "budget_remaining_min": remaining_min,
            "alerting": alerting,
            "alerts_total": report["alerts_total"],
        }
