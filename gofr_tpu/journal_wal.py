"""Disk-backed write-ahead log behind the generation journal: a
``kill -9`` of a replica must not erase its resumable streams.

The in-memory :class:`~gofr_tpu.telemetry.GenerationJournal` survives
ENGINE death (wedge → recovery rebuild) but not PROCESS death — the
deque dies with the interpreter, and a SIGKILLed replica came back
amnesiac: every ``X-Resume-From`` against it fell to full replay on
some other replica, or truncated the client stream outright. This WAL
makes the journal's resume substrate durable with the same framing
discipline the KV wire format (``fleet/kvwire.py``) proved out: a
versioned magic, CRC32-framed records, and the property that every way
a file can lie — a torn tail from mid-write death, a flipped byte, a
truncated segment — is DETECTED and refused, never installed.

Layout (``JOURNAL_DIR``): numbered segments ``wal-<seq>.log``, each
``MAGIC + u32 version`` then frames of ``u8 kind + u32 len + u32 crc +
payload``. Appends go to the newest segment; at ``segment_bytes`` the
log rotates, writing a CHECKPOINT record (every live entry's full
state) at the head of the new segment so retention can drop old
segments without losing a live entry, and at most ``retain`` segments
are kept. Record kinds:

- ``open``  — a generation started (entry id, key, identity fields);
- ``tokens`` — emitted token ids appended to an entry (the per-token
  record whose cost the bench gate holds);
- ``finish`` / ``claim`` / ``retire`` — the entry stopped being
  resumable (clean completion / resumed / evicted);
- ``interrupt`` — the generation died mid-flight WITH the process
  still alive (the valuable record: it carries the cause);
- ``checkpoint`` — rotation-time snapshot of all live entries.

Recovery (:meth:`JournalWAL.recover`) replays segments oldest→newest,
stopping a segment at its first unparseable/CRC-failing frame (a torn
tail is expected after SIGKILL mid-append; everything before it is
intact by CRC and is kept — the truncation fuzz in
``tests/test_journal_wal.py`` holds exactly this line). Entries whose
final state is ``interrupted`` — or still ``open`` with no terminal
record, which is what SIGKILL leaves — rehydrate into the journal as
interrupted, resumable entries: the restarted replica serves
``X-Resume-From`` for its own pre-crash streams bit-identically.

Durability policy (``JOURNAL_FSYNC``): ``interrupt`` (default) flushes
every record to the OS (surviving process death, the threat model) and
``fsync``s on interruption, rotation, and close; ``always`` fsyncs
every record (surviving power loss, at a per-token cost the bench
measures); ``off`` only flushes. Import-light: stdlib only.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Optional

MAGIC = b"GJW1"
WIRE_VERSION = 1
_U32 = struct.Struct("<I")
_FRAME_HEAD = struct.Struct("<BII")  # kind, payload_len, crc32

K_OPEN = 1
K_TOKENS = 2
K_FINISH = 3
K_INTERRUPT = 4
K_CLAIM = 5
K_RETIRE = 6
K_CHECKPOINT = 7
_KINDS = (K_OPEN, K_TOKENS, K_FINISH, K_INTERRUPT, K_CLAIM, K_RETIRE,
          K_CHECKPOINT)

# a single frame's payload bound: a checkpoint of `capacity` entries at
# `max_tokens` tokens each stays far under this; anything larger is a
# framing error, not data (kvwire's MAX_BLOCK_BYTES discipline)
MAX_RECORD_BYTES = 1 << 24

FSYNC_POLICIES = ("always", "interrupt", "off")


class WALError(Exception):
    """A segment stopped being trustworthy (torn tail, flipped byte,
    bad magic). Recovery catches it per segment and keeps everything
    already verified; it never propagates into serving."""


def _frame(kind: int, payload: bytes) -> bytes:
    if len(payload) > MAX_RECORD_BYTES:
        raise ValueError(f"WAL record {len(payload)}B exceeds the bound")
    # the CRC covers the KIND byte too: a flipped kind would otherwise
    # reinterpret a perfectly-checksummed payload under the wrong schema
    crc = zlib.crc32(payload, zlib.crc32(bytes([kind])))
    return _FRAME_HEAD.pack(kind, len(payload), crc) + payload


def _iter_frames(data: bytes) -> Any:
    """Yield ``(kind, payload)`` from one segment's bytes, stopping at
    the first frame that cannot be trusted. Raises :class:`WALError`
    AFTER yielding every intact frame — callers keep the verified
    prefix and refuse the rest, which is the whole recovery contract."""
    if len(data) < len(MAGIC) + _U32.size:
        raise WALError("segment shorter than its header")
    if data[:len(MAGIC)] != MAGIC:
        raise WALError(f"bad segment magic {data[:len(MAGIC)]!r}")
    (version,) = _U32.unpack_from(data, len(MAGIC))
    if version != WIRE_VERSION:
        raise WALError(f"segment speaks WAL version {version}")
    pos = len(MAGIC) + _U32.size
    while pos < len(data):
        if len(data) - pos < _FRAME_HEAD.size:
            raise WALError("torn frame head at segment tail")
        kind, length, crc = _FRAME_HEAD.unpack_from(data, pos)
        if kind not in _KINDS or length > MAX_RECORD_BYTES:
            raise WALError(f"unparseable frame (kind {kind}, len {length})")
        start = pos + _FRAME_HEAD.size
        payload = data[start:start + length]
        if len(payload) != length:
            raise WALError("torn frame payload at segment tail")
        if zlib.crc32(payload, zlib.crc32(bytes([kind]))) != crc:
            raise WALError(f"frame failed its CRC at offset {pos}")
        pos = start + length
        yield kind, payload


class _EntryState:
    """One entry's replayed/live state: the WAL's own mirror, used both
    by recovery and by rotation checkpoints (the journal's JournalEntry
    objects are not reachable from here, and must not be — the WAL
    stays import-light and single-purpose)."""

    __slots__ = ("entry_id", "key", "model", "max_new_tokens", "seeded",
                 "deterministic", "tokens", "status", "reason")

    def __init__(self, entry_id: int, key: str, model: str,
                 max_new_tokens: int, seeded: bool, deterministic: bool,
                 tokens: Optional[list[int]] = None, status: str = "open",
                 reason: str = ""):
        self.entry_id = entry_id
        self.key = key
        self.model = model
        self.max_new_tokens = max_new_tokens
        self.seeded = seeded
        self.deterministic = deterministic
        self.tokens: list[int] = list(tokens or ())
        self.status = status  # open | interrupted | done
        self.reason = reason

    def to_json(self) -> dict[str, Any]:
        return {
            "id": self.entry_id, "key": self.key, "model": self.model,
            "mnt": self.max_new_tokens, "seeded": self.seeded,
            "det": self.deterministic, "tokens": self.tokens,
            "status": self.status, "reason": self.reason,
        }

    @classmethod
    def from_json(cls, raw: dict[str, Any]) -> "_EntryState":
        return cls(
            int(raw["id"]), str(raw["key"]), str(raw["model"]),
            int(raw["mnt"]), bool(raw["seeded"]), bool(raw["det"]),
            tokens=[int(t) for t in raw.get("tokens") or ()],
            status=str(raw.get("status") or "open"),
            reason=str(raw.get("reason") or ""),
        )


class JournalWAL:
    """The segmented on-disk log. Thread-safe: one internal lock covers
    append+rotate (emitting threads are per-request; the per-token
    append is a dict lookup, a small struct pack, and one buffered
    ``write`` — the bench gate holds its cost)."""

    def __init__(self, directory: str, segment_bytes: int = 1 << 20,
                 retain: int = 4, fsync: str = "interrupt",
                 logger: Any = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"JOURNAL_FSYNC '{fsync}' not one of {FSYNC_POLICIES}"
            )
        self.directory = directory
        self.segment_bytes = max(4096, int(segment_bytes))
        self.retain = max(1, int(retain))
        self.fsync_policy = fsync
        self.logger = logger
        self._lock = threading.Lock()
        self._file: Optional[Any] = None
        self._seq = 0
        self._size = 0
        self._next_id = 1
        self._live: dict[int, _EntryState] = {}
        self._closed = False
        # recovery evidence, surfaced on /admin/engine journal.wal
        self.recovered_entries = 0
        self.torn_segments = 0
        self.dropped_records = 0
        os.makedirs(self.directory, exist_ok=True)

    # -- recovery --------------------------------------------------------------
    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.directory, f"wal-{seq:08d}.log")

    def _list_segments(self) -> list[int]:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if name.startswith("wal-") and name.endswith(".log"):
                try:
                    out.append(int(name[4:-4]))
                except ValueError:
                    continue
        return sorted(out)

    def recover(self) -> list[dict[str, Any]]:
        """Replay every segment and return the RESUMABLE entries (final
        state ``interrupted``, or ``open`` with no terminal record — the
        SIGKILL signature), oldest first, as plain dicts the journal
        rehydrates from. Also positions the writer: appends go to a
        fresh segment with ids above everything seen, so a rehydrated
        entry can never collide with a new one."""
        entries: dict[int, _EntryState] = {}
        max_id = 0
        for seq in self._list_segments():
            self._seq = max(self._seq, seq)
            try:
                with open(self._segment_path(seq), "rb") as f:
                    data = f.read()
            except OSError:
                self.torn_segments += 1
                continue
            try:
                for kind, payload in _iter_frames(data):
                    try:
                        replayed = self._replay(entries, kind, payload)
                    except (ValueError, KeyError, struct.error) as exc:
                        # a CRC-valid frame whose payload still fails to
                        # parse means the WRITER was broken, not the
                        # disk — refuse the rest of the segment exactly
                        # like a torn tail
                        raise WALError(f"unreplayable frame: {exc}") from exc
                    max_id = max(max_id, replayed)
            except WALError as exc:
                # a torn tail after SIGKILL-mid-append is the EXPECTED
                # shape; everything before it was CRC-verified and kept
                self.torn_segments += 1
                if self.logger is not None:
                    self.logger.warnf(
                        "journal WAL segment %s torn: %s (kept the "
                        "verified prefix)", seq, exc,
                    )
        resumable = [
            e for e in sorted(entries.values(), key=lambda e: e.entry_id)
            if e.status in ("open", "interrupted")
        ]
        for state in resumable:
            if state.status == "open":
                state.status = "interrupted"
                state.reason = "process death (recovered from WAL)"
        self.recovered_entries = len(resumable)
        self._next_id = max_id + 1
        return [s.to_json() for s in resumable]

    def _replay(self, entries: dict[int, _EntryState], kind: int,
                payload: bytes) -> int:
        """Apply one replayed record; returns the highest entry id it
        referenced. Records referencing unknown ids (their open record
        lived in a lost segment prefix) are counted and dropped — an
        entry whose identity cannot be proven is never installed."""
        if kind == K_CHECKPOINT:
            snap = json.loads(payload.decode("utf-8"))
            top = 0
            for raw in snap.get("entries", ()):
                state = _EntryState.from_json(raw)
                entries[state.entry_id] = state
                top = max(top, state.entry_id)
            return max(top, int(snap.get("next_id", 1)) - 1)
        if kind == K_OPEN:
            raw = json.loads(payload.decode("utf-8"))
            state = _EntryState.from_json(raw)
            entries[state.entry_id] = state
            return state.entry_id
        if kind == K_TOKENS:
            (entry_id,) = _U32.unpack_from(payload)
            state = entries.get(entry_id)
            n = (len(payload) - _U32.size) // 4
            tokens = struct.unpack_from(f"<{n}i", payload, _U32.size)
            if state is None or state.status != "open":
                self.dropped_records += 1
            else:
                state.tokens.extend(tokens)
            return entry_id
        if kind == K_INTERRUPT:
            raw = json.loads(payload.decode("utf-8"))
            entry_id = int(raw["id"])
            state = entries.get(entry_id)
            if state is None:
                self.dropped_records += 1
            else:
                state.status = "interrupted"
                state.reason = str(raw.get("reason") or "")
            return entry_id
        # finish / claim / retire: the entry stopped being resumable
        (entry_id,) = _U32.unpack_from(payload)
        state = entries.get(entry_id)
        if state is not None:
            state.status = "done"
        return entry_id

    # -- writing ---------------------------------------------------------------
    def _open_segment(self) -> None:
        self._seq += 1
        path = self._segment_path(self._seq)
        self._file = open(path, "wb")
        self._file.write(MAGIC + _U32.pack(WIRE_VERSION))
        self._size = len(MAGIC) + _U32.size
        if self._live:
            snap = json.dumps(
                {"entries": [s.to_json() for s in self._live.values()],
                 "next_id": self._next_id},
                separators=(",", ":"),
            ).encode("utf-8")
            frame = _frame(K_CHECKPOINT, snap)
            self._file.write(frame)
            self._size += len(frame)
        self._file.flush()
        self._sync(force=True)
        for seq in self._list_segments()[:-self.retain]:
            try:
                os.remove(self._segment_path(seq))
            except OSError:
                pass

    def _sync(self, force: bool = False) -> None:
        if self._file is None or self.fsync_policy == "off":
            return
        if self.fsync_policy == "always" or force:
            try:
                os.fsync(self._file.fileno())
            except OSError:
                pass

    def _append(self, kind: int, payload: bytes, force_sync: bool = False,
                ) -> None:
        frame = _frame(kind, payload)
        with self._lock:
            if self._closed:
                return
            if self._file is None or self._size + len(frame) > (
                self.segment_bytes
            ):
                if self._file is not None:
                    self._file.flush()
                    self._sync(force=True)
                    self._file.close()
                self._open_segment()
            self._file.write(frame)
            self._size += len(frame)
            # flush ALWAYS: buffered bytes die with the process, and
            # process death is the threat model — the flush hands them
            # to the kernel, which survives SIGKILL; fsync (policy) is
            # for the power-loss threat model only
            self._file.flush()
            self._sync(force=force_sync)

    # -- journal-facing API ----------------------------------------------------
    def open_entry(self, key: str, model: str, max_new_tokens: int,
                   seeded: bool, deterministic: bool,
                   prior: Optional[list] = None) -> int:
        with self._lock:
            entry_id = self._next_id
            self._next_id += 1
            self._live[entry_id] = _EntryState(
                entry_id, key, model, max_new_tokens, seeded, deterministic,
                tokens=list(prior or ()),
            )
        state = self._live[entry_id]
        self._append(
            K_OPEN,
            json.dumps(state.to_json(), separators=(",", ":")).encode("utf-8"),
        )
        return entry_id

    def append_tokens(self, entry_id: int, tokens: Any) -> None:
        tokens = [int(t) for t in tokens]
        if not tokens:
            return
        # frame FIRST, mirror second: _append may rotate, and the
        # rotation checkpoint snapshots the mirror — updated before the
        # frame, the checkpoint would already contain this batch and
        # the K_TOKENS frame following it would replay it a SECOND time
        # on recovery (a duplicated token = a corrupted resume prefix)
        self._append(
            K_TOKENS,
            _U32.pack(entry_id) + struct.pack(f"<{len(tokens)}i", *tokens),
        )
        with self._lock:
            state = self._live.get(entry_id)
            if state is not None:
                state.tokens.extend(tokens)

    def finish(self, entry_id: int) -> None:
        self._forget(entry_id)
        self._append(K_FINISH, _U32.pack(entry_id))

    def claim(self, entry_id: int) -> None:
        self._forget(entry_id)
        self._append(K_CLAIM, _U32.pack(entry_id))

    def retire(self, entry_id: int) -> None:
        """Capacity eviction / truncation: the entry stops being
        resumable without having completed."""
        self._forget(entry_id)
        self._append(K_RETIRE, _U32.pack(entry_id))

    def interrupt(self, entry_id: int, reason: str) -> None:
        with self._lock:
            state = self._live.get(entry_id)
            if state is not None:
                state.status = "interrupted"
                state.reason = reason
        self._append(
            K_INTERRUPT,
            json.dumps({"id": entry_id, "reason": reason[:500]},
                       separators=(",", ":")).encode("utf-8"),
            # the record resume depends on: fsync under the default
            # policy, so even power loss right after an engine failure
            # keeps the interruption durable
            force_sync=True,
        )

    def adopt(self, entry_id: int, state: dict[str, Any]) -> None:
        """Re-track a RECOVERED entry as live (rehydration calls this so
        a later claim/eviction writes its terminal record, and rotation
        checkpoints carry it)."""
        with self._lock:
            self._live[entry_id] = _EntryState.from_json(state)

    def _forget(self, entry_id: int) -> None:
        with self._lock:
            self._live.pop(entry_id, None)

    # -- lifecycle / read side -------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._file is not None:
                self._file.flush()
                self._sync(force=True)
                self._file.close()
                self._file = None

    def stats(self) -> dict[str, Any]:
        segments = self._list_segments()
        size = 0
        for seq in segments:
            try:
                size += os.path.getsize(self._segment_path(seq))
            except OSError:
                pass
        with self._lock:
            live = len(self._live)
        return {
            "dir": self.directory,
            "segments": len(segments),
            "bytes": size,
            "segment_bytes": self.segment_bytes,
            "retain": self.retain,
            "fsync": self.fsync_policy,
            "live_entries": live,
            "recovered_entries": self.recovered_entries,
            "torn_segments": self.torn_segments,
            "dropped_records": self.dropped_records,
        }
