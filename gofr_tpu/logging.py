"""Leveled structured logging with two sinks and terminal pretty-printing.

Parity: /root/reference/pkg/gofr/logging/logger.go:19-203 and level.go:8-89.
Preserved semantics:

- six levels DEBUG < INFO < NOTICE < WARN < ERROR < FATAL (level.go:8);
- level filter, then ERROR/FATAL to stderr and the rest to stdout
  (logger.go:43-51);
- JSON entries ``{"level":..,"time":..,"message":..}`` when the sink is not a
  terminal, colorized pretty format when it is (logger.go:67-71, :176);
- typed log objects (HTTP request logs, SQL/Redis/service/TPU query logs)
  render with their own pretty formats (logger.go:106-131) — implemented
  here via a duck-typed ``pretty_terminal()`` / ``log_fields()`` protocol so
  datasources never import this module (the reference's cyclic-import rule,
  datasource/logger.go:4-16);
- streams are resolved at call time so test utilities can capture output by
  swapping ``sys.stdout`` / ``sys.stderr`` (testutil parity).
"""

from __future__ import annotations

import enum
import json
import sys
import time
from typing import Any, Optional, Protocol, runtime_checkable


class Level(enum.IntEnum):
    """Parity: logging/level.go:8-16."""

    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    FATAL = 6

    def color(self) -> int:
        # Parity: logging/level.go color codes (blue/cyan/green/yellow/red).
        return {
            Level.DEBUG: 36,
            Level.INFO: 34,
            Level.NOTICE: 32,
            Level.WARN: 33,
            Level.ERROR: 31,
            Level.FATAL: 35,
        }[self]


def level_from_string(name: str) -> Level:
    """Parity: logging/level.go:72-89 — unknown strings fall back to INFO."""
    try:
        return Level[(name or "").strip().upper()]
    except KeyError:
        return Level.INFO


@runtime_checkable
class PrettyLoggable(Protocol):
    """Typed log entries (RequestLog, SQLLog, RedisLog, ServiceLog, RPCLog,
    TPULog) implement this to get custom terminal rendering and flat JSON
    fields."""

    def pretty_terminal(self) -> str: ...

    def log_fields(self) -> dict[str, Any]: ...


def _is_terminal(stream: Any) -> bool:
    try:
        return bool(stream.isatty())
    except Exception:
        return False


def _fmt_message(args: tuple[Any, ...]) -> Any:
    if len(args) == 1:
        a = args[0]
        if isinstance(a, (str, int, float, bool, dict, list)) or a is None:
            return a
        if isinstance(a, PrettyLoggable):
            return a
        return str(a)
    return " ".join(str(a) for a in args)


class Logger:
    """Concrete logger. Parity: logging/logger.go:37-151.

    ``terminal`` tristate: None = auto-detect per write (so redirecting
    stdout in tests switches to JSON mode automatically, matching the
    reference's check at construction but more test-friendly).
    """

    def __init__(self, level: Level = Level.INFO, terminal: Optional[bool] = None):
        self.level = level
        self._terminal = terminal

    # -- public leveled API (parity: logging/logger.go:19-28) ---------------
    def debug(self, *args: Any) -> None:
        self._log(Level.DEBUG, args)

    def debugf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.DEBUG, fmt, args)

    def info(self, *args: Any) -> None:
        self._log(Level.INFO, args)

    def infof(self, fmt: str, *args: Any) -> None:
        self._logf(Level.INFO, fmt, args)

    # GoFr names the INFO pair Log/Logf; keep aliases for ergonomic parity.
    log = info
    logf = infof

    def notice(self, *args: Any) -> None:
        self._log(Level.NOTICE, args)

    def noticef(self, fmt: str, *args: Any) -> None:
        self._logf(Level.NOTICE, fmt, args)

    def warn(self, *args: Any) -> None:
        self._log(Level.WARN, args)

    def warnf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.WARN, fmt, args)

    def error(self, *args: Any) -> None:
        self._log(Level.ERROR, args)

    def errorf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.ERROR, fmt, args)

    def fatal(self, *args: Any) -> None:
        self._log(Level.FATAL, args)

    def fatalf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.FATAL, fmt, args)

    def change_level(self, level: Level) -> None:
        self.level = level

    # -- internals ----------------------------------------------------------
    def _logf(self, level: Level, fmt: str, args: tuple[Any, ...]) -> None:
        if level < self.level:
            return
        try:
            message = (fmt % args) if args else fmt
        except (TypeError, ValueError):
            try:
                message = fmt.format(*args)
            except (IndexError, KeyError, ValueError):
                # A log call must never crash the caller; degrade to a join.
                message = " ".join([fmt, *(str(a) for a in args)])
        self._write(level, message)

    def _log(self, level: Level, args: tuple[Any, ...]) -> None:
        if level < self.level:
            return
        self._write(level, _fmt_message(args))

    def _stream(self, level: Level) -> Any:
        # Parity: logger.go:43-51 — ERROR and above to stderr.
        return sys.stderr if level >= Level.ERROR else sys.stdout

    def _write(self, level: Level, message: Any) -> None:
        stream = self._stream(level)
        terminal = self._terminal if self._terminal is not None else _is_terminal(stream)
        now = time.time()  # gofrlint: wall-clock — rendered log-line timestamp (presentation)
        try:
            if terminal:
                stream.write(self._render_pretty(level, message, now))
            else:
                stream.write(self._render_json(level, message, now))
            stream.flush()
        except (ValueError, OSError):  # closed stream during shutdown
            pass

    def _render_json(self, level: Level, message: Any, now: float) -> str:
        entry: dict[str, Any] = {
            "level": level.name,
            "time": _rfc3339(now),
        }
        if isinstance(message, PrettyLoggable):
            entry["message"] = message.log_fields()
        else:
            entry["message"] = message
        return json.dumps(entry, default=str) + "\n"

    def _render_pretty(self, level: Level, message: Any, now: float) -> str:
        # Parity: logger.go:106-131 — "LEVL [ts] <typed or plain message>".
        ts = time.strftime("%H:%M:%S", time.localtime(now))
        head = f"\x1b[{level.color()}m{level.name[:4]}\x1b[0m [{ts}] "
        if isinstance(message, PrettyLoggable):
            body = message.pretty_terminal()
        elif isinstance(message, (dict, list)):
            body = json.dumps(message, default=str)
        else:
            body = str(message)
        return head + body + "\n"


def new_logger(level: Level | str = Level.INFO) -> Logger:
    """Parity: logging/logger.go:153-160."""
    if isinstance(level, str):
        level = level_from_string(level)
    return Logger(level)


def new_silent_logger() -> Logger:
    """Logger that emits nothing. Parity: logging/logger.go:163-174."""
    logger = Logger(Level.FATAL, terminal=False)
    logger._write = lambda *a, **k: None  # type: ignore[method-assign]
    return logger


def _rfc3339(now: float) -> str:
    lt = time.localtime(now)
    frac = int((now % 1) * 1e6)
    off = time.strftime("%z", lt)
    if len(off) == 5:  # +0000 -> +00:00 (RFC 3339 requires the colon)
        off = off[:3] + ":" + off[3:]
    return time.strftime("%Y-%m-%dT%H:%M:%S", lt) + f".{frac:06d}" + off
