"""Llama-family decoder-only transformer: RMSNorm, RoPE, GQA attention,
SwiGLU — pure JAX, static shapes, KV-cached ragged-batch decode.

TPU-first design notes:
- all shapes static under jit: prefill is bucketed by the serving layer
  (per-request true lengths passed separately), decode is a fixed [B, 1]
  step over a preallocated cache;
- attention runs through gofr_tpu.ops.attention (Pallas flash on TPU);
- weights default to bfloat16 with f32 norm/softmax accumulation; int8
  weight-only checkpoints route through gofr_tpu.models.quant.mm;
- params are plain nested dicts so pjit PartitionSpec trees mirror them
  (gofr_tpu.parallel.sharding names the same keys);
- the cache is ragged-batch: per-request lengths [B], per-batch
  dynamic_update_slice via vmap, so one compiled step serves requests at
  different positions (continuous-batching-ready);
- RoPE tables are built once per config (lru_cache) and embedded as jit
  constants — no trig on the decode hot path.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp

from gofr_tpu.models.quant import mm as _mm
from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import rms_norm
from gofr_tpu.ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    # KV-cache storage dtype (None -> dtype). float8_e4m3fn halves cache
    # HBM per token — 2x context length or decode slots on a capacity-
    # bound chip. Writes cast on merge; attention upcasts at its boundary.
    kv_dtype: Any = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def cache_dtype(self) -> Any:
        return self.kv_dtype or self.dtype


@functools.lru_cache(maxsize=16)
def _cached_freqs(head_dim: int, max_seq: int, theta: float):
    """Concrete per-config RoPE table, embedded as a constant in each jitted
    forward — no trig on the decode hot path.

    Computed AND cached as numpy: any jax array (even jnp.asarray of a
    constant) created during a jit trace is a tracer, and caching a tracer
    leaks it into later traces. A numpy array is concrete everywhere; the
    use sites convert with jnp.asarray inside their own trace."""
    import numpy as np

    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    freqs = np.outer(np.arange(max_seq, dtype=np.float32), inv_freq)
    return np.stack([np.cos(freqs), np.sin(freqs)], axis=-1).astype(np.float32)


def init_transformer(
    key: jax.Array, cfg: TransformerConfig, quantize: Any = False
) -> dict:
    """Weight layout mirrors Llama-3 shapes; initialization is scaled
    truncated-normal (serving weights come from checkpoints; init exists for
    tests and training-from-scratch).

    ``quantize`` ("int8"/"int4"; True = int8) quantizes each matmul weight
    IMMEDIATELY after creation, so peak device memory is the packed model
    plus ONE bf16 weight — init-then-quantize of the full tree would peak
    at 3x the packed size and OOM an 8B model on a 16GB chip. Values are
    bit-identical to ``quantize_params(init_transformer(key, cfg), mode)``."""
    from gofr_tpu.models.quant import quantizer_for, quantizer_for_key

    quantizer_for(quantize)  # validate the mode eagerly
    n_keys = cfg.n_layers * 7 + 3
    keys = iter(jax.random.split(key, n_keys))

    def dense(k: jax.Array, shape: tuple[int, ...], fan_in: int,
              name: str = "") -> Any:
        w = (jax.random.truncated_normal(k, -3, 3, shape) * (fan_in ** -0.5)).astype(cfg.dtype)
        # key-aware quantizer: the w8a8 lm_head carve-out lives in
        # quant.quantizer_for_key, not here
        quantize_fn = quantizer_for_key(quantize, name)
        return quantize_fn(w) if quantize_fn else w

    params: dict[str, Any] = {
        # embeddings stay high precision (the quantization scheme's rule)
        "embed": (
            jax.random.truncated_normal(next(keys), -3, 3, (cfg.vocab_size, cfg.dim))
            * (cfg.dim ** -0.5)
        ).astype(cfg.dtype),
        "norm_f": jnp.ones((cfg.dim,), cfg.dtype),
        "lm_head": dense(
            next(keys), (cfg.dim, cfg.vocab_size), cfg.dim, name="lm_head"
        ),
    }
    kv_dim = cfg.n_kv_heads * cfg.head_dim

    def make_layer() -> dict:
        return {
            "attn_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "wq": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
            "wk": dense(next(keys), (cfg.dim, kv_dim), cfg.dim),
            "wv": dense(next(keys), (cfg.dim, kv_dim), cfg.dim),
            "wo": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
            "mlp_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "w_gate": dense(next(keys), (cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_up": dense(next(keys), (cfg.dim, cfg.hidden_dim), cfg.dim),
            "w_down": dense(next(keys), (cfg.hidden_dim, cfg.dim), cfg.hidden_dim),
        }

    # layers live as ONE pytree level of [n_layers, ...] arrays, scanned in
    # the forward — one compiled layer body instead of n_layers copies.
    # Stacking is INCREMENTAL (preallocate + at[i].set, each layer freed
    # after placement): jnp.stack of all layers at once would hold the
    # whole model twice and OOM 8B-class models during boot.
    # (Quantized {"q","scale"} dicts thread per-field through the tree maps.)
    n = cfg.n_layers
    first = make_layer()
    stacked = jax.tree.map(
        lambda x: jnp.zeros((n,) + x.shape, x.dtype).at[0].set(x), first
    )
    del first
    for i in range(1, n):
        layer = make_layer()
        stacked = jax.tree.map(lambda s, x, i=i: s.at[i].set(x), stacked, layer)
        del layer
    params["layers"] = stacked
    return params


def _default_mlp(p: dict, h: jnp.ndarray) -> tuple[jnp.ndarray, dict]:
    """Dense SwiGLU MLP (the ``mlp_fn`` default); MoE swaps in routed
    experts here (models/moe.py)."""
    gated = jax.nn.silu(_mm(h, p["w_gate"])) * _mm(h, p["w_up"])
    return _mm(gated, p["w_down"]), {}


def _block(
    cfg: TransformerConfig,
    p: dict,
    x: jnp.ndarray,
    freqs: jnp.ndarray,
    positions: jnp.ndarray,
    kv_cache: Optional[tuple[jnp.ndarray, jnp.ndarray]] = None,
    starts: Optional[jnp.ndarray] = None,
    kv_lens: Optional[jnp.ndarray] = None,
    attn_fn: Optional[Any] = None,
    mlp_fn: Optional[Any] = None,
) -> tuple[jnp.ndarray, tuple[jnp.ndarray, jnp.ndarray], dict]:
    """One decoder block — the single implementation shared by the
    no-cache forward, the cached prefill/decode path, the sequence-parallel
    ring path (which passes ``attn_fn``), and the MoE model (which passes
    ``mlp_fn`` returning (out, aux_losses)).

    Without cache: attention over this call's keys (via ``attn_fn`` when
    given), returns (out, (k, v), aux). With cache: merges k/v into the
    per-batch cache at ``starts`` [B] and attends the full cache window;
    returns (out, (k_cache, v_cache), aux).
    """
    b, s, _ = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = _mm(h, p["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = _mm(h, p["wk"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = _mm(h, p["wv"]).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, freqs, positions)
    k = apply_rope(k, freqs, positions)

    if kv_cache is None:
        if attn_fn is not None:
            attn = attn_fn(q, k, v)
        else:
            attn = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        merged = (k, v)
    else:
        k_cache, v_cache = kv_cache

        def merge(cache_b, new_b, start_b):
            return jax.lax.dynamic_update_slice(cache_b, new_b, (start_b, 0, 0))

        k_cache = jax.vmap(merge)(k_cache, k.astype(k_cache.dtype), starts)
        v_cache = jax.vmap(merge)(v_cache, v.astype(v_cache.dtype), starts)
        attn = attention(
            q, k_cache, v_cache, causal=True, q_offset=starts,
            kv_lens=kv_lens, impl=cfg.attn_impl,
        )
        merged = (k_cache, v_cache)

    x = x + _mm(attn.reshape(b, s, cfg.dim), p["wo"])
    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    y, aux = (mlp_fn or _default_mlp)(p, h)
    x = x + y
    return x, merged, aux


def transformer_forward(
    params: dict, tokens: jnp.ndarray, cfg: TransformerConfig
) -> jnp.ndarray:
    """Full-sequence forward -> logits [B, S, V] (training / no-cache
    scoring). Layers run under lax.scan over stacked weights."""
    b, s = tokens.shape
    freqs = jnp.asarray(_cached_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta))
    positions = jnp.arange(s)
    x = params["embed"][tokens]

    def body(carry, layer_params):
        y, _, _ = _block(cfg, layer_params, carry, freqs, positions)
        return y, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["norm_f"], cfg.norm_eps)
    return _mm(x, params["lm_head"]).astype(jnp.float32)


# -- KV-cached ragged-batch serving path -------------------------------------

def init_cache(cfg: TransformerConfig, batch: int, max_seq: int | None = None) -> dict:
    """Cache layout [n_layers, B, max_seq, n_kv_heads, head_dim] with
    per-request ``lengths`` [B]. ``max_seq`` must not exceed cfg.max_seq
    (the RoPE table bounds valid positions)."""
    max_seq = max_seq or cfg.max_seq
    if max_seq > cfg.max_seq:
        raise ValueError(
            f"cache max_seq {max_seq} exceeds config max_seq {cfg.max_seq} "
            "(RoPE table bound)"
        )
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.cache_dtype),
        "v": jnp.zeros(shape, cfg.cache_dtype),
        "lengths": jnp.zeros((batch,), jnp.int32),
    }


def _run_cached(
    params: dict, tokens: jnp.ndarray, cache: dict, cfg: TransformerConfig
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared cached-forward body (prefill, decode, and the speculative
    verify all run THIS): ``tokens`` [B, S] starting at per-request
    ``cache['lengths']``. Returns the final-norm hidden states [B, S, D],
    the updated k/v stacks, and ``starts`` [B].

    Keys valid for query j of request b: cache positions <= starts_b + j
    (causal handles the per-query bound; kv_lens bounds the written region
    so never-written cache slots are excluded)."""
    b, s = tokens.shape
    starts = cache["lengths"]  # [B]
    freqs = jnp.asarray(_cached_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta))
    positions = starts[:, None] + jnp.arange(s)[None, :]  # [B, S]
    x = params["embed"][tokens]
    written = starts + s  # [B]

    def body(carry, inputs):
        layer_params, k_cache, v_cache = inputs
        y, (k_cache, v_cache), _ = _block(
            cfg, layer_params, carry, freqs, positions,
            kv_cache=(k_cache, v_cache), starts=starts, kv_lens=written,
        )
        return y, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    return rms_norm(x, params["norm_f"], cfg.norm_eps), k_new, v_new, starts


def _forward_with_cache(
    params: dict,
    tokens: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    lengths: Optional[jnp.ndarray],
) -> tuple[jnp.ndarray, dict]:
    """Run ``tokens`` [B, S] starting at per-request ``cache['lengths']``.
    ``lengths`` [B] gives the true (un-padded) token count of this call per
    request (defaults to S). Returns logits at each request's final real
    position and the updated cache."""
    b, s = tokens.shape
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    x, k_new, v_new, starts = _run_cached(params, tokens, cache, cfg)
    # gather each request's last REAL position (pad-aware bucketed prefill)
    last_idx = jnp.clip(lengths - 1, 0, s - 1)  # [B]
    x_last = jnp.take_along_axis(x, last_idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = _mm(x_last, params["lm_head"]).astype(jnp.float32)
    new_cache = {"k": k_new, "v": v_new, "lengths": starts + lengths}
    return logits, new_cache


def prefill(
    params: dict,
    tokens: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    lengths: Optional[jnp.ndarray] = None,
) -> tuple[jnp.ndarray, dict]:
    """Process a (possibly padded) prompt bucket [B, S]; ``lengths`` [B] are
    true prompt lengths. Returns next-token logits [B, V] + cache.

    Chunk-resume contract (chunked prefill, PREFILL_CHUNK_TOKENS): this
    call starts at ``cache['lengths']`` and attends the full written
    window, so feeding a prompt in bucket-sized slices through the SAME
    executable produces the same cache contents and final logits as one
    full-width call — each slice's keys land at their true positions and
    its queries see every earlier slice's KV. That is what lets the
    serving layer bound per-dispatch prefill compute without changing
    outputs (asserted bit-exact in tests/test_tpu.py)."""
    return _forward_with_cache(params, tokens, cache, cfg, lengths)


def decode_step(
    params: dict, token: jnp.ndarray, cache: dict, cfg: TransformerConfig
) -> tuple[jnp.ndarray, dict]:
    """One autoregressive step: ``token`` [B, 1] -> logits [B, V] + cache."""
    return _forward_with_cache(params, token, cache, cfg, None)


def verify_chunk(
    params: dict, tokens: jnp.ndarray, cache: dict, cfg: TransformerConfig
) -> tuple[jnp.ndarray, dict]:
    """Target-model verification step for speculative decoding: run
    ``tokens`` [B, S] (the pending token followed by S-1 draft tokens)
    through the SAME cached forward as prefill/decode (``_run_cached``)
    and return the greedy next token at EVERY position [B, S] plus the
    advanced cache. Position i's argmax is the target's continuation
    after consuming tokens[:i+1] — the host accepts the longest draft
    prefix that matches and takes position n as the bonus token. One
    dispatch verifies a whole draft chunk.

    Logits are computed in f32 (same cast as ``_forward_with_cache``) so
    the verify argmax sees the decode path's numerics; note XLA may still
    schedule the [B,S,·] matmuls differently than the [B,1,·] decode
    shapes, so near-tie logits can in principle break exact greedy
    equality on low-precision checkpoints."""
    s = tokens.shape[1]
    x, k_new, v_new, starts = _run_cached(params, tokens, cache, cfg)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)  # [B, S, V]
    next_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    new_cache = {"k": k_new, "v": v_new, "lengths": starts + s}
    return next_ids, new_cache


def verify_chunk_sampled(
    params: dict,
    tokens: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    draft_toks: jnp.ndarray,
    q: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray | float,
    top_k: jnp.ndarray | int = 0,
    top_p: jnp.ndarray | float = 1.0,
    min_p: jnp.ndarray | float = 0.0,
) -> tuple:
    """Canonical speculative SAMPLING verification (accept draft token x
    with prob min(1, p(x)/q(x)); on the first reject, resample from the
    residual normalize(max(p - q, 0)); after a full accept, sample the
    bonus from p) — the emitted sequence is distributed EXACTLY as
    sampling from the target's warped p, whatever the draft proposes.

    ``tokens`` [B, k] is the pending token + k-1 draft tokens;
    ``draft_toks`` [B, k-1] and ``q`` [B, k-1, V] are the draft's
    choices and the warped distributions it sampled them from (same
    temperature/top-k/top-p/min-p knobs — the guarantee is for the
    warped target distribution). Only k-1 drafts are tested so the
    accepted prefix always fits the draft cache's k written positions
    (the greedy path's same invariant). Returns (emitted [B, k], n_acc
    [B], advanced key, cache): emitted[:, j] for j < n_acc are accepted
    drafts, emitted[:, n_acc] is the correction/bonus, positions beyond
    are garbage."""
    from gofr_tpu.ops.sampling import warped_probs

    b, s = tokens.shape
    k_drafts = s - 1
    x, k_new, v_new, starts = _run_cached(params, tokens, cache, cfg)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)  # [B, S, V]
    v = logits.shape[-1]
    p = warped_probs(
        logits.reshape(b * s, v), temperature, top_k, top_p, min_p
    ).reshape(b, s, v)
    # accept tests for the k-1 drafts: u*q(x) < p(x) avoids the division
    px = jnp.take_along_axis(
        p[:, :k_drafts, :], draft_toks[..., None], axis=-1
    )[..., 0]  # [B, k-1]
    qx = jnp.take_along_axis(q, draft_toks[..., None], axis=-1)[..., 0]
    key, ku, kc = jax.random.split(key, 3)
    u = jax.random.uniform(ku, (b, k_drafts))
    acc = (u * qx < px).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(acc, axis=1), axis=1)  # [B], <= k-1
    # correction at the reject position (residual) or bonus at position
    # k-1 after a full accept: padding q with a zero row makes the
    # residual there collapse to p — exactly the bonus distribution
    idx = n_acc[:, None, None]
    p_at = jnp.take_along_axis(p, idx, axis=1)[:, 0]  # [B, V]
    q_pad = jnp.pad(q, ((0, 0), (0, 1), (0, 0)))
    q_at = jnp.take_along_axis(q_pad, idx, axis=1)[:, 0]
    resid = jnp.maximum(p_at - q_at, 0.0)
    mass = jnp.sum(resid, axis=-1, keepdims=True)
    # p <= q pointwise means rejection probability 0 — unreachable save
    # for float dust; fall back to p rather than divide by ~0
    dist = jnp.where(mass > 1e-9, resid / jnp.maximum(mass, 1e-9), p_at)
    corr = jax.random.categorical(
        kc, jnp.log(dist + 1e-30), axis=-1
    ).astype(jnp.int32)  # [B]
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    draft_pad = jnp.pad(draft_toks, ((0, 0), (0, 1)))
    emitted = jnp.where(
        pos < n_acc[:, None], draft_pad,
        jnp.where(pos == n_acc[:, None], corr[:, None], 0),
    )
    new_cache = {"k": k_new, "v": v_new, "lengths": starts + s}
    return emitted, n_acc, key, new_cache


def draft_chunk_sampled(
    params: dict,
    token: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    n_steps: int,
    key: jax.Array,
    temperature: jnp.ndarray | float,
    top_k: jnp.ndarray | int = 0,
    top_p: jnp.ndarray | float = 1.0,
    min_p: jnp.ndarray | float = 0.0,
) -> tuple:
    """Draft proposal for speculative SAMPLING: ``n_steps`` sampled
    steps that also return the warped per-step distributions q
    [B, n_steps, V] — the verify side needs q at the chosen tokens for
    the accept tests and the full rows for the residual. Returns
    (tokens [B, n_steps], q, advanced key, cache)."""
    from gofr_tpu.ops.sampling import warped_probs

    key, sub = jax.random.split(key)

    def body(carry, _):
        tok, c, k = carry
        logits, c = decode_step(params, tok, c, cfg)
        k, s = jax.random.split(k)
        qrow = warped_probs(logits, temperature, top_k, top_p, min_p)
        nxt = jax.random.categorical(
            s, jnp.log(qrow + 1e-30), axis=-1
        ).astype(jnp.int32)
        return (nxt[:, None], c, k), (nxt, qrow)

    (_, cache, _), (toks, qs) = jax.lax.scan(
        body, (token, cache, sub), None, length=n_steps
    )
    return (
        jnp.transpose(toks),
        jnp.transpose(qs, (1, 0, 2)),
        key,
        cache,
    )


def decode_chunk(
    params: dict,
    token: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    n_steps: int,
    key: jax.Array,
    temperature: jnp.ndarray | float = 0.0,
    top_k: jnp.ndarray | int = 0,
    top_p: jnp.ndarray | float = 1.0,
    min_p: jnp.ndarray | float = 0.0,
    presence: Optional[jnp.ndarray] = None,
    repetition_penalty: jnp.ndarray | float = 1.0,
    counts: Optional[jnp.ndarray] = None,
    presence_penalty: jnp.ndarray | float = 0.0,
    frequency_penalty: jnp.ndarray | float = 0.0,
    bias: jnp.ndarray | float = 0.0,
    with_logprobs: bool = False,
) -> tuple:
    """``n_steps`` autoregressive steps in ONE dispatch: decode + on-device
    sampling under ``lax.scan``, so a whole chunk of tokens costs a single
    host↔device round trip (the round trip, not the matmuls, dominates
    decode on remote-attached devices). ``token`` [B, 1] is the last known
    token; returns sampled tokens [B, n_steps] + the advanced cache.
    temperature/top_k/top_p/min_p are dynamic (0 temperature = greedy).

    ``presence`` [B, V] bool (context-token mask) turns on the penalized
    path: logits go through ``apply_penalties`` (CTRL repetition penalty
    over the context mask, plus the additive OpenAI presence/frequency
    penalties over the GENERATED-token ``counts`` [B, V] f32, plus the
    constant ``bias`` [B, V] f32 logit_bias row) before the greedy/sampled
    split, and freshly sampled tokens join presence and counts inside the
    scan; the updated mask and counts come back as extra outputs. All
    penalty knobs are dynamic operands — every combination shares one
    executable.

    ``with_logprobs`` (static) also returns the chosen tokens' RAW model
    log-probabilities [B, n_steps] f32 — log-softmax of the unpenalized
    logits, the standard serving-API logprob — plus the top-k alternative
    values/ids [B, n_steps, TOP_LOGPROBS] as the last outputs."""
    from gofr_tpu.ops.sampling import (
        apply_penalties,
        sample_logits,
        update_counts,
        update_presence,
    )

    if presence is not None and counts is None:
        counts = jnp.zeros(presence.shape, jnp.float32)

    def body(carry, _):
        if presence is None:
            tok, c, k = carry
        else:
            tok, c, k, pres, cnt = carry
        logits, c = decode_step(params, tok, c, cfg)
        k, sub = jax.random.split(k)
        sample_in = (
            logits if presence is None
            else apply_penalties(
                logits, pres, repetition_penalty, cnt,
                presence_penalty, frequency_penalty, bias,
            )
        )
        nxt = sample_logits(sample_in, sub, temperature, top_k, top_p, min_p)
        outs = nxt
        if with_logprobs:
            outs = (nxt, *_lp_outputs(logits, nxt))
        if presence is None:
            return (nxt[:, None], c, k), outs
        pres = update_presence(pres, nxt)
        cnt = update_counts(cnt, nxt)
        return (nxt[:, None], c, k, pres, cnt), outs

    carry0 = (
        (token, cache, key) if presence is None
        else (token, cache, key, presence, counts)
    )
    carry, outs = jax.lax.scan(body, carry0, None, length=n_steps)
    cache = carry[1]
    toks, lps, tvals, tids = outs if with_logprobs else (outs, None, None, None)
    result: tuple = (jnp.transpose(toks), cache)
    if presence is not None:
        result = result + (carry[3], carry[4])
    if with_logprobs:
        result = result + (
            jnp.transpose(lps),
            jnp.transpose(tvals, (1, 0, 2)),
            jnp.transpose(tids, (1, 0, 2)),
        )
    return result


def score_tokens(
    params: dict, tokens: jnp.ndarray, cfg: TransformerConfig
) -> jnp.ndarray:
    """Teacher-forcing scoring: [B, S] token ids -> [B, S-1] f32 where
    output[i-1] = log p(t_i | t_<i) — the loglikelihood primitive eval
    harnesses drive (completions echo+logprobs / max_tokens=0). One
    full-sequence forward; the [B, S, V] log-softmax stays on device and
    only the [B, S-1] chosen values cross the link. Causal attention
    makes bucket zero-padding safe: positions before the true length
    never see the padded tail."""
    logits = transformer_forward(params, tokens, cfg)
    lps = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(
        lps[:, :-1], tokens[:, 1:, None], axis=-1
    )[..., 0]


TOP_LOGPROBS = 5  # OpenAI's completions cap; compiled into every chunk


def _chosen_logprobs(logits: jnp.ndarray, nxt: jnp.ndarray) -> jnp.ndarray:
    """[B] f32 RAW log-probabilities of the chosen tokens — log-softmax of
    the UNPENALIZED logits, the one logprob convention every decode path
    (solo, pool, penalized pool) shares."""
    return jnp.take_along_axis(
        jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
        nxt[:, None], axis=-1,
    )[:, 0]


def _lp_outputs(
    logits: jnp.ndarray, nxt: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(chosen lp [B], top-k vals [B, TOP_LOGPROBS] f32, top-k ids
    [B, TOP_LOGPROBS] i32) from one shared log-softmax — the alternatives
    OpenAI's ``logprobs: N`` returns, raw-logits convention throughout."""
    lps = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(lps, nxt[:, None], axis=-1)[:, 0]
    tvals, tids = jax.lax.top_k(lps, TOP_LOGPROBS)
    return chosen, tvals, tids.astype(jnp.int32)


def decode_chunk_pool(
    params: dict,
    token: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    n_steps: int,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray | float = 0.0,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array, dict]:
    """PER-ROW sampling params plus the on-device RNG advance and the
    feed-forward token slice, so one pooled chunk is exactly ONE dispatch:
    on tunneled/remote devices every extra tiny host-driven op (a key
    split, a [B,1] slice) costs a dispatch round trip — measured ~135ms of
    overhead per chunk on a v5e tunnel, nearly the chunk's own compute.

    The chosen tokens' RAW log-softmax [B, n_steps] f32 rides every chunk
    unconditionally: one [B, V] log-softmax per step is noise next to the
    weight stream decode is bound by, and folding it in keeps the pool at
    ONE executable while letting logprobs requests (including every
    best_of candidate, which scores by mean logprob) share the batch
    instead of decoding solo. Returns (sampled tokens [B, n_steps],
    logprobs [B, n_steps], top-k logprob values/ids [B, n_steps,
    TOP_LOGPROBS], next input token [B, 1], advanced key, cache)."""
    from gofr_tpu.ops.sampling import sample_logits_rows

    key, sub = jax.random.split(key)

    def body(carry, _):
        tok, c, k = carry
        logits, c = decode_step(params, tok, c, cfg)
        k, s = jax.random.split(k)
        nxt = sample_logits_rows(logits, s, temperature, top_k, top_p, min_p)
        lp, tv, ti = _lp_outputs(logits, nxt)
        return (nxt[:, None], c, k), (nxt, lp, tv, ti)

    (tok, cache, _), (toks, lps, tvals, tids) = jax.lax.scan(
        body, (token, cache, sub), None, length=n_steps
    )
    return (jnp.transpose(toks), jnp.transpose(lps),
            jnp.transpose(tvals, (1, 0, 2)), jnp.transpose(tids, (1, 0, 2)),
            tok, key, cache)


def decode_chunk_pool_lora(
    stacked: dict,
    adapter_ids: jnp.ndarray,
    token: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    n_steps: int,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray | float = 0.0,
) -> tuple:
    """``decode_chunk_pool`` with PER-SLOT LoRA adapter selection:
    ``stacked`` is a ``build_lora_stack`` tree (the shared base plus a
    stacked adapter bank on every targeted weight) and ``adapter_ids``
    [B] i32 picks each slot's adapter (0 = base). Slots on the base
    gather the zero adapter — delta is exactly zero — so one executable
    serves any adapter/base slot mix, and adapter traffic shares the
    continuous-batching pool instead of decoding solo. Same outputs as
    ``decode_chunk_pool``."""
    from gofr_tpu.models.lora import attach_lora_ids

    params = attach_lora_ids(stacked, adapter_ids)
    return decode_chunk_pool(
        params, token, cache, cfg, n_steps, key, temperature, top_k,
        top_p, min_p,
    )


def decode_chunk_pool_penalized(
    params: dict,
    token: jnp.ndarray,
    cache: dict,
    cfg: TransformerConfig,
    n_steps: int,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray,
    presence: jnp.ndarray,
    rep: jnp.ndarray,
    counts: jnp.ndarray,
    presence_penalty: jnp.ndarray,
    frequency_penalty: jnp.ndarray,
    bias: jnp.ndarray,
) -> tuple:
    """``decode_chunk_pool`` with PER-SLOT penalty state: ``presence``
    [B, V] bool, ``counts`` [B, V] f32 and ``bias`` [B, V] f32 rows plus
    per-row scalars ``rep``/``presence_penalty``/``frequency_penalty``
    [B]. Slots without penalties carry identity knobs (rep 1, penalties
    0, zero bias row) and sample exactly as the plain pool executable
    does — ONE executable serves any penalized/plain slot mix, chosen by
    the pool only when at least one active slot is penalized (the extra
    [B, V] elementwise work is noise next to the decode matmuls, but the
    plain pool path stays untouched for penalty-free deployments).
    Returns (tokens [B, n_steps], RAW logprobs [B, n_steps] — log-softmax
    of the UNPENALIZED logits, the solo path's convention — top-k
    values/ids [B, n_steps, TOP_LOGPROBS], next token [B, 1], advanced
    key, cache, presence, counts)."""
    from gofr_tpu.ops.sampling import (
        apply_penalties,
        sample_logits_rows,
        update_counts,
        update_presence,
    )

    rep = jnp.asarray(rep, jnp.float32).reshape(-1, 1)
    pp = jnp.asarray(presence_penalty, jnp.float32).reshape(-1, 1)
    fp = jnp.asarray(frequency_penalty, jnp.float32).reshape(-1, 1)
    key, sub = jax.random.split(key)

    def body(carry, _):
        tok, c, k, pres, cnt = carry
        logits, c = decode_step(params, tok, c, cfg)
        k, s = jax.random.split(k)
        penalized = apply_penalties(logits, pres, rep, cnt, pp, fp, bias)
        nxt = sample_logits_rows(penalized, s, temperature, top_k, top_p, min_p)
        lp, tv, ti = _lp_outputs(logits, nxt)
        pres = update_presence(pres, nxt)
        cnt = update_counts(cnt, nxt)
        return (nxt[:, None], c, k, pres, cnt), (nxt, lp, tv, ti)

    (tok, cache, _, presence, counts), (toks, lps, tvals, tids) = jax.lax.scan(
        body, (token, cache, sub, presence, counts), None, length=n_steps
    )
    return (jnp.transpose(toks), jnp.transpose(lps),
            jnp.transpose(tvals, (1, 0, 2)), jnp.transpose(tids, (1, 0, 2)),
            tok, key, cache, presence, counts)
