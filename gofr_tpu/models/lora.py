"""LoRA adapters: low-rank deltas on the matmul weights.

TPU-first design: a LoRA-wrapped weight is just another *packed leaf*
flowing through the same quant-aware ``mm`` the models already use
(gofr_tpu.models.quant.mm) — ``{"w": base, "lora_a": [..., in, r],
"lora_b": [..., r, out], "lora_scale": alpha/r}`` where ``base`` may
itself be an int8/int4 packed dict (QLoRA-style: quantized frozen base,
bf16 adapters). The forward is ``mm(x, base) + (x @ A) @ B * scale``; the
low-rank path adds two skinny matmuls that XLA fuses alongside the main
one, and stacked ``[n_layers, ...]`` weights carry stacked adapters
through the same ``lax.scan``.

Training: ``lora_mask`` drives ``optax.masked`` so the optimizer holds
moments ONLY for adapter leaves — the base stays frozen and costs no
optimizer memory. ``merge_lora`` folds the deltas back into plain
weights for serving.

A-init is scaled-normal, B-init zeros: a fresh adapter is an exact
identity, so wrapping never changes outputs until training moves B.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp

from gofr_tpu.models.quant import (
    _QUANT_KEYS,
    dequantize_array,
    dequantize_array_int4,
    dequantize_array_w8a8,
    is_quantized,
    is_quantized_int4,
    is_quantized_w8a8,
    moe_skip_keys,
)

# weight names eligible for adapters (the attention + MLP matmuls; the
# reference LoRA recipe targets attention projections — pass ``keys`` to
# restrict)
_LORA_KEYS = frozenset(_QUANT_KEYS)


def is_lora(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {
        "w", "lora_a", "lora_b", "lora_scale",
    }


def lora_mm(x: jnp.ndarray, w: dict, base_mm: Any) -> jnp.ndarray:
    """``mm`` for a LoRA leaf: the base matmul (through ``base_mm`` so a
    quantized base keeps its fused path) plus the low-rank delta."""
    y = base_mm(x, w["w"])
    delta = (x @ w["lora_a"]) @ w["lora_b"]
    return y + (delta * w["lora_scale"]).astype(y.dtype)


def is_lora_stack(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "lora_stack_a" in leaf


def plora_mm(x: jnp.ndarray, w: dict, base_mm: Any) -> jnp.ndarray:
    """``mm`` for a pooled multi-LoRA leaf: every batch row selects its own
    adapter from the stacked bank. ``w`` carries ``lora_stack_a`` [A, in,
    r] / ``lora_stack_b`` [A, r, out] / ``lora_stack_scale`` [A, 1, 1]
    (A = adapters + 1; index 0 is the zero/identity adapter base rows use)
    and ``lora_ids`` [B] attached per dispatch by ``attach_lora_ids``. The
    per-row gather is tiny next to the base matmul (rank x dim vs dim x
    dim) and XLA keeps the skinny einsums beside it — the vLLM-class
    batched-multi-adapter decode, TPU-style: no custom gather kernel, the
    bank rides the executable as a normal stacked operand."""
    y = base_mm(x, w["w"])
    a = jnp.take(w["lora_stack_a"], w["lora_ids"], axis=0)      # [B, in, r]
    b = jnp.take(w["lora_stack_b"], w["lora_ids"], axis=0)      # [B, r, out]
    s = jnp.take(w["lora_stack_scale"], w["lora_ids"], axis=0)  # [B, 1, 1]
    # x is [B, ..., in] — [B, S, in] through the layers, [B, in] at the
    # last-position lm_head — so the adapter axes contract via ellipsis
    delta = jnp.einsum("b...i,bir->b...r", x, a)
    delta = jnp.einsum("b...r,bro->b...o", delta, b)
    s = s.reshape(s.shape[0], *([1] * (delta.ndim - 1)))
    return y + (delta * s).astype(y.dtype)


def build_lora_stack(base: dict, wrapped: "dict[str, dict]") -> dict:
    """Stack named wrapped trees (``apply_adapter`` outputs over ONE shared
    base) into a single pooled tree for per-slot adapter decode: each
    targeted leaf becomes ``{"w": base_leaf, "lora_stack_a/b/scale":
    [.., A, ..]}`` with index 0 the zero (identity) adapter and insertion
    order i at index i+1. Raises ValueError when adapters disagree on
    targets or rank (the pool needs one uniform bank; such sets serve
    solo)."""
    trees = list(wrapped.values())

    def walk(b: Any, ws: list, path: str) -> Any:
        if any(is_lora(w) for w in ws):
            if not all(is_lora(w) for w in ws):
                raise ValueError(
                    f"adapters disagree on target weight at {path or '/'}"
                )
            ranks = {w["lora_a"].shape[-1] for w in ws}
            if len(ranks) != 1:
                raise ValueError(
                    f"adapter rank mismatch at {path or '/'}: {sorted(ranks)}"
                )
            zeros = (
                jnp.zeros_like(ws[0]["lora_a"]),
                jnp.zeros_like(ws[0]["lora_b"]),
                jnp.zeros_like(ws[0]["lora_scale"]),
            )
            # axis=-3 inserts the adapter axis just before (in|r|1, r|out|1),
            # after any stacked-layer leading dims — lax.scan still slices
            # the layer axis first, leaving [A, in, r] inside the layer
            return {
                "w": b,
                "lora_stack_a": jnp.stack(
                    [zeros[0]] + [w["lora_a"] for w in ws], axis=-3
                ),
                "lora_stack_b": jnp.stack(
                    [zeros[1]] + [w["lora_b"] for w in ws], axis=-3
                ),
                "lora_stack_scale": jnp.stack(
                    [zeros[2]] + [w["lora_scale"] for w in ws], axis=-3
                ),
            }
        if isinstance(b, dict) and not _is_packed(b):
            return {
                k: walk(b[k], [w[k] for w in ws], f"{path}/{k}") for k in b
            }
        return b

    return walk(base, trees, "")


def attach_lora_ids(stacked: Any, ids: jnp.ndarray) -> Any:
    """Insert the per-row adapter selection [B] into every stacked leaf
    (broadcast over stacked-layer leading dims so ``lax.scan`` slices it
    alongside the bank). Called inside the jitted pool chunk — costs
    nothing at runtime."""

    def walk(t: Any) -> Any:
        if is_lora_stack(t):
            lead = t["lora_stack_a"].shape[:-3]
            return {
                **t,
                "lora_ids": jnp.broadcast_to(ids, (*lead, ids.shape[0])),
            }
        if isinstance(t, dict):
            return {k: walk(v) for k, v in t.items()}
        return t

    return walk(stacked)


def add_lora(
    params: dict,
    key: jax.Array,
    rank: int = 8,
    alpha: float = 16.0,
    keys: Optional[Iterable[str]] = None,
) -> dict:
    """Wrap eligible weights with fresh (identity) adapters. Stacked
    ``[L, in, out]`` weights get stacked ``[L, in, r]``/``[L, r, out]``
    adapters. The wrapped tree serves and trains through the existing
    model forwards unchanged."""
    eligible = frozenset(keys) if keys is not None else _LORA_KEYS

    def reject_w8a8(tree: Any) -> None:
        if isinstance(tree, dict):
            if is_quantized_w8a8(tree):
                raise ValueError(
                    "add_lora over a w8a8 base is unsupported: the "
                    "activation round-to-int8 has zero gradient, so "
                    "adapters below the first w8a8 matmul would train on "
                    "silent zeros. Train (QLoRA) over an int8/int4 base "
                    "and re-quantize w8a8 for deployment."
                )
            for v in tree.values():
                reject_w8a8(v)

    reject_w8a8(params)
    leaves: list[tuple[str, Any]] = []

    def collect(tree: Any) -> None:
        if isinstance(tree, dict) and not _is_packed(tree):
            skip = moe_skip_keys(tree)
            for k, v in tree.items():
                if k in eligible and k not in skip and _weight_shape(v) is not None:
                    leaves.append((k, v))
                else:
                    collect(v)

    collect(params)
    subkeys = iter(jax.random.split(key, max(len(leaves), 1)))

    def wrap(tree: Any) -> Any:
        if isinstance(tree, dict) and not _is_packed(tree):
            skip = moe_skip_keys(tree)
            out = {}
            for k, v in tree.items():
                shape = _weight_shape(v)
                if k in eligible and k not in skip and shape is not None:
                    lead, i, o = shape
                    a = (
                        jax.random.normal(next(subkeys), (*lead, i, rank))
                        * (i ** -0.5)
                    ).astype(jnp.bfloat16)
                    b = jnp.zeros((*lead, rank, o), jnp.bfloat16)
                    out[k] = {
                        "w": v,
                        "lora_a": a,
                        "lora_b": b,
                        # [*lead, 1, 1] so stacked layer weights scan their
                        # scale alongside the adapters (scan slices every
                        # leaf's leading axis)
                        "lora_scale": jnp.full(
                            (*lead, 1, 1), alpha / rank, jnp.float32
                        ),
                    }
                else:
                    out[k] = wrap(v)
            return out
        return tree

    return wrap(params)


def _is_packed(tree: dict) -> bool:
    return (
        is_quantized(tree) or is_quantized_int4(tree)
        or is_quantized_w8a8(tree) or is_lora(tree)
    )


def _weight_shape(v: Any) -> Optional[tuple[tuple[int, ...], int, int]]:
    """(leading dims, in, out) for a wrappable weight: a plain >=2-D array
    or a quantized packed dict (QLoRA base)."""
    if isinstance(v, dict):
        if is_quantized(v) or is_quantized_int4(v) or is_quantized_w8a8(v):
            q = v.get("q", v.get("q4", v.get("q8")))
            return q.shape[:-2], q.shape[-2], q.shape[-1]
        return None
    if hasattr(v, "ndim") and v.ndim >= 2:
        return v.shape[:-2], v.shape[-2], v.shape[-1]
    return None


def lora_mask(params: dict) -> Any:
    """True exactly at adapter leaves (``lora_a``/``lora_b``) — the mask
    for ``optax.masked``: the optimizer sees only adapter parameters."""

    def walk(tree: Any) -> Any:
        if is_lora(tree):
            return {
                "w": jax.tree.map(lambda _: False, tree["w"]),
                "lora_a": True,
                "lora_b": True,
                "lora_scale": False,
            }
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return False

    return walk(params)


def lora_optimizer(inner: Any, params: dict) -> Any:
    """Freeze everything but the adapters: ``inner`` updates adapter
    leaves, every other parameter gets a zero update and no optimizer
    state (the memory point of LoRA fine-tuning)."""
    import optax

    mask = lora_mask(params)
    inverse = jax.tree.map(lambda m: not m, mask)
    return optax.chain(
        optax.masked(inner, mask),
        optax.masked(optax.set_to_zero(), inverse),
    )


def split_lora(params: dict) -> tuple[Any, Any]:
    """Split a wrapped tree into (adapters, rest): ``adapters`` holds ONLY
    the ``lora_a``/``lora_b`` leaves — the differentiable subtree — and
    ``rest`` everything else. Training differentiates w.r.t. ``adapters``
    alone, which is what makes QLoRA work (an int8/int4 base is not a
    valid grad input) and skips computing base grads entirely."""

    def walk(tree: Any) -> tuple[Any, Any]:
        if is_lora(tree):
            return (
                {"lora_a": tree["lora_a"], "lora_b": tree["lora_b"]},
                {"w": tree["w"], "lora_scale": tree["lora_scale"]},
            )
        if isinstance(tree, dict) and not _is_packed(tree):
            adapters: dict = {}
            rest: dict = {}
            for k, v in tree.items():
                a, r = walk(v)
                if a is not None:
                    adapters[k] = a
                rest[k] = r
            return (adapters or None), rest
        return None, tree

    return walk(params)


def combine_lora(adapters: Any, rest: Any) -> dict:
    """Inverse of ``split_lora``: rebuild the wrapped tree (called inside
    the jitted loss, so it costs nothing at runtime)."""
    if isinstance(rest, dict) and set(rest) == {"w", "lora_scale"}:
        return {**rest, **adapters}
    if isinstance(rest, dict):
        return {
            k: combine_lora(adapters.get(k) if adapters else None, v)
            for k, v in rest.items()
        }
    return rest


def init_lora_train_state(params: dict, optimizer: Any) -> dict:
    """Training state for adapter-only fine-tuning: the optimizer holds
    moments for the adapter subtree only (the memory point of LoRA)."""
    adapters, rest = split_lora(params)
    return {
        "adapters": adapters,
        "rest": rest,
        "opt_state": optimizer.init(adapters),
        "step": jnp.zeros((), jnp.int32),
    }


def make_lora_train_step(cfg: Any, optimizer: Any, loss_fn: Any = None) -> Any:
    """Jitted adapter-only train step (QLoRA-ready: the frozen base may be
    int8/int4 packed — it is never a grad input). ``loss_fn`` defaults to
    the next-token loss; signature (params, tokens, cfg)."""
    import optax

    if loss_fn is None:
        from gofr_tpu.training.trainer import cross_entropy_loss

        loss_fn = cross_entropy_loss

    def _step(carry: dict, rest: Any, tokens: jnp.ndarray) -> tuple[dict, dict]:
        def f(adapters: Any) -> jnp.ndarray:
            return loss_fn(combine_lora(adapters, rest), tokens, cfg)

        loss, grads = jax.value_and_grad(f)(carry["adapters"])
        updates, opt_state = optimizer.update(
            grads, carry["opt_state"], carry["adapters"]
        )
        adapters = optax.apply_updates(carry["adapters"], updates)
        new_carry = {
            "adapters": adapters, "opt_state": opt_state,
            "step": carry["step"] + 1,
        }
        return new_carry, {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": new_carry["step"],
        }

    # donate ONLY the adapter carry: the frozen base ("rest") is shared
    # with the caller's wrapped tree and must survive every step
    jitted = jax.jit(_step, donate_argnums=(0,))

    def train_step(state: dict, tokens: Any) -> tuple[dict, dict]:
        carry = {
            "adapters": state["adapters"],
            "opt_state": state["opt_state"],
            "step": state["step"],
        }
        new_carry, metrics = jitted(carry, state["rest"], tokens)
        return {**new_carry, "rest": state["rest"]}, metrics

    return train_step


def export_adapter(state: dict) -> dict:
    """Self-contained adapter artifact from a LoRA train state: the
    adapter subtree plus its per-leaf scales (scales live in ``rest``, so
    the adapters alone would lose the alpha/rank ratio). Orbax-saveable;
    ``apply_adapter`` re-attaches it to any same-shape base."""

    def scales(tree: Any) -> Any:
        if isinstance(tree, dict) and set(tree) == {"w", "lora_scale"}:
            return tree["lora_scale"]
        if isinstance(tree, dict):
            out = {k: scales(v) for k, v in tree.items()}
            return {k: v for k, v in out.items() if v is not None} or None
        return None

    return {"adapters": state["adapters"], "scales": scales(state["rest"])}


def apply_adapter(base: dict, artifact: dict) -> dict:
    """Attach a saved adapter artifact to a base param tree -> a wrapped
    tree (the multi-LoRA serving path: every wrapped tree SHARES the base
    arrays, so n adapters cost n × adapter bytes, not n × model bytes).
    The base may be quantized; shapes must match the training base."""
    adapters, scales = artifact["adapters"], artifact["scales"]

    def walk(b: Any, a: Any, s: Any) -> Any:
        if isinstance(a, dict) and set(a) == {"lora_a", "lora_b"}:
            lead, i, o = _weight_shape(b)
            rank = a["lora_a"].shape[-1]
            # full-shape check including stacked leading (layer) dims: a
            # wrong-depth adapter must fail HERE with a clear error, not
            # inside a jitted scan later
            want_a = (*lead, i, rank)
            want_b = (*lead, rank, o)
            if (
                tuple(a["lora_a"].shape) != want_a
                or tuple(a["lora_b"].shape) != want_b
            ):
                raise ValueError(
                    f"adapter shapes {tuple(a['lora_a'].shape)} x "
                    f"{tuple(a['lora_b'].shape)} do not fit base weight "
                    f"{(*lead, i, o)} (expected {want_a} x {want_b})"
                )
            return {"w": b, "lora_a": a["lora_a"], "lora_b": a["lora_b"],
                    "lora_scale": s}
        if isinstance(a, dict):
            return {
                k: walk(b[k], a[k], s[k]) if a.get(k) is not None else b[k]
                for k in b
            }
        return b

    return walk(base, adapters, scales)


def merge_lora(params: dict, dtype: Any = None) -> dict:
    """Fold adapters into plain weights (serving export): ``w + A@B·s``.
    Quantized bases dequantize first — the merged tree is full-precision
    (re-quantize with ``quantize_params`` if desired)."""

    def merge_leaf(leaf: dict) -> jnp.ndarray:
        w = leaf["w"]
        if is_quantized(w):
            w = dequantize_array(w)
        elif is_quantized_int4(w):
            w = dequantize_array_int4(w)
        elif is_quantized_w8a8(w):
            w = dequantize_array_w8a8(w)
        out_dtype = dtype or w.dtype
        delta = (
            leaf["lora_a"].astype(jnp.float32) @ leaf["lora_b"].astype(jnp.float32)
        ) * leaf["lora_scale"]
        return (w.astype(jnp.float32) + delta).astype(out_dtype)

    def walk(tree: Any) -> Any:
        if is_lora(tree):
            return merge_leaf(tree)
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(params)
