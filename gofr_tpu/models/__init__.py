"""Model family served by the TPU datasource.

Pure-JAX functional models: each module is an ``init(key, cfg) -> params``
pytree builder plus jit-compatible apply functions. No framework-level
Module classes — parameters are plain nested dicts, which shard cleanly
under pjit (gofr_tpu.parallel builds PartitionSpec trees matching these
dicts by name).

Families: MLP (BASELINE config 1), BERT-style encoder for embeddings
(config 2), Llama-family decoder for generation (configs 3-4).
"""

from gofr_tpu.models.bert import BertConfig, bert_embed, init_bert
from gofr_tpu.models.mlp import MLPConfig, init_mlp, mlp_forward
from gofr_tpu.models.transformer import (
    TransformerConfig,
    decode_step,
    init_cache,
    init_transformer,
    prefill,
    transformer_forward,
)

__all__ = [
    "MLPConfig", "init_mlp", "mlp_forward",
    "BertConfig", "init_bert", "bert_embed",
    "TransformerConfig", "init_transformer", "transformer_forward",
    "prefill", "decode_step", "init_cache",
]
