"""Tiny MLP — the CPU-PJRT smoke model (BASELINE.json configs[1]: 2-layer
MLP behind GET /infer)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 64
    hidden_dim: int = 256
    out_dim: int = 16
    dtype: Any = jnp.float32


def init_mlp(key: jax.Array, cfg: MLPConfig) -> dict:
    k1, k2 = jax.random.split(key)
    scale1 = (2.0 / cfg.in_dim) ** 0.5
    scale2 = (2.0 / cfg.hidden_dim) ** 0.5
    return {
        "w1": (jax.random.normal(k1, (cfg.in_dim, cfg.hidden_dim)) * scale1).astype(cfg.dtype),
        "b1": jnp.zeros((cfg.hidden_dim,), cfg.dtype),
        "w2": (jax.random.normal(k2, (cfg.hidden_dim, cfg.out_dim)) * scale2).astype(cfg.dtype),
        "b2": jnp.zeros((cfg.out_dim,), cfg.dtype),
    }


def mlp_forward(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]
