"""Mixture-of-Experts decoder: top-k routed SwiGLU experts per layer.

The reference has no ML components at all (SURVEY.md §2 "EP: absent");
expert parallelism is a first-class requirement of the TPU build. This
module holds the model definition and the exact (dense) compute path:

- ``MoEConfig`` extends the dense transformer config with expert counts
  and routing hyperparameters (Mixtral-style: every layer's MLP is a
  top-k mixture of SwiGLU experts; attention is unchanged GQA);
- the router is a linear gate over the hidden state; top-k softmax
  weights are renormalized over the chosen experts;
- ``moe_forward`` computes every expert for every token and mixes by the
  routing weights — exact, no capacity drops, O(E·T·D·F) compute. It is
  the single-device serving path for small models and the numerical
  reference the expert-parallel path (gofr_tpu.parallel.expert, which
  dispatches tokens over the ``ep`` mesh axis with all_to_all) is tested
  against;
- auxiliary losses: Switch-style load-balance loss and router z-loss,
  accumulated across layers and returned beside the logits.

Capacity-based dispatch (static shapes for XLA) lives in ``_routing`` and
is shared by the expert-parallel path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.quant import mm as _mm
from gofr_tpu.models.transformer import TransformerConfig, _block, _cached_freqs
from gofr_tpu.ops.norms import rms_norm


@dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0  # expert slots = T·k·factor/E (EP path)
    aux_weight: float = 0.01  # load-balance loss weight
    z_weight: float = 1e-3  # router z-loss weight


def init_moe(key: jax.Array, cfg: MoEConfig) -> dict:
    """Param tree: attention weights match the dense transformer; the MLP
    is replaced by a router [D, E] and stacked expert weights [E, D, F]."""
    n_keys = cfg.n_layers * 9 + 3
    keys = iter(jax.random.split(key, n_keys))

    def dense(k: jax.Array, shape: tuple[int, ...], fan_in: int) -> jnp.ndarray:
        return (jax.random.truncated_normal(k, -3, 3, shape) * (fan_in ** -0.5)).astype(cfg.dtype)

    params: dict[str, Any] = {
        "embed": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "norm_f": jnp.ones((cfg.dim,), cfg.dtype),
        "lm_head": dense(next(keys), (cfg.dim, cfg.vocab_size), cfg.dim),
    }
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((cfg.dim,), cfg.dtype),
                "wq": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
                "wk": dense(next(keys), (cfg.dim, kv_dim), cfg.dim),
                "wv": dense(next(keys), (cfg.dim, kv_dim), cfg.dim),
                "wo": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
                "mlp_norm": jnp.ones((cfg.dim,), cfg.dtype),
                # router in f32: routing decisions are precision-sensitive
                "router": dense(next(keys), (cfg.dim, cfg.n_experts), cfg.dim).astype(jnp.float32),
                "w_gate": dense(next(keys), (cfg.n_experts, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_up": dense(next(keys), (cfg.n_experts, cfg.dim, cfg.hidden_dim), cfg.dim),
                "w_down": dense(next(keys),
                                (cfg.n_experts, cfg.hidden_dim, cfg.dim),
                                cfg.hidden_dim),
            }
        )
    params["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return params


def _route_top_k(
    logits: jnp.ndarray, top_k: int
) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Top-k expert choice from router logits [T, E]: returns renormalized
    weights [T, k], indices [T, k], and the aux-loss dict."""
    n_experts = logits.shape[-1]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    gate_vals, expert_idx = lax.top_k(gates, top_k)
    gate_vals = gate_vals / gate_vals.sum(-1, keepdims=True)
    # Switch load-balance: E · Σ_e (token fraction to e) · (mean router prob e)
    me = gates.mean(axis=0)
    f = jax.nn.one_hot(expert_idx[:, 0], n_experts).mean(axis=0)
    load_balance = n_experts * jnp.sum(f * me)
    z = jnp.mean(jax.scipy.special.logsumexp(logits.astype(jnp.float32), -1) ** 2)
    return gate_vals, expert_idx, {"load_balance": load_balance, "router_z": z}


def _routing(
    logits: jnp.ndarray, top_k: int, capacity: int
) -> tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Capacity-bounded dispatch/combine tensors (GShard style) — static
    shapes for XLA. dispatch/combine: [T, E, C]; tokens overflowing an
    expert's C slots are dropped (their residual stream passes through)."""
    t, n_experts = logits.shape
    gate_vals, expert_idx, aux = _route_top_k(logits, top_k)
    dispatch = jnp.zeros((t, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, n_experts, capacity), jnp.float32)
    counts = jnp.zeros((n_experts,), jnp.float32)
    for j in range(top_k):
        oh = jax.nn.one_hot(expert_idx[:, j], n_experts)  # [T, E]
        pos = counts[None, :] + jnp.cumsum(oh, axis=0) - oh  # slot before me
        pos_t = jnp.sum(pos * oh, axis=-1).astype(jnp.int32)  # [T]
        slot = jax.nn.one_hot(pos_t, capacity) * (pos_t < capacity)[:, None]
        d_j = oh[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + gate_vals[:, j, None, None] * d_j
        counts = counts + oh.sum(axis=0)
    return dispatch, combine, aux


def _expert_ffn(
    w_gate: jnp.ndarray, w_up: jnp.ndarray, w_down: jnp.ndarray, xs: jnp.ndarray
) -> jnp.ndarray:
    """SwiGLU over per-expert token blocks: xs [E, C, D] -> [E, C, D]."""
    g = jnp.einsum("ecd,edf->ecf", xs, w_gate)
    u = jnp.einsum("ecd,edf->ecf", xs, w_up)
    return jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, w_down)


def _moe_mlp_dense(p: dict, x: jnp.ndarray, cfg: MoEConfig) -> tuple[jnp.ndarray, dict]:
    """Exact mixture: every expert computes every token, outputs mixed by
    the renormalized top-k weights. x [B, S, D]."""
    b, s, d = x.shape
    xt = x.reshape(b * s, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), p["router"])
    gate_vals, expert_idx, aux = _route_top_k(logits, cfg.top_k)
    g = jnp.einsum("td,edf->tef", xt, p["w_gate"])
    u = jnp.einsum("td,edf->tef", xt, p["w_up"])
    y_all = jnp.einsum("tef,efd->ted", jax.nn.silu(g) * u, p["w_down"])  # [T, E, D]
    oh = jax.nn.one_hot(expert_idx, cfg.n_experts)  # [T, k, E]
    w = jnp.sum(gate_vals[:, :, None] * oh, axis=1)  # [T, E]
    out = jnp.einsum("te,ted->td", w.astype(y_all.dtype), y_all)
    return out.reshape(b, s, d).astype(x.dtype), aux


def moe_block(
    cfg: MoEConfig,
    p: dict,
    x: jnp.ndarray,
    freqs: jnp.ndarray,
    positions: jnp.ndarray,
    moe_mlp: Any = _moe_mlp_dense,
) -> tuple[jnp.ndarray, dict]:
    """The canonical decoder block (models/transformer.py ``_block``: GQA
    attention + residual) with the MLP swapped for routed experts."""
    y, _, aux = _block(
        cfg, p, x, freqs, positions, mlp_fn=lambda pp, h: moe_mlp(pp, h, cfg)
    )
    return y, aux


def moe_forward(
    params: dict, tokens: jnp.ndarray, cfg: MoEConfig, moe_mlp: Any = _moe_mlp_dense
) -> tuple[jnp.ndarray, dict]:
    """Full forward -> (logits [B, S, V] f32, aux losses averaged over
    layers)."""
    b, s = tokens.shape
    freqs = jnp.asarray(_cached_freqs(cfg.head_dim, cfg.max_seq, cfg.rope_theta))
    positions = jnp.arange(s)
    x = params["embed"][tokens]

    def body(carry, layer_params):
        y, aux = moe_block(cfg, layer_params, carry, freqs, positions, moe_mlp)
        return y, aux

    x, aux_stack = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["norm_f"], cfg.norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    aux = {k: v.mean() for k, v in aux_stack.items()}
    return logits, aux


def moe_loss(params: dict, tokens: jnp.ndarray, cfg: MoEConfig) -> jnp.ndarray:
    """Next-token loss + weighted aux losses (dense/exact path)."""
    from gofr_tpu.ops.loss import next_token_nll

    logits, aux = moe_forward(params, tokens[:, :-1], cfg)
    nll = next_token_nll(logits, tokens[:, 1:]).mean()
    return nll + cfg.aux_weight * aux["load_balance"] + cfg.z_weight * aux["router_z"]
