"""Real-model ingestion: safetensors reader + HF-Llama weight mapping.

The north-star serving config (BASELINE.json: Llama-3-8B via http-server)
must boot from a real released checkpoint, not only seeded init. The
reference framework has no model loading at all (it is a Go microservice
framework); this module is the TPU-native equivalent of its datasource
connectors: MODEL_PATH pointing at a ``.safetensors`` file (or an HF
checkpoint directory, possibly sharded) loads directly into the serving
param tree.

Design:
- a from-scratch mmap-backed safetensors parser (the format is an 8-byte
  little-endian header length + JSON header + raw little-endian tensor
  bytes); tensors are zero-copy numpy views on the mapped file, so loading
  is incremental — one tensor crosses host->device at a time and an 8B
  checkpoint never exists twice in host memory;
- HF Llama name mapping (model.layers.N.self_attn.q_proj.weight -> stacked
  layers/wq[N], transposed [out,in]->[in,out] since HF stores PyTorch
  nn.Linear layout and our matmuls are x @ w). HF checkpoints use the same
  split-half RoPE convention as ops/rope.py, so weights map with NO
  permutation;
- optional int8 weight-only quantization DURING load (models/quant.py
  scheme), so peak device memory for an 8B is the int8 tree plus one bf16
  layer stack — never the full bf16 model.
"""

from __future__ import annotations

import json
import mmap
import os
from typing import Any, Iterator, Optional

import numpy as np

_DTYPES: dict[str, Any] = {}


def _dtype(name: str) -> Any:
    if not _DTYPES:
        import ml_dtypes  # ships with jax

        _DTYPES.update({
            "F64": np.float64, "F32": np.float32, "F16": np.float16,
            "BF16": ml_dtypes.bfloat16, "I64": np.int64, "I32": np.int32,
            "I16": np.int16, "I8": np.int8, "U8": np.uint8, "BOOL": np.bool_,
            "F8_E4M3": ml_dtypes.float8_e4m3fn, "F8_E5M2": ml_dtypes.float8_e5m2,
        })
    try:
        return _DTYPES[name]
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {name!r}") from None


class SafetensorsFile:
    """One ``.safetensors`` file: parsed header + zero-copy tensor views.

    Format: [u64 little-endian header_len][header JSON][raw tensor data];
    each header entry maps name -> {dtype, shape, data_offsets:[begin,end)}
    relative to the end of the header.
    """

    def __init__(self, path: str):
        self.path = path
        f = open(path, "rb")
        self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        f.close()
        header_len = int.from_bytes(self._mm[:8], "little")
        if header_len > len(self._mm) - 8:
            raise ValueError(f"{path}: corrupt safetensors header length {header_len}")
        header = json.loads(self._mm[8 : 8 + header_len].decode("utf-8"))
        self.metadata = header.pop("__metadata__", {})
        self._entries = header
        self._data_start = 8 + header_len

    def names(self) -> list[str]:
        return list(self._entries)

    def tensor(self, name: str) -> np.ndarray:
        """Zero-copy read-only view (copy before mutating)."""
        try:
            meta = self._entries[name]
        except KeyError:
            raise KeyError(f"{self.path} has no tensor {name!r}") from None
        begin, end = meta["data_offsets"]
        dt = _dtype(meta["dtype"])
        buf = memoryview(self._mm)[self._data_start + begin : self._data_start + end]
        return np.frombuffer(buf, dtype=dt).reshape(meta["shape"])

    def close(self) -> None:
        try:
            self._mm.close()
        except BufferError:
            pass  # tensor views still alive; the map unlinks when they die


class Checkpoint:
    """A checkpoint = one file, or an HF directory with either a single
    ``model.safetensors`` or sharded files + ``model.safetensors.index.json``
    (weight_map: tensor name -> shard file)."""

    def __init__(self, path: str):
        self._files: dict[str, SafetensorsFile] = {}
        self._index: dict[str, str] = {}  # tensor name -> file path
        if os.path.isfile(path):
            self._add(path)
        elif os.path.isdir(path):
            index = os.path.join(path, "model.safetensors.index.json")
            if os.path.exists(index):
                with open(index) as f:
                    weight_map = json.load(f)["weight_map"]
                for name, fname in weight_map.items():
                    self._index[name] = os.path.join(path, fname)
            else:
                shards = sorted(
                    os.path.join(path, n) for n in os.listdir(path)
                    if n.endswith(".safetensors")
                )
                if not shards:
                    raise FileNotFoundError(f"no .safetensors files under {path}")
                for shard in shards:
                    self._add(shard)
        else:
            raise FileNotFoundError(path)

    def _add(self, path: str) -> SafetensorsFile:
        sf = self._files.get(path)
        if sf is None:
            sf = self._files[path] = SafetensorsFile(path)
            for name in sf.names():
                self._index.setdefault(name, path)
        return sf

    def names(self) -> list[str]:
        return list(self._index)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def tensor(self, name: str) -> np.ndarray:
        try:
            path = self._index[name]
        except KeyError:
            raise KeyError(f"checkpoint has no tensor {name!r}") from None
        return self._add(path).tensor(name)

    def close(self) -> None:
        for sf in self._files.values():
            sf.close()


def is_safetensors_path(path: Optional[str]) -> bool:
    """MODEL_PATH routing: .safetensors file, or a directory containing
    safetensors shards/index (otherwise treated as an orbax dir)."""
    if not path:
        return False
    if path.endswith(".safetensors"):
        return True
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "model.safetensors.index.json")):
            return True
        return any(n.endswith(".safetensors") for n in os.listdir(path))
    return False


# -- HF Llama mapping ---------------------------------------------------------

# our per-layer name -> (HF suffix, transpose). HF nn.Linear stores [out, in];
# our forwards compute x @ w with w [in, out].
_LAYER_MAP = {
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
    "attn_norm": ("input_layernorm.weight", False),
    "mlp_norm": ("post_attention_layernorm.weight", False),
}

_QUANT_LAYER_KEYS = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def _expect_shape(name: str, arr: np.ndarray, shape: tuple[int, ...]) -> None:
    if tuple(arr.shape) != shape:
        raise ValueError(
            f"checkpoint tensor {name!r} has shape {tuple(arr.shape)}, "
            f"model config expects {shape}"
        )


def iter_hf_llama_tensors(
    ckpt: Checkpoint, cfg: Any
) -> Iterator[tuple[tuple[str, ...], np.ndarray]]:
    """Yield ((tree path), array-in-our-layout) for every param the
    transformer tree needs, shape-checked against ``cfg``. Missing tensors
    raise KeyError naming the HF tensor."""
    d, f, v = cfg.dim, cfg.hidden_dim, cfg.vocab_size
    kv = cfg.n_kv_heads * cfg.head_dim
    embed = ckpt.tensor("model.embed_tokens.weight")
    _expect_shape("model.embed_tokens.weight", embed, (v, d))
    yield ("embed",), embed
    norm = ckpt.tensor("model.norm.weight")
    _expect_shape("model.norm.weight", norm, (d,))
    yield ("norm_f",), norm
    if "lm_head.weight" in ckpt:
        head = ckpt.tensor("lm_head.weight")
    else:  # tied embeddings (Llama-3.2-1B style)
        head = embed
    _expect_shape("lm_head.weight", head, (v, d))
    yield ("lm_head",), head.T
    shapes = {
        "wq": (d, d), "wk": (d, kv), "wv": (d, kv), "wo": (d, d),
        "w_gate": (d, f), "w_up": (d, f), "w_down": (f, d),
        "attn_norm": (d,), "mlp_norm": (d,),
    }
    for i in range(cfg.n_layers):
        for ours, (suffix, transpose) in _LAYER_MAP.items():
            name = f"model.layers.{i}.{suffix}"
            arr = ckpt.tensor(name)
            if transpose:
                arr = arr.T
            _expect_shape(name, arr, shapes[ours])
            yield ("layers", ours, i), arr


def load_llama_params(
    path: str, cfg: Any, quantize: Any = False
) -> dict:
    """Build the serving param tree (models/transformer.py layout: stacked
    [n_layers, ...] layer weights) from an HF Llama safetensors checkpoint.

    Per-layer tensors are collected as zero-copy mmap views and stacked
    HOST-side — one numpy memcpy and one host->device transfer per weight
    key (an eager per-layer ``.at[i].set`` would copy the whole device
    stack n_layers times). Peak device memory beyond the final tree is one
    stacked bf16 key while it quantizes; peak extra host memory is one
    stacked key (the views themselves are mmap-backed)."""
    import jax.numpy as jnp

    from gofr_tpu.models.quant import quantizer_for

    quantize_fn = quantizer_for(quantize)
    ckpt = Checkpoint(path)
    try:
        params: dict[str, Any] = {"layers": {}}

        def place(arr: np.ndarray, quant_ok: bool, key: str = "") -> Any:
            from gofr_tpu.models.quant import quantizer_for_key

            x = jnp.asarray(np.ascontiguousarray(arr), dtype=cfg.dtype)
            if not (quantize_fn and quant_ok):
                return x
            # key-aware: encodes the w8a8 lm_head carve-out centrally
            return quantizer_for_key(quantize, key)(x)

        pending: dict[str, list[np.ndarray]] = {}
        for tree_path, arr in iter_hf_llama_tensors(ckpt, cfg):
            if tree_path[0] != "layers":
                quant_ok = tree_path[0] == "lm_head"  # embeds/norms stay hi-prec
                params[tree_path[0]] = place(arr, quant_ok, tree_path[0])
                continue
            _, key, _i = tree_path  # yielded in layer order 0..n-1
            pending.setdefault(key, []).append(arr)
        for key in list(pending):
            stacked = np.stack(pending.pop(key))
            # quantize_array on [L, in, out] reduces axis=-2: bit-identical
            # to quantizing each layer slice separately
            params["layers"][key] = place(stacked, key in _QUANT_LAYER_KEYS, key)
            del stacked
        return params
    finally:
        ckpt.close()


def export_llama_hf(params: dict, cfg: Any) -> dict[str, np.ndarray]:
    """Inverse mapping (our tree -> HF tensor dict), used by tests to
    round-trip and by users exporting trained weights. Quantized trees must
    be dequantized first."""
    from gofr_tpu.models.quant import (
        is_quantized,
        is_quantized_int4,
        is_quantized_w8a8,
    )

    def host(x: Any) -> np.ndarray:
        if is_quantized(x) or is_quantized_int4(x) or is_quantized_w8a8(x):
            raise ValueError("dequantize params before export")
        return np.asarray(x)

    out = {
        "model.embed_tokens.weight": host(params["embed"]),
        "model.norm.weight": host(params["norm_f"]),
        "lm_head.weight": host(params["lm_head"]).T,
    }
    for ours, (suffix, transpose) in _LAYER_MAP.items():
        stacked = host(params["layers"][ours])
        for i in range(cfg.n_layers):
            arr = stacked[i]
            out[f"model.layers.{i}.{suffix}"] = arr.T if transpose else arr
    return out
