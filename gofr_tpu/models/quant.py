"""Int8 weight-only quantization for serving (BASELINE target: Llama-3-8B
int8 on v5e-4).

Per-output-channel symmetric quantization: a weight ``w [..., in, out]``
becomes ``{"q": int8 [..., in, out], "scale": f32 [..., 1, out]}``. Matmuls
upcast int8 in registers (XLA fuses the convert into the MXU feed);
HBM traffic — the serving bottleneck — drops 2x vs bf16. Embeddings and
norms stay high precision.

This module is the single source of truth for the scheme: ``quantize_array``
/ ``dequantize_array`` / ``mm`` are what the model forwards use
(gofr_tpu.models.transformer._mm and bert both route through ``mm``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# weight names eligible for int8 (2-D matmul weights used via mm())
_QUANT_KEYS = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head", "wqkv", "w_in", "w_out"}

_CLIP = 127.0
_SCALE_FLOOR = 1e-8


def quantize_array(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Quantize along the reduction axis (second-to-last): works for plain
    [in, out] weights and stacked [n_layers, in, out] weights alike."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / _CLIP, _SCALE_FLOOR)
    w_q = jnp.clip(jnp.round(wf / scale), -_CLIP, _CLIP).astype(jnp.int8)
    return {"q": w_q, "scale": scale.astype(jnp.float32)}


def dequantize_array(packed: dict[str, jnp.ndarray], dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    return (packed["q"].astype(jnp.float32) * packed["scale"]).astype(dtype)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "scale"}


def mm(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Quant-aware matmul: ``w`` is a plain [in, out] array or a packed int8
    dict. Accumulation in f32 either way (preferred_element_type feeds the
    MXU correctly on TPU).

    The int8 operand goes into ``dot_general`` DIRECTLY — an explicit
    ``astype`` before the matmul makes XLA materialize the dequantized
    bf16 weight in HBM (3x the traffic, measured ~1.9x slower per decode
    matvec on v5e), while the mixed-dtype dot fuses the upconvert into the
    MXU feed so only int8 bytes ever cross HBM. Numerics are identical:
    int8 values are exactly representable in bf16/f32."""
    if is_quantized(w):
        y = jax.lax.dot_general(
            x, w["q"], (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * w["scale"].reshape(1, -1)).astype(x.dtype)
    return x @ w


def quantize_params(params: dict) -> dict:
    """Quantize all eligible weights in a model param tree (stacked layer
    weights quantized per layer-slice by the axis=-2 convention)."""

    def walk(tree: Any) -> Any:
        if isinstance(tree, dict):
            out = {}
            for key, value in tree.items():
                if key in _QUANT_KEYS and isinstance(value, jnp.ndarray) and value.ndim >= 2:
                    out[key] = quantize_array(value)
                else:
                    out[key] = walk(value)
            return out
        return tree

    return walk(params)


def dequantize_params(params: dict, dtype: Any = jnp.bfloat16) -> dict:
    def walk(tree: Any) -> Any:
        if is_quantized(tree):
            return dequantize_array(tree, dtype)
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(params)


def quantization_error(w: jnp.ndarray) -> float:
    """Relative RMS error of quantize->dequantize (diagnostics)."""
    back = dequantize_array(quantize_array(w), jnp.float32)
    wf = w.astype(jnp.float32)
    return float(jnp.sqrt(jnp.mean((wf - back) ** 2)) / (jnp.sqrt(jnp.mean(wf**2)) + 1e-12))
