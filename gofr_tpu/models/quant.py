"""Weight-only quantization for serving (BASELINE target: Llama-3-8B
int8 on v5e-4; ``MODEL_QUANT=int4`` halves HBM weight traffic again).

Two schemes, both symmetric:

- **int8, per output channel**: ``w [..., in, out]`` becomes
  ``{"q": int8 [..., in, out], "scale": f32 [..., 1, out]}``. Matmuls
  upcast int8 in registers (XLA fuses the convert into the MXU feed);
  HBM traffic — the serving bottleneck — drops 2x vs bf16.
- **int4, group-wise** (group = 128 input rows per scale): ``w`` becomes
  ``{"q4": int4 [..., in, out], "scale": f32 [..., in/128, out]}``.
  Per-group scales recover most of the accuracy a 4-bit grid loses at
  per-channel granularity (~0.25 extra bits/weight of scale overhead);
  decode is weight-streaming-bound, so 4-bit weights raise its
  throughput ceiling ~2x over int8.

Embeddings and norms stay high precision in both schemes.

This module is the single source of truth: ``quantize_array`` /
``quantize_array_int4`` / ``mm`` are what the model forwards use
(gofr_tpu.models.transformer._mm and bert both route through ``mm``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# weight names eligible for quantization (2-D matmul weights used via mm())
_QUANT_KEYS = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
               "wqkv", "w_in", "w_out"}


def moe_skip_keys(tree: dict) -> frozenset:
    """Keys a param-tree walker must leave dense inside a MoE block: expert
    stacks (the dict also holds the router) compute their FFN via batched
    einsum over the expert axis, not mm(), so a packed/LoRA dict there
    would be untraceable. Shared by quantize_params and lora.add_lora so
    the skip set cannot drift between walkers."""
    return (
        frozenset(("w_gate", "w_up", "w_down")) if "router" in tree else frozenset()
    )

_CLIP = 127.0
_CLIP4 = 7.0
_SCALE_FLOOR = 1e-8

INT4_GROUP = 128  # input rows per int4 scale group


def quantize_array(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Quantize along the reduction axis (second-to-last): works for plain
    [in, out] weights and stacked [n_layers, in, out] weights alike."""
    wf = w.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2, keepdims=True) / _CLIP, _SCALE_FLOOR)
    w_q = jnp.clip(jnp.round(wf / scale), -_CLIP, _CLIP).astype(jnp.int8)
    return {"q": w_q, "scale": scale.astype(jnp.float32)}


def dequantize_array(packed: dict[str, jnp.ndarray], dtype: Any = jnp.bfloat16) -> jnp.ndarray:
    return (packed["q"].astype(jnp.float32) * packed["scale"]).astype(dtype)


def quantize_array_int4(
    w: jnp.ndarray, group: int = INT4_GROUP
) -> dict[str, jnp.ndarray]:
    """Group-wise symmetric int4: ``group`` input rows share one scale per
    output channel. The group clamps to the reduction dim for small
    (test-sized) weights; the dim must divide by the effective group
    (true for every transformer dim this framework ships)."""
    wf = w.astype(jnp.float32)
    i, o = wf.shape[-2], wf.shape[-1]
    group = min(group, i)
    if i % group:
        raise ValueError(
            f"int4 quantization needs the reduction dim ({i}) divisible by "
            f"the scale group ({group})"
        )
    lead = wf.shape[:-2]
    wg = wf.reshape(*lead, i // group, group, o)
    scale = jnp.maximum(
        jnp.max(jnp.abs(wg), axis=-2, keepdims=True) / _CLIP4, _SCALE_FLOOR
    )  # [..., n_groups, 1, out]
    q4 = (
        jnp.clip(jnp.round(wg / scale), -_CLIP4, _CLIP4)
        .astype(jnp.int4)
        .reshape(*lead, i, o)
    )
    return {"q4": q4, "scale": scale[..., 0, :].astype(jnp.float32)}


def dequantize_array_int4(
    packed: dict[str, jnp.ndarray], dtype: Any = jnp.bfloat16
) -> jnp.ndarray:
    q4, scale = packed["q4"], packed["scale"]
    i, o = q4.shape[-2], q4.shape[-1]
    lead = q4.shape[:-2]
    n = scale.shape[-2]
    wg = q4.astype(jnp.float32).reshape(*lead, n, i // n, o)
    return (wg * scale[..., :, None, :]).reshape(*lead, i, o).astype(dtype)


def quantize_array_w8a8(w: jnp.ndarray) -> dict[str, jnp.ndarray]:
    """Same per-channel int8 packing as ``quantize_array`` but under the
    ``q8`` key: the marker that ``mm`` should ALSO dynamically quantize
    the activations and run the int8 x int8 MXU path (2x the bf16 peak on
    v5e/v5p). Weight numerics are identical to weight-only int8; the
    difference is entirely in how ``mm`` consumes the pack.

    SERVING mode: the activation round-to-int8 has zero gradient, so a
    backward pass through a w8a8 matmul passes no gradient to earlier
    layers — train (incl. QLoRA) over int8/int4 bases and re-quantize
    for deployment instead."""
    packed = quantize_array(w)
    return {"q8": packed["q"], "scale": packed["scale"]}


def dequantize_array_w8a8(
    packed: dict[str, jnp.ndarray], dtype: Any = jnp.bfloat16
) -> jnp.ndarray:
    return (packed["q8"].astype(jnp.float32) * packed["scale"]).astype(dtype)


def quantize_act_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic per-token symmetric int8: each row (token) gets one absmax
    scale over the feature axis. Returns (q [..., d] int8, scale [..., 1]
    f32). Cheap on TPU (one reduction + elementwise, fused by XLA into
    the surrounding graph) and accurate enough that W8A8 logits stay
    within bf16 noise of the weight-only path on RMS-normed inputs."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / _CLIP, _SCALE_FLOOR
    )
    q = jnp.clip(jnp.round(xf / scale), -_CLIP, _CLIP).astype(jnp.int8)
    return q, scale


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "scale"}


def is_quantized_int4(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q4", "scale"}


def is_quantized_w8a8(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q8", "scale"}


def mm(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Quant-aware matmul: ``w`` is a plain [in, out] array or a packed
    int8/int4 dict. Accumulation in f32 either way (preferred_element_type
    feeds the MXU correctly on TPU).

    The quantized operand goes into ``dot_general`` DIRECTLY — an explicit
    ``astype`` before the matmul makes XLA materialize the dequantized
    bf16 weight in HBM (3x the traffic, measured ~1.9x slower per decode
    matvec on v5e), while the mixed-dtype dot fuses the upconvert into the
    MXU feed so only the packed bytes ever cross HBM. Numerics are
    identical: int8/int4 values are exactly representable in bf16/f32.

    int4 runs one dot per scale group (the group axis becomes a batched
    matmul dim); the per-group scale multiplies the f32 partials before
    the group sum."""
    if isinstance(w, dict) and "lora_a" in w:
        from gofr_tpu.models.lora import lora_mm

        return lora_mm(x, w, mm)
    if isinstance(w, dict) and "lora_stack_a" in w:
        # pooled multi-LoRA leaf: per-batch-row adapter selection from a
        # stacked bank (decode_chunk_pool_lora attaches the row ids)
        from gofr_tpu.models.lora import plora_mm

        return plora_mm(x, w, mm)
    if is_quantized(w):
        y = jax.lax.dot_general(
            x, w["q"], (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * w["scale"].reshape(1, -1)).astype(x.dtype)
    if is_quantized_int4(w):
        q4, scale = w["q4"], w["scale"]
        i, o = q4.shape
        n = scale.shape[-2]
        xg = x.reshape(*x.shape[:-1], n, i // n)
        qg = q4.reshape(n, i // n, o)
        y = jnp.einsum(
            "...ag,ago->...ao", xg, qg, preferred_element_type=jnp.float32
        )
        return jnp.sum(y * scale, axis=-2).astype(x.dtype)
    if is_quantized_w8a8(w):
        # W8A8: dynamic per-token activation quant feeds an int8 x int8
        # dot with int32 accumulation — on v5e/v5p the MXU's int8 path
        # runs at 2x the bf16 FLOP rate, so a compute-bound prefill
        # halves. The two scales (per-token activation, per-channel
        # weight) rescale the int32 result; XLA fuses the quantize
        # reduction + elementwise into the surrounding graph.
        qx, sx = quantize_act_rows(x)
        y = jax.lax.dot_general(
            qx, w["q8"], (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return (
            y.astype(jnp.float32) * sx * w["scale"].reshape(1, -1)
        ).astype(x.dtype)
    return x @ w


def quantizer_for(mode: Any) -> Any:
    """Map a MODEL_QUANT value to the per-array quantizer. Accepts the
    legacy bool (True = int8), "int8", "int4", and ""/None/False (no
    quantization -> None). Unknown strings raise at config time."""
    if mode in ("int8", True):
        return quantize_array
    if mode == "int4":
        return quantize_array_int4
    if mode == "w8a8":
        return quantize_array_w8a8
    if mode in ("", None, False):
        return None
    raise ValueError(
        f"MODEL_QUANT '{mode}' not supported — use int8, int4, or w8a8"
    )


def quantizer_for_key(mode: Any, key: str) -> Any:
    """Key-aware quantizer — THE single home of the w8a8 lm_head
    carve-out: under w8a8 the logits matmul stays weight-only int8 so
    per-token activation noise cannot flip an argmax. Every walker that
    quantizes a named param tree (quantize_params, checkpoint loaders,
    model inits) must resolve its quantizer through this, or the
    carve-out silently evaporates for that entry point."""
    fn = quantizer_for(mode)
    if fn is None:
        return None
    if mode == "w8a8" and key == "lm_head":
        return quantize_array
    return fn


def quantize_params(params: dict, mode: Any = "int8") -> dict:
    """Quantize all eligible weights in a model param tree (stacked layer
    weights quantized per layer-slice by the axis=-2 convention)."""
    quantize = quantizer_for(mode)
    if quantize is None:
        return params

    def walk(tree: Any) -> Any:
        if isinstance(tree, dict):
            skip = moe_skip_keys(tree)
            out = {}
            for key, value in tree.items():
                if (
                    key in _QUANT_KEYS
                    and key not in skip
                    and isinstance(value, jnp.ndarray)
                    and value.ndim >= 2
                ):
                    out[key] = quantizer_for_key(mode, key)(value)
                else:
                    out[key] = walk(value)
            return out
        return tree

    return walk(params)


def dequantize_params(params: dict, dtype: Any = jnp.bfloat16) -> dict:
    def walk(tree: Any) -> Any:
        if is_quantized(tree):
            return dequantize_array(tree, dtype)
        if is_quantized_int4(tree):
            return dequantize_array_int4(tree, dtype)
        if is_quantized_w8a8(tree):
            return dequantize_array_w8a8(tree, dtype)
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(params)


def quantization_error(w: jnp.ndarray) -> float:
    """Relative RMS error of quantize->dequantize (diagnostics)."""
    back = dequantize_array(quantize_array(w), jnp.float32)
    wf = w.astype(jnp.float32)
    return float(jnp.sqrt(jnp.mean((wf - back) ** 2)) / (jnp.sqrt(jnp.mean(wf**2)) + 1e-12))
