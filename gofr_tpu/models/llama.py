"""Named Llama-family configurations (BASELINE.json configs 3-4) plus tiny
test/dev shapes."""

from __future__ import annotations

import jax.numpy as jnp

from gofr_tpu.models.transformer import TransformerConfig

# Llama-3-8B (serving target: int8 on v5e-4, p50 TTFT < 200ms)
LLAMA3_8B = TransformerConfig(
    vocab_size=128256,
    dim=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    hidden_dim=14336,
    max_seq=8192,
    rope_theta=500000.0,
)

# Llama-3-70B (DP-sharded decode on v5e-16)
LLAMA3_70B = TransformerConfig(
    vocab_size=128256,
    dim=8192,
    n_layers=80,
    n_heads=64,
    n_kv_heads=8,
    hidden_dim=28672,
    max_seq=8192,
    rope_theta=500000.0,
)

# Tiny config: fast CPU tests and the virtual-mesh dryrun
TINY = TransformerConfig(
    vocab_size=256,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    hidden_dim=128,
    max_seq=128,
    rope_theta=10000.0,
    dtype=jnp.float32,
    attn_impl="xla",
)

# Small-but-realistic single-chip bench model (fits v5e-1 in bf16 and
# exercises the same kernels/shapes class as 8B)
SMALL = TransformerConfig(
    vocab_size=32000,
    dim=1024,
    n_layers=8,
    n_heads=8,
    n_kv_heads=4,
    hidden_dim=4096,
    max_seq=2048,
    rope_theta=500000.0,
)

CONFIGS: dict[str, TransformerConfig] = {
    "tiny": TINY,
    "small": SMALL,
    "llama3-8b": LLAMA3_8B,
    "llama3-70b": LLAMA3_70B,
}
