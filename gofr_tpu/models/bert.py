"""BERT-style bidirectional encoder for embedding serving (BASELINE.json
configs[2]: unary RPC serving BERT-base embeddings).

Pre-LN encoder blocks with learned position embeddings, GELU FFN, mean-pool
over valid tokens -> L2-normalized sentence embedding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.models.quant import mm as _mm
from gofr_tpu.ops.attention import attention
from gofr_tpu.ops.norms import layer_norm


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def init_bert(key: jax.Array, cfg: BertConfig) -> dict:
    keys = iter(jax.random.split(key, cfg.n_layers * 6 + 3))

    def dense(k: jax.Array, shape: tuple[int, ...], fan_in: int) -> jnp.ndarray:
        return (jax.random.truncated_normal(k, -3, 3, shape) * (fan_in ** -0.5)).astype(cfg.dtype)

    params: dict[str, Any] = {
        "tok_embed": dense(next(keys), (cfg.vocab_size, cfg.dim), cfg.dim),
        "pos_embed": dense(next(keys), (cfg.max_seq, cfg.dim), cfg.dim),
        "norm_f_w": jnp.ones((cfg.dim,), cfg.dtype),
        "norm_f_b": jnp.zeros((cfg.dim,), cfg.dtype),
    }
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm_w": jnp.ones((cfg.dim,), cfg.dtype),
                "attn_norm_b": jnp.zeros((cfg.dim,), cfg.dtype),
                "wqkv": dense(next(keys), (cfg.dim, 3 * cfg.dim), cfg.dim),
                "wo": dense(next(keys), (cfg.dim, cfg.dim), cfg.dim),
                "mlp_norm_w": jnp.ones((cfg.dim,), cfg.dtype),
                "mlp_norm_b": jnp.zeros((cfg.dim,), cfg.dtype),
                "w_in": dense(next(keys), (cfg.dim, cfg.hidden_dim), cfg.dim),
                "b_in": jnp.zeros((cfg.hidden_dim,), cfg.dtype),
                "w_out": dense(next(keys), (cfg.hidden_dim, cfg.dim), cfg.hidden_dim),
                "b_out": jnp.zeros((cfg.dim,), cfg.dtype),
            }
        )
    params["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return params


def bert_embed(
    params: dict, tokens: jnp.ndarray, attn_mask: jnp.ndarray, cfg: BertConfig
) -> jnp.ndarray:
    """``tokens`` [B, S] ids, ``attn_mask`` [B, S] 1=valid. Returns
    L2-normalized [B, dim] float32 embeddings."""
    b, s = tokens.shape
    x = params["tok_embed"][tokens] + params["pos_embed"][:s][None]
    key_mask = attn_mask.astype(bool)

    def body(carry, p):
        h = layer_norm(carry, p["attn_norm_w"], p["attn_norm_b"], cfg.norm_eps)
        qkv = _mm(h, p["wqkv"]).reshape(b, s, 3, cfg.n_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = attention(q, k, v, causal=False, mask=key_mask, impl=cfg.attn_impl)
        carry = carry + _mm(attn.reshape(b, s, cfg.dim), p["wo"])
        h = layer_norm(carry, p["mlp_norm_w"], p["mlp_norm_b"], cfg.norm_eps)
        h = _mm(jax.nn.gelu(_mm(h, p["w_in"]) + p["b_in"]), p["w_out"]) + p["b_out"]
        return carry + h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["norm_f_w"], params["norm_f_b"], cfg.norm_eps)
    # masked mean pool in f32
    xf = x.astype(jnp.float32)
    weights = attn_mask.astype(jnp.float32)[..., None]
    pooled = (xf * weights).sum(axis=1) / jnp.maximum(weights.sum(axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)
