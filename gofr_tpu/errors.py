"""Framework error types with HTTP status mapping.

Parity: /root/reference/pkg/gofr/http/responder.go:43-57 — the responder
derives the HTTP status from the error value a handler returns. In Python the
handler *raises*; any exception carrying ``status_code`` maps to that status,
everything else is a 500 (matching the reference default).
"""

from __future__ import annotations


class GofrError(Exception):
    """Base error; subclasses set ``status_code``."""

    status_code: int = 500

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message or self.__class__.__name__

    def __str__(self) -> str:  # envelope message text
        return self.message


class InvalidParamError(GofrError):
    """Bad/missing request parameter -> 400."""

    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        n = len(self.params)
        noun = "parameter" if n == 1 else "parameters"
        super().__init__(f"'{n}' invalid {noun} {', '.join(self.params)}")


class MissingParamError(GofrError):
    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        n = len(self.params)
        noun = "parameter" if n == 1 else "parameters"
        super().__init__(f"'{n}' missing {noun} {', '.join(self.params)}")


class EntityNotFoundError(GofrError):
    """Row/key not found -> 404."""

    status_code = 404

    def __init__(self, name: str = "entity", value: str = ""):
        super().__init__(f"No '{name}' found for value '{value}'")


class RouteNotFoundError(GofrError):
    status_code = 404

    def __init__(self) -> None:
        super().__init__("route not registered")


class UnauthenticatedError(GofrError):
    status_code = 401

    def __init__(self, message: str = "authentication required"):
        super().__init__(message)


class ForbiddenError(GofrError):
    status_code = 403

    def __init__(self, message: str = "forbidden"):
        super().__init__(message)


class RequestTimeoutError(GofrError):
    status_code = 408

    def __init__(self, message: str = "request timed out"):
        super().__init__(message)


class TooManyRequestsError(GofrError):
    """Batch queue overflow / admission control -> 429 (TPU-native addition:
    the batching layer sheds load instead of growing the queue unboundedly)."""

    status_code = 429

    def __init__(self, message: str = "server overloaded"):
        super().__init__(message)


class DeadlineExceeded(GofrError):
    """The request's end-to-end deadline expired before (or while) it
    could be served -> 504 (TPU-native addition: deadline-aware serving
    sheds hopeless work at the queue/admission/decode stages instead of
    burning device time on an answer nobody is waiting for).
    ``stage`` records WHERE the budget ran out (queue | admission |
    decode) — the same label the
    ``gofr_tpu_deadline_exceeded_total{stage}`` counter carries."""

    status_code = 504

    def __init__(self, message: str = "request deadline exceeded",
                 stage: str = ""):
        super().__init__(message)
        self.stage = stage


class HTTPError(GofrError):
    """Arbitrary status escape hatch."""

    def __init__(self, status_code: int, message: str):
        self.status_code = status_code
        super().__init__(message)


def status_from_error(err: BaseException | None) -> int:
    """Parity: http/responder.go:43-57 — unknown errors are 500."""
    if err is None:
        return 200
    code = getattr(err, "status_code", None)
    if isinstance(code, int) and 100 <= code <= 599:
        return code
    return 500
