"""CMD transport: CLI apps sharing the transport-agnostic handler signature.

Parity: /root/reference/pkg/gofr/cmd.go:12-70 (non-flag args joined into the
command string :33-41, regex route matching :54-63, "No Command Found!" on
stderr :46-49), cmd/request.go:14-114 (flag parsing ``-a`` / ``--a=b``
:36-60, reflection Bind into str/bool/int fields :87-114), and
cmd/responder.go:8-19 (stdout for results, stderr for errors).
"""

from __future__ import annotations

import json
import re
import sys
from typing import Any, Optional

from gofr_tpu.context import Context
from gofr_tpu.tracing import get_tracer


class CMDRequest:
    """Argv-backed request façade (parity: cmd/request.go:14-114)."""

    def __init__(self, args: Optional[list[str]] = None):
        self.args = list(sys.argv[1:] if args is None else args)
        self.flags: dict[str, str] = {}
        self._parse_flags()

    def _parse_flags(self) -> None:
        # parity: cmd/request.go:36-60 — `-a` / `-a=b` / `--a=b`; bare flags
        # get value "true"
        for arg in self.args:
            if not arg.startswith("-"):
                continue
            name = arg.lstrip("-")
            if not name:
                continue
            if "=" in name:
                key, _, value = name.partition("=")
                if key:
                    self.flags[key] = value
            else:
                self.flags[name] = "true"

    # -- Request interface ---------------------------------------------------
    def param(self, key: str) -> str:
        return self.flags.get(key, "")

    def params(self, key: str) -> list[str]:
        value = self.param(key)
        return [value] if value else []

    def path_param(self, key: str) -> str:
        return self.param(key)

    def bind(self, into: Any = None) -> Any:
        """Reflection-style bind of flags into an object's declared fields
        (parity: cmd/request.go:87-114 — string/bool/int conversions)."""
        if into is None:
            return dict(self.flags)
        obj = into() if isinstance(into, type) else into
        hints = getattr(obj, "__annotations__", {}) or {
            k: type(v) for k, v in vars(obj).items()
        }
        for key, value in self.flags.items():
            if key not in hints:
                continue
            kind = hints[key]
            if kind is bool:
                setattr(obj, key, value.lower() in ("true", "1", "yes", ""))
            elif kind is int:
                try:
                    setattr(obj, key, int(value))
                except ValueError:
                    pass
            elif kind is float:
                try:
                    setattr(obj, key, float(value))
                except ValueError:
                    pass
            else:
                setattr(obj, key, value)
        return obj

    def header(self, name: str) -> str:
        return ""

    def host_name(self) -> str:
        return "cli"


class CMDResponder:
    """stdout/stderr responder (parity: cmd/responder.go:8-19)."""

    def respond(self, result: Any, error: Optional[BaseException]) -> None:
        if error is not None:
            print(str(error), file=sys.stderr)
            return
        if result is None:
            return
        if isinstance(result, str):
            print(result)
        else:
            print(json.dumps(result, default=str))


def command_string(args: list[str]) -> str:
    """Join non-flag args (parity: cmd.go:28-41)."""
    return " ".join(a for a in args if not a.startswith("-"))


def run_cmd(app: Any, args: Optional[list[str]] = None) -> int:
    """Match the command against registered sub-command patterns and run the
    handler (parity: cmd.go:27-63). Returns a process exit code."""
    argv = list(sys.argv[1:] if args is None else args)
    command = command_string(argv)
    responder = CMDResponder()
    for pattern, handler in app._cmd_routes:
        if pattern == command:
            matched = True
        else:
            try:
                matched = re.fullmatch(pattern, command) is not None
            except re.error:  # pattern is a plain literal, not a regex
                matched = False
        if matched:
            request = CMDRequest(argv)
            ctx = Context(request, app.container)
            with get_tracer().start_span(f"cmd {command or pattern}"):
                try:
                    result, error = handler(ctx), None
                except Exception as exc:
                    result, error = None, exc
            responder.respond(result, error)
            return 0 if error is None else 1
    print("No Command Found!", file=sys.stderr)  # parity: cmd.go:46-49
    return 1
