"""Postmortem black box: on-disk flight-data bundles for dead processes.

PR 1 and PR 3 made the LIVE process explainable (`/admin/requests`,
`/admin/engine`, `/admin/dispatches`) — but every one of those surfaces
dies with the process, and three bench rounds in a row (r03–r05) ended
in device wedges whose evidence evaporated exactly that way. This
module is the flight recorder's crash-survivable twin: when the engine
wedges, the process crashes, or an operator asks, the ENTIRE
observability state is serialized into one atomic
``postmortem-<ts>.json`` bundle under ``POSTMORTEM_DIR`` — readable
after SIGKILL, harvestable by ``bench.py``/``tools/tunnel_watch.py``
into the round's ``hw/rNN/`` evidence directory, pretty-printed by
``tools/postmortem_view.py``.

Bundle contents (schema ``gofr-postmortem/1``):

- ``reason``/``detail``/``ts`` — what triggered the write;
- ``versions`` — gofr_tpu, python, jax (when loaded), platform;
- ``config`` — fingerprint of every framework config key in the
  environment, secrets redacted, plus a stable hash;
- ``engine`` — the full ``/admin/engine`` snapshot (state history, boot
  timeline, watchdog with the STALLING dispatch ids, caches, HBM);
- ``dispatches`` — the whole dispatch timeline ring (a wedged dispatch
  shows ``status: "running"``);
- ``requests`` / ``requests_in_flight`` — the flight-record ring with
  its slow/errored side buffer merged, plus the records still in
  flight (the ones riding the wedge never reach the ring);
- ``timebase`` — the last N metric snapshots (``POSTMORTEM_SNAPSHOTS``,
  default 60 ≈ 5 min at the default interval): the lead-up, not just
  the end state;
- ``threads`` — every thread's current stack (the data that turns "it
  hung" into "it hung HERE").

Triggers:

- **watchdog wedge / boot failure** — an ``EngineState`` listener fires
  on the ``wedged``/``failed`` transitions and writes from a detached
  thread (never from under the watchdog's lock);
- **unhandled crash** — ``sys.excepthook``/``threading.excepthook``
  chain-wrapped (armed only when ``POSTMORTEM_DIR`` is explicitly
  configured: an operator opt-in, so test processes don't sprout
  bundle directories);
- **fatal signal** — ``faulthandler`` into
  ``POSTMORTEM_DIR/fatal-signals.log`` (same opt-in): SIGSEGV/SIGABRT
  leave at least raw thread stacks behind;
- **operator** — ``POST /admin/postmortem`` writes one on demand.

Automatic triggers are rate-limited (``POSTMORTEM_MIN_INTERVAL_S``,
default 30) so a flapping engine cannot fill a disk; retention keeps
the newest ``POSTMORTEM_KEEP`` bundles (default 20).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Optional

from gofr_tpu.config import environ_snapshot
from gofr_tpu.version import __version__

SCHEMA = "gofr-postmortem/1"

# config keys worth carrying in the fingerprint: every framework prefix
# (the bundle must reproduce the serving shape, not the whole shell env)
CONFIG_PREFIXES = (
    "ADMIN_", "ANOMALY_", "APP_", "BATCH_", "BENCH_", "COMPILE_",
    "COSTMODEL_", "DECODE_",
    "DISPATCH_", "ECHO_", "FLIGHT_", "GEN_", "GRPC_", "HANDLER_", "HTTP_",
    "LOG_", "METRICS_", "MODEL_", "POSTMORTEM_", "PREFILL_", "PREFIX_",
    "SCHED_", "SLO", "SPEC_", "TENANT_", "TIMEBASE_", "TOKENIZER", "TPU_",
    "TRACER_", "WATCHDOG_",
)
# suffixes marking a value as secret: redacted, never written (suffix,
# not substring — GEN_STOP_TOKENS is model config, ADMIN_TOKEN is not)
SECRET_SUFFIXES = ("TOKEN", "SECRET", "PASSWORD", "PASSWD", "KEY", "CREDENTIAL")

_hooks_lock = threading.Lock()
_hooks_installed = False
# the store the process-global crash hooks write through; latest wins
# (containers come and go in tests, hooks are forever)
_active_store: Optional["PostmortemStore"] = None


class PostmortemStore:
    """Assembles, writes, lists, and prunes postmortem bundles.

    ``container`` is the DI container — every source (telemetry,
    timebase, tpu engine/timeline/watchdog) is read through it AT WRITE
    TIME, so a store constructed before the TPU wires still captures
    it, and a source that is missing (bare test container) simply
    yields null fields."""

    def __init__(
        self,
        container: Any,
        directory: str = "./postmortems",
        keep: int = 20,
        min_interval_s: float = 30.0,
        snapshots: int = 60,
        logger: Any = None,
    ):
        self.container = container
        # anchor NOW: bundles must land relative to where the app was
        # constructed, not wherever the process has chdir'd to by the
        # time a wedge (much later) triggers the write
        self.directory = os.path.abspath(directory)
        self.keep = max(1, keep)
        self.min_interval_s = float(min_interval_s)
        self.snapshots = max(1, snapshots)
        self.logger = logger
        self._lock = threading.Lock()
        # None = no automatic bundle written yet. NOT 0.0: monotonic
        # time starts near zero at HOST boot (Linux), so a zero anchor
        # silently rate-limited every automatic bundle for the
        # machine's first min_interval_s of uptime — exactly the
        # early-boot wedges whose evidence matters most
        self._last_auto: Optional[float] = None

    # -- triggers -------------------------------------------------------------
    def watch_engine(self, engine: Any) -> None:
        """Subscribe to the engine state machine: the ``wedged`` and
        ``failed`` transitions each write a bundle from a detached
        thread (the transition may run under the watchdog's lock, and a
        bundle write — stack formatting, JSON, fsync — must never sit
        in that critical section)."""

        def on_transition(state: str, detail: str) -> None:
            if state not in ("wedged", "failed"):
                return
            threading.Thread(
                target=self.write,
                kwargs={"reason": state, "detail": detail},
                name="gofr-postmortem",
                daemon=True,
            ).start()

        engine.add_listener(on_transition)

    def install_crash_hooks(self) -> None:
        """Chain-wrap ``sys.excepthook`` and ``threading.excepthook`` to
        write a bundle on any unhandled exception before the previous
        hook runs, and arm ``faulthandler`` so fatal signals dump every
        thread's stack into ``fatal-signals.log``. Installed once per
        process; the newest store wins the write."""
        global _hooks_installed, _active_store
        with _hooks_lock:
            _active_store = self
            if _hooks_installed:
                return
            _hooks_installed = True
            prev_sys = sys.excepthook
            prev_threading = threading.excepthook

            def sys_hook(exc_type, exc, tb):
                store = _active_store
                if store is not None:
                    store.write(
                        reason="crash",
                        detail=f"{exc_type.__name__}: {exc}",
                        force=True,
                    )
                prev_sys(exc_type, exc, tb)

            def threading_hook(args):
                store = _active_store
                if store is not None and args.exc_type is not SystemExit:
                    store.write(
                        reason="thread-crash",
                        detail=(
                            f"{args.exc_type.__name__}: {args.exc_value} "
                            f"(thread {getattr(args.thread, 'name', '?')})"
                        ),
                    )
                prev_threading(args)

            sys.excepthook = sys_hook
            threading.excepthook = threading_hook
        try:
            import faulthandler

            os.makedirs(self.directory, exist_ok=True)
            # the file object must outlive this frame: faulthandler
            # keeps the fd, the attribute keeps the object alive
            self._fault_file = open(  # noqa: SIM115 - lifetime is the process
                os.path.join(self.directory, "fatal-signals.log"), "a"
            )
            faulthandler.enable(file=self._fault_file, all_threads=True)
        except Exception as exc:
            self._log_error("faulthandler arm failed: %r", exc)

    def detach(self) -> None:
        """Stop being the crash-hook target (container close)."""
        global _active_store
        with _hooks_lock:
            if _active_store is self:
                _active_store = None

    # -- write side -----------------------------------------------------------
    def write(
        self, reason: str, detail: str = "", force: bool = False
    ) -> Optional[str]:
        """Assemble and atomically write one bundle; returns its path.
        Automatic triggers (``force=False``) are rate-limited to one per
        ``min_interval_s`` — a flapping engine must not fill the disk.
        Forced (operator) writes neither consult nor consume that
        budget, and a FAILED write refunds it: a manual drill or an
        assembly error must never suppress the next wedge's bundle —
        that bundle is the whole point. Never raises: a postmortem
        failing is itself logged, nothing more (the process is usually
        already in trouble here)."""
        now = time.monotonic()
        consumed = False
        prev: Optional[float] = None
        if not force:
            with self._lock:
                if (
                    self._last_auto is not None
                    and now - self._last_auto < self.min_interval_s
                ):
                    return None
                prev = self._last_auto
                self._last_auto = now
                consumed = True
        try:
            bundle = self.bundle(reason, detail)
            path = self._write_atomic(bundle)
            self._prune()
            if self.logger is not None:
                self.logger.warnf(
                    "postmortem bundle written: %s (reason=%s)", path, reason
                )
            return path
        except Exception as exc:
            if consumed:
                with self._lock:
                    if self._last_auto == now:  # nobody else stamped since
                        self._last_auto = prev
            self._log_error("postmortem write failed: %r", exc)
            return None

    def bundle(self, reason: str, detail: str = "") -> dict[str, Any]:
        """Assemble the bundle dict. Host-side reads only — safe (and
        most useful) while the engine is wedged."""
        c = self.container
        out: dict[str, Any] = {
            "schema": SCHEMA,
            "reason": reason,
            "detail": detail,
            # gofrlint: wall-clock — bundle ts (filename + correlation)
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "versions": runtime_versions(),
            "config": _config_fingerprint(),
            "threads": _thread_stacks(),
        }
        telemetry = getattr(c, "telemetry", None)
        if telemetry is not None:
            out["requests"] = telemetry.records(limit=telemetry.capacity)
            out["requests_in_flight"] = telemetry.active_records()
        slo = getattr(c, "slo", None)
        if slo is not None:
            # the error-budget ledger at death: "were we already burning
            # before this happened" — a fresh evaluation, not a cache,
            # plus the latched alert evidence it carries
            try:
                out["slo_budget"] = slo.budget()
            except Exception as exc:
                out["slo_budget"] = {"error": repr(exc)}
        tenants = getattr(c, "tenants", None)
        if tenants is not None:
            # who was on the box: top-K tenants by token volume (hashed
            # ids only — the sketch never holds raw keys)
            out["tenants"] = tenants.snapshot(k=50)
        timebase = getattr(c, "timebase", None)
        if timebase is not None:
            from gofr_tpu.timebase import jsonable_snapshots

            out["timebase"] = jsonable_snapshots(
                timebase.snapshots(last=self.snapshots)
            )
        tpu = getattr(c, "tpu", None)
        if tpu is not None:
            try:
                out["engine"] = tpu.engine_snapshot()
            except Exception as exc:
                out["engine"] = {"error": repr(exc)}
            timeline = getattr(tpu, "timeline", None)
            if timeline is not None:
                out["dispatches"] = timeline.records(limit=1_000_000)
            costmodel = getattr(tpu, "costmodel", None)
            if costmodel is not None:
                # the residual watchtower's state at death: calibration,
                # sheets, per-family residual EMAs, and the full anomaly
                # ring — "was the engine already blowing its predictions
                # before it wedged" is the first postmortem question
                try:
                    out["costmodel"] = costmodel.snapshot()
                    out["anomalies"] = costmodel.ring.events(
                        limit=costmodel.ring.capacity
                    )
                except Exception as exc:
                    out["costmodel"] = {"error": repr(exc)}
        return out

    def _write_atomic(self, bundle: dict[str, Any]) -> str:
        os.makedirs(self.directory, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime(bundle["ts"]))
        ms = int((bundle["ts"] % 1) * 1000)
        name = f"postmortem-{ts}.{ms:03d}.json"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(bundle, fh, indent=1, default=str)
            fh.flush()
            # fsync BEFORE the rename: the whole point is surviving a
            # SIGKILL moments later, so the data must hit the platter
            # before the name does
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path

    def _prune(self) -> None:
        bundles = self.list()
        for entry in bundles[: -self.keep]:
            try:
                os.unlink(os.path.join(self.directory, entry["file"]))
            except OSError:
                pass

    # -- read side ------------------------------------------------------------
    def list(self) -> list[dict[str, Any]]:
        """Bundle inventory, oldest first: file, size, mtime."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith("postmortem-") and n.endswith(".json")
            )
        except OSError:
            return []
        out = []
        for name in names:
            try:
                st = os.stat(os.path.join(self.directory, name))
            except OSError:
                continue
            out.append({"file": name, "bytes": st.st_size, "mtime": st.st_mtime})
        return out

    def _log_error(self, fmt: str, *args: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.errorf(fmt, *args)
                return
            except Exception:
                # gofrlint: disable=GFL006 — crash-path reporter: the
                # logger itself failed, fall through to stderr
                pass
        try:
            print("[postmortem] " + (fmt % args), file=sys.stderr)
        except Exception:
            # gofrlint: disable=GFL006 — last-resort reporter on the
            # crash path; nothing left to report to
            pass


def runtime_versions() -> dict[str, Any]:
    """The one versions dict — shared by bundles and the device's
    ``engine_snapshot`` so the two can never drift."""
    out: dict[str, Any] = {
        "gofr_tpu": __version__,
        "python": sys.version.split()[0],
    }
    # sys.modules, never an import: an echo/no-device process must not
    # pay the jax import because it crashed
    jax = sys.modules.get("jax")
    if jax is not None:
        out["jax"] = getattr(jax, "__version__", "?")
    try:
        import platform

        out["platform"] = platform.platform()
    except Exception:
        # gofrlint: disable=GFL006 — crash-path version probe: a
        # failure must not block the bundle
        pass
    return out


def _config_fingerprint() -> dict[str, Any]:
    """Framework config keys present in the environment, secrets
    redacted, plus a stable hash of the redacted view — enough to say
    "these two wedges ran the same config" without leaking credentials."""
    environ = environ_snapshot()
    keys: dict[str, str] = {}
    for key in sorted(environ):
        if not key.startswith(CONFIG_PREFIXES):
            continue
        if key.upper().endswith(SECRET_SUFFIXES):
            keys[key] = "<redacted>"
        else:
            keys[key] = environ[key]
    digest = hashlib.sha256(
        "\n".join(f"{k}={v}" for k, v in keys.items()).encode()
    ).hexdigest()[:16]
    return {"keys": keys, "fingerprint": digest}


def _thread_stacks() -> list[dict[str, Any]]:
    """Every live thread's current stack. The wedged dispatch's thread
    is in here — the line that says WHICH call never returned."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        thread = by_ident.get(ident)
        out.append(
            {
                "name": thread.name if thread else f"<ident {ident}>",
                "ident": ident,
                "daemon": thread.daemon if thread else None,
                "stack": "".join(traceback.format_stack(frame)),
            }
        )
    out.sort(key=lambda t: t["name"])
    return out
