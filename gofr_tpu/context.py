"""Per-request Context handed to every handler — the DI access point.

Parity: /root/reference/pkg/gofr/context.go:12-70 — embeds the request and
the container (:13-26), ``Trace()`` span helper (:45-50), ``Bind`` (:52).
TPU-native addition: ``ctx.tpu`` exposes the TPU datasource for enqueueing
batched forward passes (SURVEY.md §2 #14).

The same Context type serves HTTP, gRPC, and CMD transports — the keystone
transport-agnostic design (request.go:10-16, responder.go:5-7).
"""

from __future__ import annotations

from typing import Any, Optional

from gofr_tpu.tracing import Span, current_trace_id, get_tracer


class Context:
    def __init__(self, request: Any, container: Any):
        self.request = request
        self.container = container

    # -- request passthrough (parity: context.go embedding) -----------------
    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, into: Any = None) -> Any:
        return self.request.bind(into)

    def header(self, name: str) -> str:
        header = getattr(self.request, "header", None)
        return header(name) if header else ""

    def host_name(self) -> str:
        return self.request.host_name()

    # -- container accessors -------------------------------------------------
    @property
    def logger(self) -> Any:
        return self.container.logger

    @property
    def config(self) -> Any:
        return self.container.config

    @property
    def redis(self) -> Any:
        return self.container.redis

    @property
    def db(self) -> Any:
        return self.container.db

    @property
    def tpu(self) -> Any:
        """The TPU inference datasource (TPU-native addition)."""
        return self.container.tpu

    @property
    def metrics(self) -> Any:
        return self.container.metrics

    @property
    def telemetry(self) -> Any:
        """The request flight recorder (TPU-native addition)."""
        return self.container.telemetry

    def get_http_service(self, name: str) -> Any:
        """Parity: container/container.go:93."""
        return self.container.get_http_service(name)

    # -- tracing -------------------------------------------------------------
    def trace(self, name: str) -> Span:
        """User span helper (parity: context.go:45-50); use as a context
        manager: ``with ctx.trace("work"): ...``"""
        return get_tracer().start_span(name)

    @property
    def trace_id(self) -> Optional[str]:
        return current_trace_id()
