"""Developer-facing correctness tooling (never imported by the serving
path): the runtime concurrency sanitizer lives here, the static half is
``tools/gofrlint.py``. See docs/advanced-guide/static-analysis.md."""
