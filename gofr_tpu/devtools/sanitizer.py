"""Runtime concurrency sanitizer: lock-order + hold-time + thread-leak
checking for the threaded engine, active only under ``GOFR_SANITIZE=1``.

The reference pipeline never runs ``go test -race`` (its CI gap); this
build's native boundary has TSAN, but the far larger *Python* engine —
batcher, decode pool, scheduler, watchdog, timebase sampler, postmortem
writer — had nothing, and PRs 1-4 each shipped at least one
hand-found latent concurrency fix. This module turns that by-hand
auditing into a machine check the tier-1 suite can run:

- **Lock-order graph / potential-deadlock detection.** ``install()``
  rebinds ``threading.Lock``/``threading.RLock`` to factories that
  return :class:`SanitizedLock` wrappers. Every wrapper acquisition
  while other wrappers are held records ``held -> acquired`` edges in a
  process-global graph; an edge that closes a cycle is a POTENTIAL
  DEADLOCK (two code paths take the same locks in opposite orders —
  whether it hangs today is only a scheduling accident) and is recorded
  with both acquisition stacks. Reentrant acquisitions never add edges.
- **Hold-time tracking.** A wrapper held longer than
  ``GOFR_SANITIZE_HOLD_MS`` (default 150) records a warning with the
  acquisition site — the static half of this rule is gofrlint GFL004
  (no blocking calls under a lock); this is the dynamic half.
- **Thread-leak detection.** :func:`leaked_threads` diffs live threads
  against a pre-test snapshot and reports alive non-daemon leftovers,
  minus the allowlisted long-lived singletons. The conftest fixture
  fails the test that leaked.

Scope: edges are recorded only between locks CREATED by project code
(``gofr_tpu/`` + ``tests/``) — lock ordering inside jax/stdlib is not
ours to gate, and false positives there would teach people to ignore
the sanitizer. Set ``GOFR_SANITIZE_ALL=1`` to widen to every lock.

The wrappers stay Condition-compatible: ``threading.Condition`` built
on a sanitized lock delegates ``_release_save``/``_acquire_restore``/
``_is_owned`` through the wrapper (tracking stays consistent across
``wait()``'s release/reacquire).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Optional

from gofr_tpu.config import env_flag, get_env

# the sanitizer's own mutual exclusion uses the RAW primitive so its
# bookkeeping never recurses into itself
import _thread

_state_lock = _thread.allocate_lock()
_tls = threading.local()

_installed = False
_orig_lock: Any = None
_orig_rlock: Any = None
_node_seq = 0

# node -> {node -> edge dict}; nodes are unique per wrapper instance
# (a monotonically increasing id — never reused, so a gc'd lock's edges
# can never alias a new lock)
_edges: dict[int, dict[int, dict[str, Any]]] = {}
_violations: list[dict[str, Any]] = []
_hold_warnings: list[dict[str, Any]] = []
_MAX_RECORDS = 200

# long-lived singletons the thread-leak check must tolerate (they are
# daemons, but the allowlist also covers a future non-daemon variant
# and documents intent)
THREAD_ALLOWLIST_PREFIXES = (
    "gofr-timebase", "gofr-decode-pool", "gofr-watchdog",
    "pytest_timeout",
)


def enabled() -> bool:
    """True when the suite runs under ``GOFR_SANITIZE=1``."""
    return env_flag("GOFR_SANITIZE")


def hold_threshold_s() -> float:
    try:
        return float(get_env("GOFR_SANITIZE_HOLD_MS", "150")) / 1000.0
    except ValueError:
        return 0.150


def _project_scoped() -> bool:
    return not env_flag("GOFR_SANITIZE_ALL")


_SELF_FILE = __file__


def _site(depth: int, limit: int = 12) -> list[str]:
    """Cheap stack capture (no linecache reads): outermost-last frames
    above ``depth``, this module's own frames skipped."""
    out: list[str] = []
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return out
    while frame is not None and len(out) < limit:
        code = frame.f_code
        if code.co_filename != _SELF_FILE:
            out.append(f"{code.co_filename}:{frame.f_lineno} in {code.co_name}")
        frame = frame.f_back
    return out


def _in_project(path: str) -> bool:
    return "gofr_tpu" in path or "tests" in path.replace("\\", "/").split("/")


class _Held:
    __slots__ = ("node", "count", "t_acquired", "stack", "lock")

    def __init__(self, node: int, stack: list[str], lock: "SanitizedLock"):
        self.node = node
        self.count = 1
        self.t_acquired = time.monotonic()
        self.stack = stack
        self.lock = lock


def _held_list() -> list[_Held]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _path_exists(start: int, goal: int) -> bool:
    """DFS over the edge graph (caller holds ``_state_lock``)."""
    seen = {start}
    stack = [start]
    while stack:
        node = stack.pop()
        if node == goal:
            return True
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _note_acquire(lock: "SanitizedLock") -> None:
    held = _held_list()
    for entry in held:
        if entry.node == lock._node:
            entry.count += 1  # reentrant: no edges, no fresh hold clock
            return
    stack = _site(3)
    entry = _Held(lock._node, stack, lock)
    if held:  # edge recording only matters under nested acquisition —
        # never serialize the (overwhelmingly common) un-nested case
        # through the global graph lock
        scoped = _project_scoped()
        with _state_lock:
            for holder in held:
                if scoped and not (
                    holder.lock._project and lock._project
                ):
                    continue
                _add_edge_locked(holder, entry)
    held.append(entry)


def _add_edge_locked(holder: _Held, entry: _Held) -> None:
    out = _edges.setdefault(holder.node, {})
    if entry.node in out:
        return
    out[entry.node] = {
        "from": holder.lock._label,
        "to": entry.lock._label,
        "held_stack": holder.stack,
        "acquire_stack": entry.stack,
        "thread": threading.current_thread().name,
    }
    # does acquiring `entry` while holding `holder` close a cycle — is
    # there already a path entry -> ... -> holder from another site?
    if _path_exists(entry.node, holder.node) and \
            len(_violations) < _MAX_RECORDS:
        reverse = _edges.get(entry.node, {}).get(holder.node)
        _violations.append({
            "kind": "lock-order-cycle",
            "summary": (
                f"potential deadlock: {holder.lock._label} -> "
                f"{entry.lock._label} here, but an opposite-order path "
                "already exists"
            ),
            "this_edge": out[entry.node],
            "reverse_edge": reverse,  # None when the path is indirect
            "thread": threading.current_thread().name,
        })


def _note_release(lock: "SanitizedLock", full: bool = False) -> None:
    held = _held_list()
    for i, entry in enumerate(held):
        if entry.node == lock._node:
            entry.count = 0 if full else entry.count - 1
            if entry.count <= 0:
                held.pop(i)
                dt = time.monotonic() - entry.t_acquired
                if dt >= hold_threshold_s() and \
                        len(_hold_warnings) < _MAX_RECORDS:
                    with _state_lock:
                        _hold_warnings.append({
                            "kind": "long-hold",
                            "lock": lock._label,
                            "seconds": round(dt, 4),
                            "stack": entry.stack,
                            "thread": threading.current_thread().name,
                        })
            return


class SanitizedLock:
    """Instrumented wrapper over a primitive lock. Deliberately does
    NOT define the RLock protocol (``_release_save`` & co.):
    ``threading.Condition`` probes for it with getattr and must fall
    back to its generic acquire/release path for plain locks."""

    def __init__(self, inner: Any, label: str, project: bool):
        global _node_seq
        self._inner = inner
        with _state_lock:
            _node_seq += 1
            self._node = _node_seq
        self._label = label
        self._project = project

    # -- lock protocol --------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    acquire_lock = acquire  # ancient alias some libraries still use

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    release_lock = release

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._label} node={self._node}>"

    def __getattr__(self, name: str) -> Any:
        # delegate what we don't wrap (e.g. _at_fork_reinit); missing
        # attrs raise AttributeError from the inner lock, which is what
        # Condition's protocol probing relies on
        inner = object.__getattribute__(self, "__dict__").get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)


class SanitizedRLock(SanitizedLock):
    """Reentrant variant: adds the RLock protocol so a Condition built
    on it keeps sanitizer bookkeeping consistent across ``wait()``'s
    full release/reacquire."""

    def _release_save(self):
        state = self._inner._release_save()
        _note_release(self, full=True)
        return state

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _note_acquire(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def sanitized_lock(label: Optional[str] = None) -> SanitizedLock:
    """A fresh instrumented plain lock (direct-construction seam for
    unit tests; ``install()`` is the fleet-wide path)."""
    return _make_lock(label, depth=2)


def sanitized_rlock(label: Optional[str] = None) -> SanitizedLock:
    return _make_rlock(label, depth=2)


def _creation_label(depth: int) -> tuple[str, bool]:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return "<unknown>", False
    while frame is not None and frame.f_code.co_filename == _SELF_FILE:
        frame = frame.f_back
    if frame is None:
        return "<unknown>", False
    path = frame.f_code.co_filename
    return f"{path}:{frame.f_lineno}", _in_project(path)


def _make_lock(label: Optional[str] = None, depth: int = 2) -> SanitizedLock:
    site, project = _creation_label(depth)
    return SanitizedLock(_thread.allocate_lock(), label or site, project)


def _make_rlock(label: Optional[str] = None, depth: int = 2) -> SanitizedRLock:
    site, project = _creation_label(depth)
    # the C RLock straight from _thread: never the (possibly patched)
    # threading.RLock factory
    return SanitizedRLock(_thread.RLock(), label or site, project)


# -- install / report ---------------------------------------------------------
def install() -> None:
    """Rebind ``threading.Lock``/``threading.RLock`` to the sanitizing
    factories. Idempotent; ``uninstall()`` restores the originals."""
    global _installed, _orig_lock, _orig_rlock
    if _installed:
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = _make_lock  # type: ignore[assignment]
    threading.RLock = _make_rlock  # type: ignore[assignment]
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _orig_lock  # type: ignore[assignment]
    threading.RLock = _orig_rlock  # type: ignore[assignment]
    _installed = False


def installed() -> bool:
    return _installed


def drain() -> dict[str, Any]:
    """The accumulated findings, cleared on read (per-test consumption:
    the conftest fixture fails the test that produced them). The edge
    graph itself persists — opposite-order acquisitions in two
    DIFFERENT tests of the same process are still a real finding."""
    with _state_lock:
        out = {
            "violations": list(_violations),
            "hold_warnings": list(_hold_warnings),
            "edges": sum(len(v) for v in _edges.values()),
        }
        _violations.clear()
        _hold_warnings.clear()
    return out


def reset() -> None:
    """Full reset (unit-test seam): findings AND the edge graph."""
    with _state_lock:
        _violations.clear()
        _hold_warnings.clear()
        _edges.clear()


def export_graph(path: Optional[str] = None) -> dict[str, Any]:
    """Snapshot of the OBSERVED lock-order graph, in the same schema as
    the static exporter (``tools/gofrlint.py --emit-lock-graph``) so
    ``tools/lockgraph_check.py`` can union the two: node ids are lock
    creation labels (``file:lineno``, absolute here — the checker
    normalizes paths), each edge is "``to`` was acquired while ``from``
    was held", ``site`` is the innermost acquiring frame. Deterministic:
    nodes and edges are sorted, and re-exporting an unchanged graph
    yields an identical document. Wired to ``GOFR_SANITIZE_GRAPH`` by
    tests/conftest.py and to ``--emit-graph`` by devtools/fleetsim.py."""
    nodes: set[str] = set()
    edges: dict[tuple[str, str], dict[str, Any]] = {}
    with _state_lock:
        for out in _edges.values():
            for info in out.values():
                a, b = info["from"], info["to"]
                nodes.add(a)
                nodes.add(b)
                site = info["acquire_stack"][0] if info["acquire_stack"] else ""
                edges.setdefault((a, b), {
                    "from": a, "to": b,
                    "site": site.split(" in ")[0],
                    "thread": info["thread"],
                })
    graph: dict[str, Any] = {
        "version": 1,
        "source": "runtime",
        "nodes": [{"id": n} for n in sorted(nodes)],
        "edges": [edges[k] for k in sorted(edges)],
    }
    if path:
        import json

        with open(path, "w", encoding="utf-8") as f:
            json.dump(graph, f, indent=2, sort_keys=True)
            f.write("\n")
    return graph


def is_allowlisted(thread: threading.Thread) -> bool:
    return any(
        thread.name.startswith(p) for p in THREAD_ALLOWLIST_PREFIXES
    )


def leaked_threads(
    before: "set[threading.Thread]", grace_s: float = 2.0
) -> list[threading.Thread]:
    """Alive non-daemon threads that appeared since ``before`` and are
    not allowlisted. Waits up to ``grace_s`` for stragglers (executor
    workers unwinding a ``shutdown(wait=False)``) before reporting."""
    deadline = time.monotonic() + grace_s
    while True:
        leaked = [
            t for t in threading.enumerate()
            if t not in before and t.is_alive() and not t.daemon
            and not is_allowlisted(t)
        ]
        if not leaked or time.monotonic() >= deadline:
            return leaked
        for t in leaked:
            t.join(timeout=0.05)
