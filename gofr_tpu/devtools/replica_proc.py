"""Subprocess-mode echo replica: ``python -m gofr_tpu.devtools.replica_proc``.

The in-process :class:`~gofr_tpu.devtools.chaos.ChaosReplica` can fake
every failure EXCEPT process death — a ``kill -9`` needs a real OS
process to kill. This entry boots the same serving surface
``chaos.build_replica`` wires (echo runner, OpenAI routes, the
``/generate`` token surface) in its own interpreter, configured purely
through the inherited environment (``HTTP_PORT``, ``MODEL_NAME=echo``,
``JOURNAL_DIR`` for WAL durability, ...), and blocks in ``app.run()``
until SIGTERM.

Spawned by :class:`~gofr_tpu.devtools.chaos.SubprocessReplica` (usually
under a :class:`~gofr_tpu.devtools.supervise.Supervisor`, so a SIGKILL
is followed by a respawn that rehydrates the journal WAL) and by the
fleetsim ``process_kill`` scenario.
"""

from __future__ import annotations


def main() -> None:
    import gofr_tpu
    from gofr_tpu.devtools.chaos import _generate_handler
    from gofr_tpu.openai_compat import register_openai_routes

    app = gofr_tpu.new()
    register_openai_routes(app)
    app.post("/generate", _generate_handler)
    app.run()  # blocks until SIGTERM, then drains + shuts down


if __name__ == "__main__":
    main()
